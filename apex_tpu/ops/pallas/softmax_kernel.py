"""Pallas TPU kernel for the megatron attention-score softmax family.

Reference: ``csrc/megatron/scaled_masked_softmax.h`` warp kernels (:106
unmasked, :211 arbitrary mask, scaled_upper_triang_masked_softmax.h:130
causal) and their backward chains (:106-207). Semantics preserved: scale
applied first, masked positions REPLACED with -10000.0, fully-masked rows
output zeros, math in fp32 regardless of IO dtype.

TPU design: one grid step owns a (block_rows, sk) row-complete tile resident
in VMEM, so the max / exp / sum / divide chain touches HBM exactly once per
element (read x, write y) — the XLA jnp lowering re-reads the input for each
reduction pass, which caps it at ~1/3 of HBM peak; this kernel removes those
extra passes. The backward needs only y and dy (masked positions have y == 0
so their dx is exactly 0 without consulting the mask — same trick as the
reference backward kernels, which also take no mask).

The mask is streamed block-wise with broadcast dims UNMATERIALIZED, matching
the reference's (b, 1, sq, sk) mask vs (b, h, sq, sk) scores convention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops.pallas._compat import CompilerParams as _CompilerParams
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas.tiling import softmax_block_rows
from apex_tpu.tune.api import pow2_bucket, tuned_params
from apex_tpu.utils.env import interpret_default
from apex_tpu.utils.tiling import round_up as _round_up

_f32 = jnp.float32
MASK_FILL = -10000.0
# largest row length the VMEM-resident tile supports (fp32 working set);
# beyond this the caller falls back to the XLA path (the "generic" variant
# has no length limit, like generic_scaled_masked_softmax.cpp:58-61)
MAX_PALLAS_COLS = 16384


def _pick_rows(skp: int, sq: int, itemsize: int = 4,
               has_mask: bool = False) -> int:
    """Row-block size from a per-grid-step VMEM budget covering EVERY
    streamed operand (in + out tiles double-buffered, mask tile, fp32
    temporaries) — shared heuristic (ops/pallas/tiling.py), also the
    autotuner's default candidate."""
    return softmax_block_rows(skp, sq, itemsize, has_mask)


def _block_rows(skp: int, sq: int, itemsize: int, has_mask: bool, dtype,
                interpret: bool, block_rows: int | None = None) -> int:
    """Row-block resolution: explicit arg > tuned cache entry > heuristic.
    Any 8-aligned block is grid-legal (sq pads up to a block multiple), so
    validation only checks alignment."""
    if block_rows is not None:
        return block_rows

    def ok(p):
        br = p["block_rows"]
        return isinstance(br, int) and br >= 8 and br % 8 == 0

    return tuned_params(
        "softmax",
        (("sk", skp), ("sq", pow2_bucket(sq)), ("mask", has_mask)),
        {"block_rows": _pick_rows(skp, sq, itemsize, has_mask)},
        dtype=dtype, interpret=interpret, validate=ok)["block_rows"]


def _softmax_rows_f32(x32):
    """Row softmax on a masked fp32 tile. Reciprocal-multiply (one divide
    per ROW, then a row-broadcast mul) instead of a per-element divide;
    fully-masked rows (max == fill) output zeros,
    scaled_masked_softmax.h:297. Shared by every forward kernel."""
    m = jnp.max(x32, axis=-1, keepdims=True)
    e = jnp.exp(x32 - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    return e * jnp.where(m <= MASK_FILL, 0.0, 1.0 / s)


def _sm_fwd_kernel(*refs, scale, causal, has_mask, sk_orig, br, skp):
    if has_mask:
        x_ref, m_ref, o_ref = refs
    else:
        x_ref, o_ref = refs
        m_ref = None
    qi = pl.program_id(1)
    x32 = x_ref[0].astype(_f32) * scale
    if has_mask:
        x32 = jnp.where(m_ref[0] != 0, MASK_FILL, x32)
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (br, skp), 0) + qi * br
        cols = jax.lax.broadcasted_iota(jnp.int32, (br, skp), 1)
        x32 = jnp.where(cols > rows, MASK_FILL, x32)
    if skp != sk_orig:
        cols = jax.lax.broadcasted_iota(jnp.int32, (br, skp), 1)
        x32 = jnp.where(cols >= sk_orig, MASK_FILL, x32)
    o_ref[0] = _softmax_rows_f32(x32).astype(o_ref.dtype)


def _sm_bwd_kernel(y_ref, dy_ref, dx_ref, *, scale):
    y32 = y_ref[0].astype(_f32)
    dy32 = dy_ref[0].astype(_f32)
    c = jnp.sum(dy32 * y32, axis=-1, keepdims=True)
    dx_ref[0] = ((dy32 - c) * y32 * scale).astype(dx_ref.dtype)


def _sm_causal_chunked_kernel(x_ref, o_ref, xbuf, *, scale, sk_orig, br, bc,
                              skp, nc):
    """Causal forward with column-chunked fetch: chunk j of row block qi is
    DMA'd from HBM only when it intersects the lower triangle (the index
    map aliases above-diagonal chunks to the last needed one, and Mosaic
    skips the copy when the block index repeats) — on causal scores ~25%
    of the input bytes never leave HBM. Chunks are staged into a
    row-complete VMEM buffer; the softmax itself runs once per row block
    at the last chunk."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    limit = ((qi + 1) * br - 1) // bc  # last chunk touching the triangle

    @pl.when(j <= limit)
    def _stage():
        xbuf[:, pl.ds(j * bc, bc)] = x_ref[0].astype(_f32)

    @pl.when(j == nc - 1)
    def _softmax():
        rows = jax.lax.broadcasted_iota(jnp.int32, (br, skp), 0) + qi * br
        cols = jax.lax.broadcasted_iota(jnp.int32, (br, skp), 1)
        # one mask covers the diagonal straddle, the never-staged region
        # (whose xbuf content is stale garbage — replaced, not arithmetic,
        # so NaN/Inf there cannot leak), and key padding
        keep = (cols <= rows) & (cols < sk_orig)
        x32 = jnp.where(keep, xbuf[...] * scale, MASK_FILL)
        o_ref[0] = _softmax_rows_f32(x32).astype(o_ref.dtype)


def _softmax_fwd_causal_chunked(x3, *, scale, interpret,
                                block_rows=None, chunk_cols=None):
    B, sq, sk = x3.shape
    skp = _round_up(sk, 128)
    # largest chunk that still gives >= 2 chunks; with one row block or one
    # chunk nothing can ever be skipped — signal the caller to use the
    # plain row-complete kernel instead of paying the staging overhead.
    # 0 encodes "no usable chunk" (cache values must be ints, not None).
    defaults = {
        "block_rows": _pick_rows(skp, sq, x3.dtype.itemsize, False),
        "chunk_cols": next((c for c in (512, 256, 128)
                            if skp % c == 0 and skp > c), 0),
    }

    def ok(p):
        br, bc = p["block_rows"], p["chunk_cols"]
        return (isinstance(br, int) and isinstance(bc, int)
                and br >= 8 and br % 8 == 0 and bc > 0 and bc % 128 == 0
                and skp % bc == 0 and skp > bc)

    if block_rows is None and chunk_cols is None:
        tuned = tuned_params(
            "softmax_causal_chunked",
            (("sk", skp), ("sq", pow2_bucket(sq))),
            defaults, dtype=x3.dtype, interpret=interpret, validate=ok)
        br, bc = tuned["block_rows"], tuned["chunk_cols"]
    else:
        br = block_rows if block_rows is not None else \
            defaults["block_rows"]
        bc = chunk_cols if chunk_cols is not None else \
            defaults["chunk_cols"]
    sqp = _round_up(sq, br)
    if not bc or sqp // br < 2:
        return None
    nc = skp // bc
    xp = jnp.pad(x3, ((0, 0), (0, sqp - sq), (0, skp - sk)))

    def x_idx(b, i, j):
        limit = ((i + 1) * br - 1) // bc
        return (b, i, jnp.minimum(j, limit))

    out = pl.pallas_call(
        functools.partial(_sm_causal_chunked_kernel, scale=scale,
                          sk_orig=sk, br=br, bc=bc, skp=skp, nc=nc),
        grid=(B, sqp // br, nc),
        in_specs=[pl.BlockSpec((1, br, bc), x_idx,
                               memory_space=pltpu.VMEM)],
        # the output block ignores j: written once (at the last chunk) and
        # flushed when the row-block index advances
        out_specs=pl.BlockSpec((1, br, skp), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, sqp, skp), x3.dtype),
        scratch_shapes=[pltpu.VMEM((br, skp), _f32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xp)
    return out[:, :sq, :sk]


def softmax_fwd_pallas(x3, mask3, *, scale, causal, h=1, interpret=None,
                       block_rows=None, chunk_cols=None):
    """x3: (B, sq, sk) scores (B = b·h). mask3: None or (Bm, sqm, sk) with
    Bm in {1, B//h·? } — concretely Bm in {1, B // h} (the reference's
    per-batch mask shared across heads) or B; sqm in {1, sq}. 1/True =
    masked. ``block_rows``/``chunk_cols`` override the tuned/heuristic
    tile geometry (the autotuner's probe path)."""
    if interpret is None:
        interpret = interpret_default()
    B, sq, sk = x3.shape
    skp = _round_up(sk, 128)
    if causal and mask3 is None and skp >= 256 and sq >= 16:
        # chunked fetch pays only when >= 2 column chunks AND >= 2 row
        # blocks exist (so upper-triangle chunks can actually be skipped);
        # the helper returns None for degenerate shapes
        out = _softmax_fwd_causal_chunked(x3, scale=scale,
                                          interpret=interpret,
                                          block_rows=block_rows,
                                          chunk_cols=chunk_cols)
        if out is not None:
            return out
    br = _block_rows(skp, sq, x3.dtype.itemsize, mask3 is not None,
                     x3.dtype, interpret, block_rows)
    sqp = _round_up(sq, br)
    xp = jnp.pad(x3, ((0, 0), (0, sqp - sq), (0, skp - sk)))
    grid = (B, sqp // br)

    in_specs = [pl.BlockSpec((1, br, skp), lambda b, i: (b, i, 0),
                             memory_space=pltpu.VMEM)]
    operands = [xp]
    has_mask = mask3 is not None
    if has_mask:
        Bm, sqm, _ = mask3.shape
        mp = jnp.pad(mask3.astype(jnp.int32),
                     ((0, 0), (0, sqp - sq if sqm != 1 else 0),
                      (0, skp - sk)))
        full_q = sqm != 1
        if Bm == 1:
            bidx = lambda b: 0  # noqa: E731
        elif Bm == B:
            bidx = lambda b: b  # noqa: E731
        else:  # per-batch mask shared across h heads
            assert Bm * h == B, (Bm, h, B)
            bidx = lambda b: b // h  # noqa: E731
        in_specs.append(pl.BlockSpec(
            (1, br if full_q else 1, skp),
            lambda b, i: (bidx(b), i if full_q else 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(mp)

    out = pl.pallas_call(
        functools.partial(_sm_fwd_kernel, scale=scale, causal=causal,
                          has_mask=has_mask, sk_orig=sk, br=br, skp=skp),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, br, skp), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, sqp, skp), x3.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*operands)
    return out[:, :sq, :sk]


def softmax_bwd_pallas(y3, dy3, *, scale, interpret=None, block_rows=None):
    """dx for any variant: masked positions have y == 0 ⇒ dx == 0, so no
    mask input is needed (matches the reference backward kernels)."""
    if interpret is None:
        interpret = interpret_default()
    B, sq, sk = y3.shape
    skp = _round_up(sk, 128)
    br = _block_rows(skp, sq, y3.dtype.itemsize, False, y3.dtype,
                     interpret, block_rows)
    sqp = _round_up(sq, br)
    # padded cols have y == 0 ⇒ contribute nothing to the row sum
    yp = jnp.pad(y3, ((0, 0), (0, sqp - sq), (0, skp - sk)))
    dyp = jnp.pad(dy3, ((0, 0), (0, sqp - sq), (0, skp - sk)))
    spec = pl.BlockSpec((1, br, skp), lambda b, i: (b, i, 0),
                        memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_sm_bwd_kernel, scale=scale),
        grid=(B, sqp // br),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((B, sqp, skp), y3.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(yp, dyp)
    return out[:, :sq, :sk]
