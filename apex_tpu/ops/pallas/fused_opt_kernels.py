"""Flat-buffer Pallas kernels for LAMB / NovoGrad / Adagrad (round 2).

TPU-native equivalents of the remaining ``amp_C`` multi-tensor optimizer
kernels, completing the flat family next to fused_adam_kernel / fused_sgd_kernel:

- LAMB: ``csrc/multi_tensor_lamb.cu`` — the two-phase scheme
  (``LAMBStage1Functor`` update-term computation, ``LAMBStage2Functor``
  trust-ratio weight update) with the per-tensor L2 norms of
  ``csrc/multi_tensor_l2norm_kernel.cu`` in between.
- NovoGrad: ``csrc/multi_tensor_novograd.cu`` (``NovoGradFunctor``) — per-tensor
  second-moment norm state.
- Adagrad: ``csrc/multi_tensor_adagrad.cu`` (``AdagradFunctor``).

Layout: one contiguous 128-lane-aligned flat buffer per role (see
apex_tpu.utils.flatten) viewed as (rows, 128). Because FlatSpec keeps every
tensor's offset and padded size lane-aligned, EACH ROW BELONGS TO EXACTLY ONE
TENSOR — per-tensor norms reduce to a segment-sum over per-row partials
(``row_ids``), the TPU answer to the CUDA chunked two-stage l2norm reduction.
Elementwise phases run as Pallas kernels over (block_rows, 128) tiles with
scalars in SMEM; the tiny (T,)-sized trust-ratio/normalization math runs as
plain XLA ops between them (it is nanoseconds of work and XLA fuses it).

The per-tensor math matches optimizers/functional.py leaf-for-leaf so the
flat and tree paths are bit-comparable (the flat-vs-tree parity tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas.fused_adam_kernel import (LANE, _as_rows,
                                                   _flat_block_rows)
from apex_tpu.utils.env import interpret_default
from apex_tpu.utils.flatten import FlatSpec

_f32 = jnp.float32


def row_segment_ids(spec: FlatSpec, total_size: int):
    """Static (rows,) int32 tensor-id per 128-lane row of the flat buffer.

    Rows in the tail padding get id ``num_leaves`` (an ignored segment).
    """
    import numpy as np

    rows = total_size // LANE
    ids = np.full((rows,), spec.num_leaves, np.int32)
    for t, (off, padded) in enumerate(zip(spec.offsets, spec.padded_sizes)):
        ids[off // LANE:(off + padded) // LANE] = t
    return jnp.asarray(ids)


def _per_tensor_sumsq(flat32, row_ids, num_tensors):
    """Per-tensor sum of squares via row partials + sorted segment-sum."""
    row_sums = jnp.sum(flat32.reshape(-1, LANE) ** 2, axis=1)
    return jax.ops.segment_sum(row_sums, row_ids,
                               num_segments=num_tensors + 1,
                               indices_are_sorted=True)[:-1]


def _dspec(br):
    return pl.BlockSpec((br, LANE), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _rowspec(br):
    return pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _sspec(ns):
    return pl.BlockSpec((1, ns), lambda i: (0, 0), memory_space=pltpu.SMEM)


# -------------------------------------------------------------------- LAMB


def _lamb_stage1_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref,
                        u_out, m_out, v_out, *, adam_w: bool):
    beta1 = scal_ref[0, 0]
    beta2 = scal_ref[0, 1]
    beta3 = scal_ref[0, 2]
    eps = scal_ref[0, 3]
    wd = scal_ref[0, 4]
    bc1 = scal_ref[0, 5]
    bc2 = scal_ref[0, 6]
    clip = scal_ref[0, 7]        # global-grad-norm clip divisor
    inv_scale = scal_ref[0, 8]
    noop = scal_ref[0, 9]

    p = p_ref[...].astype(_f32)
    g = g_ref[...].astype(_f32) * inv_scale / clip
    m = m_ref[...].astype(_f32)
    v = v_ref[...].astype(_f32)

    if not adam_w:
        g = g + wd * p
    m_new = beta1 * m + beta3 * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w:
        u = u + wd * p

    keep = noop != 0.0
    u_out[...] = jnp.where(keep, 0.0, u)
    m_out[...] = jnp.where(keep, m, m_new).astype(m_out.dtype)
    v_out[...] = jnp.where(keep, v, v_new).astype(v_out.dtype)


def _lamb_stage2_kernel(scal_ref, p_ref, u_ref, tr_ref, p_out):
    lr = scal_ref[0, 0]
    noop = scal_ref[0, 1]
    p = p_ref[...].astype(_f32)
    p_new = p - lr * tr_ref[...] * u_ref[...]
    p_out[...] = jnp.where(noop != 0.0, p, p_new).astype(p_out.dtype)


@functools.partial(jax.jit, static_argnames=(
    "num_tensors", "bias_correction", "grad_averaging", "use_nvlamb",
    "adam_w_mode", "max_grad_norm", "block_rows", "interpret"),
    donate_argnums=(0, 2, 3))
def fused_lamb_flat(p, g, m, v, row_ids, *, num_tensors: int, lr,
                    beta1: float = 0.9, beta2: float = 0.999,
                    eps: float = 1e-6, weight_decay: float = 0.01,
                    step=1, bias_correction: bool = True,
                    grad_averaging: bool = True,
                    max_grad_norm: float = 1.0, use_nvlamb: bool = False,
                    adam_w_mode: bool = True, inv_scale=1.0,
                    found_inf=False, block_rows: int | None = None,
                    interpret: bool | None = None):
    """Two-phase flat LAMB (multi_tensor_lamb.cu stage1/stage2 + l2norm).

    ``row_ids``: per-row tensor ids from ``row_segment_ids``. Returns
    ``(p, m, v, global_grad_norm)``.
    """
    if interpret is None:
        interpret = interpret_default()
    stepf = jnp.asarray(step, _f32)
    one = _f32(1.0)
    g32 = g.astype(_f32) * jnp.asarray(inv_scale, _f32)
    gnorm = jnp.sqrt(jnp.sum(g32 * g32))
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.maximum(gnorm / max_grad_norm, 1.0)
    else:
        clip = one
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = one - jnp.power(_f32(beta1), stepf)
        bc2 = one - jnp.power(_f32(beta2), stepf)
    else:
        bc1 = bc2 = one
    noop = jnp.asarray(found_inf, _f32)

    scal1 = jnp.stack([
        _f32(beta1), _f32(beta2), _f32(beta3), _f32(eps),
        jnp.asarray(weight_decay, _f32), bc1, bc2, clip,
        jnp.asarray(inv_scale, _f32), noop]).reshape(1, 10)

    p2, g2, m2, v2 = _as_rows(p), _as_rows(g), _as_rows(m), _as_rows(v)
    rows = p2.shape[0]
    br = _flat_block_rows("fused_lamb", rows, p2.dtype, interpret,
                          block_rows)
    grid = (pl.cdiv(rows, br),)

    u2, m_new, v_new = pl.pallas_call(
        functools.partial(_lamb_stage1_kernel, adam_w=adam_w_mode),
        grid=grid,
        in_specs=[_sspec(10), _dspec(br), _dspec(br), _dspec(br),
                  _dspec(br)],
        out_specs=[_dspec(br), _dspec(br), _dspec(br)],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, _f32),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype)],
        input_output_aliases={3: 1, 4: 2},
        interpret=interpret,
    )(scal1, p2, g2, m2, v2)

    # per-tensor trust ratios (LAMBStage2Functor + l2norm cleanup)
    w_sq = _per_tensor_sumsq(p2.astype(_f32), row_ids, num_tensors)
    u_sq = _per_tensor_sumsq(u2, row_ids, num_tensors)
    w_norm = jnp.sqrt(w_sq)
    u_norm = jnp.sqrt(u_sq)
    if use_nvlamb:
        ratios = jnp.where(u_norm > 0, w_norm / u_norm, 1.0)
    else:
        ratios = jnp.where((w_norm > 0) & (u_norm > 0),
                           w_norm / u_norm, 1.0)
    ratios = jnp.concatenate([ratios, jnp.ones((1,), _f32)])  # pad segment
    tr_rows = jnp.take(ratios, row_ids).reshape(rows, 1)

    scal2 = jnp.stack([jnp.asarray(lr, _f32), noop]).reshape(1, 2)
    p_new = pl.pallas_call(
        _lamb_stage2_kernel,
        grid=grid,
        in_specs=[_sspec(2), _dspec(br), _dspec(br), _rowspec(br)],
        out_specs=[_dspec(br)],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype)],
        input_output_aliases={1: 0},
        interpret=interpret,
    )(scal2, p2, u2, tr_rows)[0]

    return (p_new.reshape(p.shape), m_new.reshape(m.shape),
            v_new.reshape(v.shape), gnorm)


# ---------------------------------------------------------------- NovoGrad


def _novograd_kernel(scal_ref, p_ref, g_ref, m_ref, denom_ref,
                     p_out, m_out):
    lr = scal_ref[0, 0]
    beta1 = scal_ref[0, 1]
    beta3 = scal_ref[0, 2]
    wd = scal_ref[0, 3]
    bc1 = scal_ref[0, 4]
    inv_scale = scal_ref[0, 5]
    noop = scal_ref[0, 6]

    p = p_ref[...].astype(_f32)
    g = g_ref[...].astype(_f32) * inv_scale
    m = m_ref[...].astype(_f32)

    gg = g / denom_ref[...]          # (br, 1) per-tensor denom broadcast
    gg = gg + wd * p
    m_new = beta1 * m + beta3 * gg
    p_new = p - lr * (m_new / bc1)

    keep = noop != 0.0
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    m_out[...] = jnp.where(keep, m, m_new).astype(m_out.dtype)


@functools.partial(jax.jit, static_argnames=(
    "num_tensors", "bias_correction", "grad_averaging", "norm_type",
    "init_zero", "block_rows", "interpret"), donate_argnums=(0, 2))
def fused_novograd_flat(p, g, m, v_per_tensor, row_ids, *, num_tensors: int,
                        lr, beta1: float = 0.95, beta2: float = 0.98,
                        eps: float = 1e-8, weight_decay: float = 0.0,
                        step=1, grad_averaging: bool = False,
                        bias_correction: bool = False, norm_type: int = 2,
                        init_zero: bool = False, inv_scale=1.0,
                        found_inf=False, block_rows: int | None = None,
                        interpret: bool | None = None):
    """Flat NovoGrad (multi_tensor_novograd.cu): per-tensor 2nd-moment norm
    state ``v_per_tensor`` of shape (num_tensors,). Returns ``(p, m, v)``."""
    if norm_type != 2:
        raise NotImplementedError(
            "norm_type=0 (inf-norm) rides the tree path "
            "(optimizers/functional.py:novograd_update)")
    if interpret is None:
        interpret = interpret_default()
    stepf = jnp.asarray(step, _f32)
    one = _f32(1.0)
    first = stepf <= 1.0
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = one - jnp.power(_f32(beta1), stepf)
        bc2 = one - jnp.power(_f32(beta2), stepf)
    else:
        bc1 = bc2 = one
    noop = jnp.asarray(found_inf, _f32)

    g32 = g.astype(_f32) * jnp.asarray(inv_scale, _f32)
    gn_sq = _per_tensor_sumsq(g32, row_ids, num_tensors)
    v32 = v_per_tensor.astype(_f32)
    v_upd = beta2 * v32 + (1.0 - beta2) * gn_sq
    if init_zero:
        v_new = jnp.where(first, (1.0 - beta2) * gn_sq, v_upd)
    else:
        v_new = jnp.where(first, gn_sq, v_upd)
    denom_t = jnp.sqrt(v_new / bc2) + eps
    v_keep = jnp.where(noop != 0.0, v32, v_new).astype(v_per_tensor.dtype)

    denom_t = jnp.concatenate([denom_t, jnp.ones((1,), _f32)])
    rows = p.size // LANE
    denom_rows = jnp.take(denom_t, row_ids).reshape(rows, 1)

    scal = jnp.stack([
        jnp.asarray(lr, _f32), _f32(beta1), _f32(beta3),
        jnp.asarray(weight_decay, _f32), bc1,
        jnp.asarray(inv_scale, _f32), noop]).reshape(1, 7)

    p2, g2, m2 = _as_rows(p), _as_rows(g), _as_rows(m)
    br = _flat_block_rows("fused_novograd", rows, p2.dtype, interpret,
                          block_rows)
    grid = (pl.cdiv(rows, br),)

    p_new, m_new = pl.pallas_call(
        _novograd_kernel,
        grid=grid,
        in_specs=[_sspec(7), _dspec(br), _dspec(br), _dspec(br),
                  _rowspec(br)],
        out_specs=[_dspec(br), _dspec(br)],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype)],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret,
    )(scal, p2, g2, m2, denom_rows)

    return p_new.reshape(p.shape), m_new.reshape(m.shape), v_keep


# ----------------------------------------------------------------- Adagrad


def _adagrad_kernel(scal_ref, p_ref, g_ref, h_ref, p_out, h_out,
                    *, adagrad_w: bool):
    lr = scal_ref[0, 0]
    eps = scal_ref[0, 1]
    wd = scal_ref[0, 2]
    inv_scale = scal_ref[0, 3]
    noop = scal_ref[0, 4]

    p = p_ref[...].astype(_f32)
    g = g_ref[...].astype(_f32) * inv_scale
    h = h_ref[...].astype(_f32)

    if not adagrad_w:
        g = g + wd * p
    h_new = h + g * g
    upd = g / (jnp.sqrt(h_new) + eps)
    if adagrad_w:
        upd = upd + wd * p
    p_new = p - lr * upd

    keep = noop != 0.0
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    h_out[...] = jnp.where(keep, h, h_new).astype(h_out.dtype)


@functools.partial(jax.jit, static_argnames=("adagrad_w_mode", "block_rows",
                                             "interpret"),
                   donate_argnums=(0, 2))
def fused_adagrad_flat(p, g, h, *, lr, eps: float = 1e-10,
                       weight_decay: float = 0.0,
                       adagrad_w_mode: bool = False, inv_scale=1.0,
                       found_inf=False, block_rows: int | None = None,
                       interpret: bool | None = None):
    """Flat Adagrad (multi_tensor_adagrad.cu AdagradFunctor).
    Returns ``(p, h)``."""
    if interpret is None:
        interpret = interpret_default()
    scal = jnp.stack([
        jnp.asarray(lr, _f32), _f32(eps), jnp.asarray(weight_decay, _f32),
        jnp.asarray(inv_scale, _f32),
        jnp.asarray(found_inf, _f32)]).reshape(1, 5)
    p2, g2, h2 = _as_rows(p), _as_rows(g), _as_rows(h)
    rows = p2.shape[0]
    br = _flat_block_rows("fused_adagrad", rows, p2.dtype, interpret,
                          block_rows)
    grid = (pl.cdiv(rows, br),)

    p_new, h_new = pl.pallas_call(
        functools.partial(_adagrad_kernel, adagrad_w=adagrad_w_mode),
        grid=grid,
        in_specs=[_sspec(5), _dspec(br), _dspec(br), _dspec(br)],
        out_specs=[_dspec(br), _dspec(br)],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(h2.shape, h2.dtype)],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret,
    )(scal, p2, g2, h2)
    return p_new.reshape(p.shape), h_new.reshape(h.shape)
