"""Shared VMEM-budget / row-block heuristics for the Pallas kernel zoo.

Before this module, three kernels carried their own copy of the same
arithmetic — ``layer_norm_kernel._pick_block_rows``, ``softmax_kernel``'s
bytes-per-row budget math, and ``group_norm_kernel._pick_hw_block``. The
copies are now expressed through two primitives:

- :func:`fit_block_rows` — start from a candidate block and halve until it
  fits a row budget and (optionally) divides the row count. The
  layer-norm/group-norm family.
- :func:`clamp_block_rows` — clamp a raw budget-derived row count into
  ``[quantum, cap]`` on sublane granularity, optionally bounded by the
  (rounded) real row count. The softmax family.

The concrete per-kernel heuristics (:func:`norm_block_rows`,
:func:`softmax_block_rows`, :func:`groupnorm_hw_block`) live here too so
the kernels AND the autotuner's default candidate generator
(``apex_tpu.tune.registry``) share one source of truth: with an empty tune
cache every kernel reproduces exactly these choices (asserted in
tests/test_tune.py).
"""

from __future__ import annotations

from apex_tpu.utils.tiling import round_up

SUBLANE = 8
LANE = 128

# the default per-grid-step VMEM payload budget for a streamed fp32 operand
# block (the historical "keep ~4 operand blocks under a few MiB" rule)
NORM_VMEM_BUDGET = 2 * 1024 * 1024
# the softmax kernels budget for EVERY double-buffered operand at once and
# therefore get a larger envelope (fits v5e's ~16 MB VMEM worst case)
SOFTMAX_VMEM_BUDGET = 10 << 20


def pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    return 1 << (max(int(n), 1).bit_length() - 1)


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1) — the shape-bucketing quantum
    used by the autotune cache keys."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def vmem_row_budget(row_bytes: int, vmem_bytes: int = NORM_VMEM_BUDGET) -> int:
    """How many rows of ``row_bytes`` fit the per-block VMEM budget."""
    return vmem_bytes // max(row_bytes, 1)


def fit_block_rows(rows: int, budget_rows: int, *, start: int = 256,
                   min_rows: int = SUBLANE,
                   require_divisor: bool = True) -> int:
    """Halve ``start`` until it fits ``budget_rows`` and (optionally)
    divides ``rows``; never below ``min_rows``."""
    br = start
    while br > budget_rows and br > min_rows:
        br //= 2
    if require_divisor:
        while rows % br != 0 and br > min_rows:
            br //= 2
    return max(br, min_rows)


def clamp_block_rows(budget_rows: int, *, cap: int = 512,
                     quantum: int = SUBLANE,
                     rows_hint: int | None = None) -> int:
    """Clamp a budget-derived row count into ``[quantum, cap]`` on
    ``quantum`` granularity; ``rows_hint`` additionally bounds the result
    by the (quantum-rounded) real row count so short inputs are not padded
    to a full block."""
    br = max(quantum, min(cap, round_up(budget_rows, quantum)
                          if budget_rows >= quantum else quantum))
    if rows_hint is not None:
        br = min(br, round_up(rows_hint, quantum))
    return br


# ------------------------------------------------- per-kernel heuristics


def norm_block_rows(rows: int, hidden: int) -> int:
    """LayerNorm/RMSNorm row block: ~4 operand blocks under a few MiB of
    VMEM; ``rows`` is a multiple of 8 (layer_norm_kernel pads first)."""
    return fit_block_rows(rows, vmem_row_budget(hidden * 4), start=256)


def softmax_block_rows(skp: int, sq: int, itemsize: int = 4,
                       has_mask: bool = False) -> int:
    """Softmax row block from a per-grid-step VMEM budget covering EVERY
    streamed operand — in + out tiles (double-buffered by the pipeline)
    plus the int32 mask tile and the fp32 compute temporaries — so
    fp32+mask at the 16384-column cap still fits v5e's ~16 MB VMEM."""
    bytes_per_elt = 2 * (2 * itemsize + (4 if has_mask else 0)) + 8
    return clamp_block_rows(SOFTMAX_VMEM_BUDGET // (skp * bytes_per_elt),
                            rows_hint=sq)


def groupnorm_hw_block(hw: int, c: int) -> int:
    """GroupNorm two-pass HW tile: largest power of two fitting the fp32
    row budget, clamped to and dividing ``hw``."""
    budget = max(vmem_row_budget(c * 4), SUBLANE)
    blk = min(pow2_floor(budget), hw)
    return fit_block_rows(hw, blk, start=blk)


def decode_attention_block(max_len: int) -> int:
    """Serving decode-attention KV tile (apex_tpu.serve.attention): how
    many cached key/value rows each partial-softmax chunk covers. Wants to
    be large (fewer partial reductions) but bounded so a chunk of K plus V
    stays comfortably VMEM-resident next to the weights; must divide the
    static ``max_len``. The largest divisor of ``max_len`` that is
    <= 512 — i.e. 512 for the usual pow2 cache lengths; lengths with no
    such divisor above 1 (primes and odd lengths) get ONE chunk of
    ``max_len`` rather than a degenerate per-row unroll."""
    max_len = max(int(max_len), 1)
    for blk in range(min(max_len, 512), 1, -1):
        if max_len % blk == 0:
            return blk
    return max_len
