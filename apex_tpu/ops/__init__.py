"""Kernel layer: Pallas TPU kernels and XLA-fused ops (≈ csrc/ + contrib csrc)."""
