"""Dynamic loss scaling — TPU equivalent of the amp_C scaling family.

Kernels replaced (all jitted, no host sync — the "capturable" goal of the
reference's GradScaler integration, apex/optimizers/fused_adam.py:236-252):
- ``multi_tensor_scale`` (csrc/multi_tensor_scale_kernel.cu) → scale/unscale with
  found_inf detection
- ``update_scale_hysteresis`` (csrc/update_scale_hysteresis.cu:5-41) → growth /
  backoff state machine with hysteresis

State lives in a ``ScalerState`` pytree carried through the train step, so the
whole fp16 flow (scale loss → backward → unscale+check → conditional step →
scale update) stays inside one jit (SURVEY §7 hard part (f)).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor.functional import (multi_tensor_l2norm,
                                              multi_tensor_scale,
                                              multi_tensor_unscale_l2norm,
                                              tree_check_finite,
                                              update_scale_hysteresis)


class ScalerState(NamedTuple):
    scale: jax.Array            # f32 scalar
    growth_tracker: jax.Array   # i32 scalar
    hysteresis_tracker: jax.Array  # i32 scalar

    @classmethod
    def create(cls, init_scale: float = 2.0 ** 16, hysteresis: int = 1):
        return cls(jnp.float32(init_scale), jnp.int32(0),
                   jnp.int32(hysteresis))


def scale_loss(loss: jax.Array, state: ScalerState) -> jax.Array:
    """``with amp.scale_loss(loss, opt)`` equivalent: loss * scale."""
    return loss * state.scale.astype(loss.dtype)


class DynamicGradScaler:
    """Pure-functional dynamic scaler (configuration only; state is explicit).

    Hyperparameters mirror torch.amp.GradScaler + apex hysteresis.
    """

    def __init__(self, init_scale: float = 2.0 ** 16,
                 growth_factor: float = 2.0, backoff_factor: float = 0.5,
                 growth_interval: int = 2000, hysteresis: int = 1,
                 enabled: bool = True, min_scale: Optional[float] = None):
        self.init_scale = init_scale
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self.hysteresis = hysteresis
        self.enabled = enabled
        # floor under backoff: an overflow storm (every step non-finite)
        # would otherwise halve the scale to denormal/zero, silently
        # flushing all gradients — the failure mode resilience.step guards
        self.min_scale = min_scale

    def init(self) -> ScalerState:
        return ScalerState.create(self.init_scale, self.hysteresis)

    def scale(self, loss, state: ScalerState):
        if not self.enabled:
            return loss
        return scale_loss(loss, state)

    def unscale(self, grads: Any, state: ScalerState) -> Tuple[Any, jax.Array]:
        """Unscale grads, returning (unscaled_grads, found_inf)."""
        if not self.enabled:
            return grads, jnp.zeros((), jnp.bool_)
        inv = 1.0 / state.scale
        return multi_tensor_scale(grads, inv)

    def unscale_and_norm(self, grads: Any, state: ScalerState
                         ) -> Tuple[Any, jax.Array, jax.Array]:
        """Fused unscale + global grad-norm + overflow check in ONE pass
        over the gradients (ref csrc/amp_C_frontend.cpp:13-28
        ``multi_tensor_unscale_l2norm``).

        Returns ``(unscaled_grads, grad_norm, found_inf)`` — exactly what
        :func:`apex_tpu.monitor.metrics.collect_metrics` wants, so metric
        collection costs nothing beyond the unscale the step already does.
        """
        if not self.enabled:
            gnorm, _ = multi_tensor_l2norm(grads)
            return grads, gnorm, tree_check_finite(grads)
        out, gnorm, _, found_inf = multi_tensor_unscale_l2norm(
            grads, 1.0 / state.scale)
        return out, gnorm, found_inf

    def update(self, state: ScalerState, found_inf,
               freeze_growth: bool = False) -> ScalerState:
        """Advance the scale state machine given this step's found_inf.

        ``freeze_growth=True`` (the overflow-storm degraded mode set by
        :mod:`apex_tpu.resilience.step`) permits backoff but suppresses
        growth, so a recovering run can't immediately re-overflow;
        ``min_scale`` clamps backoff so a storm can't collapse the scale
        to zero. Both are static at trace time.
        """
        if not self.enabled:
            return state
        s, g, h = update_scale_hysteresis(
            state.scale, state.growth_tracker, state.hysteresis_tracker,
            found_inf, self.growth_factor, self.backoff_factor,
            self.growth_interval, self.hysteresis)
        if freeze_growth:
            s = jnp.minimum(s, state.scale)
        if self.min_scale is not None:
            s = jnp.maximum(s, jnp.float32(self.min_scale))
        return ScalerState(s, g, h)


class GradScaler(DynamicGradScaler):
    """Stateful torch.amp.GradScaler-style facade for host-driven loops.

    ``scaler.step(opt, grads)`` = unscale + inf check + (no-op'd) optimizer
    step + scale update, matching the modern reference flow
    (examples/imagenet/main_amp.py:153-154).
    """

    def __init__(self, **kw):
        super().__init__(**kw)
        self.state = self.init()

    def step(self, optimizer, grads: Any, lr=None):
        inv_scale = 1.0 / self.state.scale
        # finiteness of the scaled grads == finiteness of the grads: probe
        # without materializing an unscaled copy (the optimizer applies
        # inv_scale inside its fused update)
        found_inf = tree_check_finite(grads)
        params = optimizer.step(grads, lr=lr, inv_scale=inv_scale,
                                found_inf=found_inf)
        self.state = self.update(self.state, found_inf)
        return params

    def get_scale(self):
        return float(self.state.scale)
