"""Cast helpers ≈ ``apex/_autocast_utils.py:22-26`` (``_cast_if_autocast_enabled``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def cast_to(dtype, *args):
    """Cast every floating leaf of args to ``dtype``; pass others through."""
    out = jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float(x) else x, args)
    return out if len(args) != 1 else out[0]


def cast_if_autocast_enabled(compute_dtype, *args):
    """Signature-parity shim: in JAX autocast is the explicit policy dtype."""
    if compute_dtype is None:
        return args if len(args) != 1 else args[0]
    return cast_to(compute_dtype, *args)
