"""amp opt-level policies — TPU equivalent of ``amp.initialize`` O0–O3 semantics
(legacy surface spec'd by tests/L1/common/run_test.sh:29-49 and
tests/L1/common/main_amp.py:21-24).

On TPU the opt levels become dtype policies (SURVEY §7 step 4):
- O0: fp32 params, fp32 compute (pure fp32 baseline)
- O1: fp32 params, bf16 compute at op boundaries ("autocast" ≈ policy casts)
- O2: low-precision params + fp32 master weights in the optimizer
- O3: pure low-precision ("speed of light" mode)

``keep_batchnorm_fp32`` survives as a policy field consumed by the
normalization/model layers; ``loss_scale`` selects None / static / dynamic
scaling (only meaningful for fp16).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp.grad_scaler import DynamicGradScaler


@dataclasses.dataclass(frozen=True)
class Policy:
    opt_level: str
    param_dtype: Any
    compute_dtype: Any
    output_dtype: Any
    keep_batchnorm_fp32: bool
    loss_scale: Union[None, float, str]  # None | static value | "dynamic"
    master_weights: bool

    @classmethod
    def from_opt_level(cls, opt_level: str = "O1",
                       low_dtype=jnp.bfloat16,
                       keep_batchnorm_fp32: Optional[bool] = None,
                       loss_scale: Union[None, float, str] = None) -> "Policy":
        ol = opt_level.upper()
        if ol == "O0":
            # fp32 end to end; an explicit loss_scale is still honored
            # (the reference L1 matrix runs O0 with --loss-scale 1/128/
            # dynamic — scaling fp32 grads is a semantic no-op but the
            # machinery must run, run_test.sh:29-49)
            return cls(ol, jnp.float32, jnp.float32, jnp.float32,
                       True if keep_batchnorm_fp32 is None
                       else keep_batchnorm_fp32, loss_scale, False)
        if ol == "O1":
            return cls(ol, jnp.float32, low_dtype, jnp.float32,
                       True if keep_batchnorm_fp32 is None
                       else keep_batchnorm_fp32, loss_scale, False)
        if ol == "O2":
            return cls(ol, low_dtype, low_dtype, low_dtype,
                       True if keep_batchnorm_fp32 is None
                       else keep_batchnorm_fp32, loss_scale, True)
        if ol == "O3":
            return cls(ol, low_dtype, low_dtype, low_dtype,
                       False if keep_batchnorm_fp32 is None
                       else keep_batchnorm_fp32, loss_scale, False)
        raise ValueError(f"Unexpected optimization level {opt_level}")

    # -- helpers consumed by models / train loops ---------------------------
    def cast_params(self, params: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda p: p.astype(self.param_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)

    def cast_inputs(self, x: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda v: v.astype(self.compute_dtype)
            if jnp.issubdtype(v.dtype, jnp.floating) else v, x)

    def make_scaler(self) -> Optional[DynamicGradScaler]:
        if self.loss_scale is None:
            return None
        if self.loss_scale == "dynamic":
            return DynamicGradScaler()
        return DynamicGradScaler(init_scale=float(self.loss_scale),
                                 growth_interval=2 ** 31 - 1,
                                 growth_factor=1.0, backoff_factor=1.0)


def initialize(params: Any, optimizer=None, opt_level: str = "O1",
               keep_batchnorm_fp32: Optional[bool] = None,
               loss_scale: Union[None, float, str] = None,
               low_dtype=jnp.bfloat16):
    """≈ ``amp.initialize(model, opt, opt_level=...)``.

    Returns ``(cast_params, optimizer, policy, scaler_or_None)``. The caller
    runs the model with policy.cast_inputs / compute_dtype and feeds the scaler
    into the optimizer step (see apex_tpu.amp.grad_scaler).
    """
    policy = Policy.from_opt_level(opt_level, low_dtype, keep_batchnorm_fp32,
                                   loss_scale)
    cast = policy.cast_params(params)
    return cast, optimizer, policy, policy.make_scaler()
