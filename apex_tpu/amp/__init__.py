"""amp — mixed-precision machinery, TPU equivalent of the removed ``apex.amp``
package (spec: tests/L1/common/main_amp.py:21-24, run matrix
tests/L1/common/run_test.sh:29-49) and the ``amp_C`` loss-scaling kernels.

TPU reality: bf16 training needs no loss scaling, so O1/O2 become dtype
policies; the fp16 dynamic-loss-scale state machine survives as an optional,
fully-jitted component (``DynamicGradScaler``), with the exact hysteresis
semantics of csrc/update_scale_hysteresis.cu:5-41.
"""

from apex_tpu.amp.policy import Policy, initialize  # noqa: F401
from apex_tpu.amp.grad_scaler import (  # noqa: F401
    DynamicGradScaler,
    GradScaler,
    ScalerState,
    scale_loss,
)
from apex_tpu.amp._cast_utils import cast_to, cast_if_autocast_enabled  # noqa: F401
