"""apex_tpu.tune — shape-keyed Pallas kernel autotuner.

Every kernel in the zoo picks its tile geometry through
:func:`tuned_params`: a cached winner for the exact
``(kernel, shape-bucket, dtype, chip, code-version)`` when the on-disk
cache has one, else today's hand-written heuristics (now shared in
``ops/pallas/tiling.py``) — and ALWAYS the heuristics in interpret mode,
so CPU tests and virtual meshes never depend on cache state.

The cache is warmed by timing real compiled calls
(:func:`~apex_tpu.tune.search.autotune_kernel`, the ``apex-tpu-tune``
CLI) and persists as one JSON file (``APEX_TPU_TUNE_CACHE`` /
``~/.cache/apex_tpu/tune_cache.json``). Selections and search results
publish ``kernel_autotune`` events on the monitor event bus, so tuning
provenance lands in the telemetry JSONL. The committed
``BENCH_BASELINE.json`` + ``tools/check_regression.py --suite`` close the
loop: warm cache → bench → commit baseline → CI gate
(docs/performance.md).
"""

from apex_tpu.tune.api import (pow2_bucket, record_tuned,  # noqa: F401
                               tuned_params)
from apex_tpu.tune.cache import (CODE_VERSIONS, TuneCache,  # noqa: F401
                                 cache_key, code_version, default_cache,
                                 default_cache_path, device_key, invalidate)

__all__ = [
    "tuned_params", "record_tuned", "pow2_bucket", "TuneCache",
    "cache_key", "code_version", "CODE_VERSIONS", "default_cache",
    "default_cache_path", "device_key", "invalidate",
]
