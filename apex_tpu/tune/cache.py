"""Persistent shape-keyed autotune cache.

One JSON file maps deterministic string keys —
``kernel|shape-bucket|dtype|device|code-version`` — to the winning kernel
parameters found by ``apex_tpu.tune.search`` (or pinned by hand). The file
is the durable half of the autotuner: warmed once per (chip, code-version)
by ``apex-tpu-tune``, then consulted at trace time by every kernel's
``tuned_params()`` lookup.

Durability rules (mirroring ``apex_tpu.resilience``'s conventions):

- writes are atomic (tmp + ``os.replace``) so a reader never sees a torn
  file;
- an unreadable / corrupt / wrong-schema cache file degrades to an EMPTY
  cache with one ``tune_cache_corrupt`` structured warning — a broken
  cache must never break training, it only loses tuning;
- keys are pure functions of their inputs (no timestamps, no dict order,
  no floats) so two processes tuning the same workload produce identical
  keys and can share one file.

The default location is ``~/.cache/apex_tpu/tune_cache.json``; override
with ``APEX_TPU_TUNE_CACHE`` (tests point it at a tmpdir; CI can point it
at a committed warm cache).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

SCHEMA_VERSION = 1

# per-kernel code-version: bump when a kernel's tiling semantics change so
# stale cache entries (tuned against the old kernel) stop applying. This is
# the ``code-version`` component of every cache key.
CODE_VERSIONS = {
    "layer_norm": 1,
    "softmax": 1,
    "softmax_causal_chunked": 1,
    "group_norm": 1,
    "flash_attention": 1,
    # v2: the paged KV pool added a page_size shape-key axis and the
    # block_k-divides-page constraint — entries tuned against the v1
    # slot-only geometry must not apply
    # v3: tensor-parallel serving added a tp_shards shape-key axis (the
    # per-shard head count changes the best block shapes) — v2 entries,
    # keyed without it, must invalidate rather than apply to a mesh
    # shape they were never timed on
    "decode_attention": 3,
    "fused_adam": 1,
    "fused_sgd": 1,
    "fused_lamb": 1,
    "fused_novograd": 1,
    "fused_adagrad": 1,
}


def code_version(kernel: str) -> int:
    return CODE_VERSIONS.get(kernel, 0)


def default_cache_path() -> str:
    env = os.environ.get("APEX_TPU_TUNE_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "apex_tpu",
                        "tune_cache.json")


def device_key(devices=None) -> str:
    """Stable chip identifier for cache keys: the detected generation
    (``v5e``/``v5p``/``v6e``), else the raw ``device_kind`` slug, else
    ``cpu``. Never raises — keys must be computable backend-less."""
    try:
        from apex_tpu.utils.prof import detect_chip

        gen = detect_chip(devices)
        if gen:
            return gen
        if devices is None:
            import jax

            devices = jax.devices()
        if devices and getattr(devices[0], "platform", None) == "tpu":
            kind = str(getattr(devices[0], "device_kind", "tpu"))
            return kind.lower().replace(" ", "-") or "tpu"
    except Exception:
        pass
    return "cpu"


def cache_key(kernel: str, shape_key, dtype, device: str,
              version: Optional[int] = None) -> str:
    """Deterministic cache key.

    ``shape_key`` is a tuple of ``(name, value)`` pairs (already bucketed
    by the caller — see ``apex_tpu.tune.api.pow2_bucket``); ``dtype`` any
    jnp dtype / dtype-like / None. The rendering is canonical: pairs are
    sorted by name, values rendered with ``repr`` for ints/bools/strings
    only, so the same inputs produce the same key in every process.
    """
    parts = []
    for name, value in sorted(shape_key):
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, str)):
            raise TypeError(
                f"shape_key value for {name!r} must be int/bool/str, got "
                f"{type(value).__name__} (floats and arrays are not "
                f"deterministic key material)")
        parts.append(f"{name}={value}")
    if dtype is None:
        dt = "any"
    else:
        try:  # canonical name for jnp scalar types / np dtypes / strings
            import numpy as np

            dt = np.dtype(dtype).name
        except Exception:
            dt = str(getattr(dtype, "name", dtype))
    ver = code_version(kernel) if version is None else int(version)
    return f"{kernel}|{','.join(parts)}|{dt}|{device}|v{ver}"


class TuneCache:
    """On-disk JSON autotune cache with atomic writes and corrupt-file
    fallback. Thread-safe for the in-process mutation path."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_cache_path()
        self._lock = threading.Lock()
        self.entries: Dict[str, Dict[str, Any]] = {}
        self.load()

    def load(self) -> "TuneCache":
        """(Re)load entries from disk; corrupt or alien files degrade to an
        empty cache with one structured warning."""
        from apex_tpu.utils.logging import structured_warning

        entries: Dict[str, Dict[str, Any]] = {}
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    doc = json.load(f)
                if not isinstance(doc, dict) or \
                        not isinstance(doc.get("entries"), dict):
                    raise ValueError("not a tune-cache document")
                if doc.get("schema") != SCHEMA_VERSION:
                    raise ValueError(
                        f"schema {doc.get('schema')!r} != {SCHEMA_VERSION}")
                for key, entry in doc["entries"].items():
                    if isinstance(entry, dict) and \
                            isinstance(entry.get("params"), dict):
                        entries[key] = entry
            except (ValueError, OSError) as e:
                structured_warning(
                    "tune_cache_corrupt", path=self.path,
                    error=f"{type(e).__name__}: {e}",
                    action="falling back to heuristic tile choices")
                entries = {}
        with self._lock:
            self.entries = entries
        return self

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.entries.get(key)

    def put(self, key: str, params: Dict[str, Any],
            meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        entry = {"params": dict(params)}
        if meta:
            entry["meta"] = dict(meta)
        with self._lock:
            self.entries[key] = entry
        return entry

    def save(self) -> str:
        """Atomic write (tmp + rename); creates parent dirs on demand."""
        doc = {"schema": SCHEMA_VERSION, "entries": self.entries}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)
        return self.path

    def __len__(self) -> int:
        return len(self.entries)


# process-wide default cache, loaded lazily per path (the env var can move
# it between tests); invalidate() drops it so the next lookup reloads.
_default: Tuple[Optional[str], Optional[TuneCache]] = (None, None)
_default_lock = threading.Lock()


def default_cache() -> TuneCache:
    global _default
    path = default_cache_path()
    with _default_lock:
        cached_path, cache = _default
        if cache is None or cached_path != path:
            cache = TuneCache(path)
            _default = (path, cache)
        return cache


def invalidate() -> None:
    """Forget the process-wide cache so the next lookup reloads from disk
    (used after ``apex-tpu-tune`` writes, and by tests)."""
    global _default
    with _default_lock:
        _default = (None, None)
