"""``tuned_params()`` — the one lookup every Pallas kernel entry point
makes before choosing its tile geometry.

Contract (asserted in tests/test_tune.py):

- **interpret mode never consults the cache**: CPU tests and virtual
  meshes always get the hand-written heuristics, so numerics/grids there
  are independent of whatever cache file happens to exist;
- **empty cache == today's heuristics, bit for bit**: a miss returns the
  ``defaults`` dict unchanged;
- a hit merges ONLY keys already present in ``defaults`` (a cache entry
  cannot smuggle unknown kwargs into a kernel) and is optionally passed
  through a ``validate`` predicate — an entry tuned for a different shape
  in the same bucket that no longer satisfies the kernel's divisibility
  constraints falls back to the heuristics instead of crashing inside
  ``pallas_call``;
- every selection publishes ONE ``kernel_autotune`` event per (key,
  params) on the event bus (``utils.logging.publish_event``), so a
  :class:`~apex_tpu.monitor.telemetry.Telemetry` sink records tuning
  provenance in the run's JSONL.

Lookups happen at Python trace time (shapes are static), cost one dict
probe after the first call, and never touch the backend.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from apex_tpu.tune.cache import (cache_key, code_version, default_cache,
                                 device_key)
from apex_tpu.utils.env import interpret_default

# (key, frozen params) pairs already announced on the event bus — one
# kernel_autotune event per distinct selection per process, not per trace
_announced: set = set()


def pow2_bucket(n: int) -> int:
    """Shape-bucketing quantum for cache keys: next power of two. Nearby
    row counts share one tuned entry; the per-kernel ``validate`` hook
    rejects entries that stop dividing a particular member of the bucket."""
    from apex_tpu.ops.pallas.tiling import pow2_ceil

    return pow2_ceil(n)


def _announce(kernel: str, key: str, params: Dict[str, Any],
              source: str) -> None:
    from apex_tpu.utils.logging import publish_event

    tag = (key, tuple(sorted(params.items())))
    if tag in _announced:
        return
    _announced.add(tag)
    publish_event("kernel_autotune", kernel=kernel, key=key,
                  params=dict(params), source=source, emit=False)


def tuned_params(kernel: str, shape_key, defaults: Dict[str, Any], *,
                 dtype=None, interpret: Optional[bool] = None,
                 validate: Optional[Callable[[Dict[str, Any]], bool]] = None,
                 ) -> Dict[str, Any]:
    """Resolve a kernel's tile parameters: cached winner if one exists for
    this (kernel, shape-bucket, dtype, chip, code-version), else the
    hand-written ``defaults``.

    ``shape_key``: tuple of ``(name, value)`` pairs, pre-bucketed by the
    caller (``pow2_bucket`` for row-ish dims, exact for layout-defining
    dims like ``hidden``). ``interpret=None`` resolves via
    :func:`~apex_tpu.utils.env.interpret_default`; ``interpret=True``
    short-circuits to ``defaults`` without touching the cache.
    ``validate(params)`` may reject a merged candidate (fall back to
    defaults) when it violates the kernel's constraints for the CONCRETE
    shape at hand.
    """
    if interpret is None:
        interpret = interpret_default()
    if interpret:
        return dict(defaults)
    import os

    if os.environ.get("APEX_TPU_FORCE_COMPILED") == "1":
        # deviceless AOT compile (tools/mosaic_aot.py & co.): the jit
        # target is a topology client, not jax.devices() — device_key()
        # would name the HOST, so a stray cache file could silently change
        # the committed AOT artifacts. Heuristics only.
        return dict(defaults)
    key = cache_key(kernel, shape_key, dtype, device_key())
    entry = default_cache().get(key)
    if entry is None:
        return dict(defaults)
    params = entry.get("params", {})
    merged = dict(defaults)
    merged.update({k: params[k] for k in defaults if k in params})
    if merged == dict(defaults):
        return merged
    if validate is not None and not validate(merged):
        return dict(defaults)
    _announce(kernel, key, merged, source="cache")
    return merged


def record_tuned(kernel: str, shape_key, params: Dict[str, Any], *,
                 dtype=None, meta: Optional[Dict[str, Any]] = None,
                 device: Optional[str] = None, save: bool = True) -> str:
    """Store a tuning winner in the default cache (search results, or a
    hand-pinned config) and publish its ``kernel_autotune`` provenance
    event. Returns the cache key."""
    key = cache_key(kernel, shape_key, dtype, device or device_key(),
                    code_version(kernel))
    cache = default_cache()
    cache.put(key, params, meta=meta)
    if save:
        cache.save()
    _announce(kernel, key, dict(params), source="search")
    return key
