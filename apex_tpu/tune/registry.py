"""Tunable-kernel registry: how each Pallas kernel is searched.

One :class:`KernelSpec` per kernel entry point declares:

- ``shape_key(shape)`` — the bucketed cache-key pairs, computed EXACTLY the
  way the kernel's ``tuned_params()`` call site computes them (same
  padding, same bucketing) so warmed entries are found at run time;
- ``defaults(shape)`` — today's heuristic choice (from
  ``ops/pallas/tiling.py``, the shared source of truth);
- ``candidates(shape)`` — the geometries the search times, always
  including the default so the heuristic can win;
- ``build(shape, dtype, params)`` — a ``(step_fn, state, consts)`` triple
  for :func:`apex_tpu.utils.benchtime.timed_steps` that exercises the real
  kernel at that geometry (compiled on TPU; interpret elsewhere, which is
  only meaningful as a smoke test).

Kernel modules are imported lazily inside ``build`` so importing the tune
package never drags the kernel zoo (and cannot create an import cycle:
the kernels import ``apex_tpu.tune.api``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence, Tuple

from apex_tpu.ops.pallas.tiling import (groupnorm_hw_block, norm_block_rows,
                                        round_up, softmax_block_rows)
from apex_tpu.tune.api import pow2_bucket

ShapeKey = Tuple[Tuple[str, Any], ...]


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    name: str
    shape_key: Callable[[Dict[str, Any]], ShapeKey]
    defaults: Callable[[Dict[str, Any]], Dict[str, Any]]
    candidates: Callable[[Dict[str, Any]], List[Dict[str, Any]]]
    build: Callable[..., Tuple[Callable, Any, Tuple]]
    default_shapes: Tuple[Dict[str, Any], ...] = ()
    # kernels whose lookup is keyed dtype=None (the flat optimizers: the
    # streaming block depends on row count, not element type, and the
    # master-weight fp32 variant must share bf16-warmed entries)
    dtype_agnostic: bool = False


def _row_block_candidates(limit: int, ceiling: int = 2048,
                          floor: int = 8) -> List[int]:
    out = []
    br = floor
    while br <= min(limit, ceiling):
        out.append(br)
        br *= 2
    return out or [floor]


# ----------------------------------------------------------- layer_norm


def _ln_padded_rows(shape):
    return round_up(int(shape["rows"]), 8)


def _ln_shape_key(shape) -> ShapeKey:
    return (("rows", pow2_bucket(_ln_padded_rows(shape))),
            ("hidden", int(shape["hidden"])))


def _ln_defaults(shape):
    return {"block_rows": norm_block_rows(_ln_padded_rows(shape),
                                          int(shape["hidden"]))}


def _ln_candidates(shape):
    from apex_tpu.ops.pallas.tiling import NORM_VMEM_BUDGET

    rows, hidden = _ln_padded_rows(shape), int(shape["hidden"])
    cands = []
    for br in _row_block_candidates(rows, ceiling=1024):
        # the winner is consulted by ln_bwd_pallas too (dy + saved + dx
        # streams, MORE resident tiles than the forward) — blocks must
        # tile rows exactly AND keep the slab inside the same VMEM budget
        # the heuristic honors, so a fwd-timed winner cannot OOM the bwd
        if rows % br == 0 and br * hidden * 4 <= NORM_VMEM_BUDGET:
            cands.append({"block_rows": br})
    default = _ln_defaults(shape)
    if default not in cands:
        cands.append(default)
    return cands


def _ln_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.layer_norm_kernel import ln_fwd_pallas

    rows, hidden = int(shape["rows"]), int(shape["hidden"])
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, hidden), dtype)
    g = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)
    br = params["block_rows"]

    def step(i, x, g, b):
        y, _, _ = ln_fwd_pallas(x, g, b, eps=1e-5, rms=False,
                                interpret=interpret, block_rows=br)
        return y.astype(x.dtype)

    return step, x, (g, b)


# -------------------------------------------------------------- softmax


def _sm_skp(shape):
    return round_up(int(shape["sk"]), 128)


def _sm_shape_key(shape) -> ShapeKey:
    return (("sk", _sm_skp(shape)),
            ("sq", pow2_bucket(int(shape["sq"]))),
            ("mask", bool(shape.get("mask", False))))


def _sm_defaults(shape):
    return {"block_rows": softmax_block_rows(
        _sm_skp(shape), int(shape["sq"]), int(shape.get("itemsize", 2)),
        bool(shape.get("mask", False)))}


def _sm_candidates(shape):
    from apex_tpu.ops.pallas.tiling import SOFTMAX_VMEM_BUDGET

    skp, sq = _sm_skp(shape), int(shape["sq"])
    itemsize = int(shape.get("itemsize", 2))
    # the winner is also consulted by softmax_bwd_pallas, which streams
    # THREE row-complete tiles (y, dy, dx) double-buffered plus fp32
    # temporaries — bound candidates by that footprint (≈6·itemsize+12
    # bytes/elt), and keep the heuristic's 512-row cap
    cands = [{"block_rows": br}
             for br in _row_block_candidates(round_up(sq, 8), ceiling=512)
             if skp * br * (6 * itemsize + 12) <= SOFTMAX_VMEM_BUDGET]
    default = _sm_defaults(shape)
    if default not in cands:
        cands.append(default)
    return cands


def _sm_build(shape, dtype, params, interpret=None):
    import jax

    from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas

    B, sq, sk = int(shape.get("B", 8)), int(shape["sq"]), int(shape["sk"])
    x = jax.random.normal(jax.random.PRNGKey(0), (B, sq, sk), dtype) * 0.1
    br = params["block_rows"]

    def step(i, x):
        # softmax output is a stable input distribution; chain directly
        return softmax_fwd_pallas(x, None, scale=1.0, causal=False,
                                  interpret=interpret,
                                  block_rows=br).astype(x.dtype)

    return step, x, ()


# ------------------------------------------- softmax (causal, chunked)


def _smc_shape_key(shape) -> ShapeKey:
    return (("sk", _sm_skp(shape)), ("sq", pow2_bucket(int(shape["sq"]))))


def _smc_defaults(shape):
    skp = _sm_skp(shape)
    return {
        "block_rows": softmax_block_rows(skp, int(shape["sq"]),
                                         int(shape.get("itemsize", 2)),
                                         False),
        "chunk_cols": next((c for c in (512, 256, 128)
                            if skp % c == 0 and skp > c), 0),
    }


def _smc_candidates(shape):
    from apex_tpu.ops.pallas.tiling import SOFTMAX_VMEM_BUDGET

    skp, sq = _sm_skp(shape), int(shape["sq"])
    itemsize = int(shape.get("itemsize", 2))
    chunks = [c for c in (1024, 512, 256, 128) if skp % c == 0 and skp > c]
    # dominant residents: the (br, skp) fp32 staging scratch plus the
    # double-buffered in/out tiles
    cands = [{"block_rows": br, "chunk_cols": bc}
             for br in _row_block_candidates(round_up(sq, 8), ceiling=512,
                                             floor=32)
             for bc in chunks
             if skp * br * (4 + 4 * itemsize) <= SOFTMAX_VMEM_BUDGET]
    default = _smc_defaults(shape)
    if default["chunk_cols"] and default not in cands:
        cands.append(default)
    return cands


def _smc_build(shape, dtype, params, interpret=None):
    import jax

    from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas

    B, sq, sk = int(shape.get("B", 8)), int(shape["sq"]), int(shape["sk"])
    x = jax.random.normal(jax.random.PRNGKey(0), (B, sq, sk), dtype) * 0.1

    def step(i, x):
        return softmax_fwd_pallas(
            x, None, scale=1.0, causal=True, interpret=interpret,
            block_rows=params["block_rows"],
            chunk_cols=params["chunk_cols"]).astype(x.dtype)

    return step, x, ()


# ----------------------------------------------------------- group_norm


def _gn_shape_key(shape) -> ShapeKey:
    return (("hw", pow2_bucket(int(shape["hw"]))),
            ("c", int(shape["c"])))


def _gn_defaults(shape):
    return {"hw_block": groupnorm_hw_block(int(shape["hw"]),
                                           int(shape["c"]))}


def _gn_candidates(shape):
    from apex_tpu.ops.pallas.tiling import NORM_VMEM_BUDGET

    hw, c = int(shape["hw"]), int(shape["c"])
    cands = []
    for blk in _row_block_candidates(hw, ceiling=4096):
        # same slab budget as the heuristic: the stats+apply pair streams
        # multiple (blk, c) tiles double-buffered
        if hw % blk == 0 and blk * c * 4 <= NORM_VMEM_BUDGET:
            cands.append({"hw_block": blk})
    default = _gn_defaults(shape)
    if default not in cands:
        cands.append(default)
    return cands


def _gn_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.group_norm_kernel import group_norm_nhwc_pallas

    n = int(shape.get("n", 2))
    hw, c, g = int(shape["hw"]), int(shape["c"]), int(shape.get("groups", 8))
    h = int(hw ** 0.5)
    while hw % h:
        h -= 1
    w = hw // h
    x = jax.random.normal(jax.random.PRNGKey(0), (n, h, w, c), dtype)
    weight = jnp.ones((c,), jnp.float32)
    blk = params["hw_block"]

    def step(i, x, weight):
        y, _, _ = group_norm_nhwc_pallas(x, g, weight, None,
                                         interpret=interpret,
                                         algo="two_pass", hw_block=blk)
        return y.astype(x.dtype)

    return step, x, (weight,)


# ------------------------------------------------------ flash_attention


def _fa_shape_key(shape) -> ShapeKey:
    return (("sq", pow2_bucket(int(shape["sq"]))),
            ("sk", pow2_bucket(int(shape["sk"]))),
            ("d", int(shape["d"])),
            ("causal", bool(shape.get("causal", True))))


def _fa_defaults(shape):
    from apex_tpu.ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                                     DEFAULT_BLOCK_Q)

    return {"block_q": DEFAULT_BLOCK_Q, "block_k": DEFAULT_BLOCK_K}


# the on-chip sweep set of tools/tune_flash.py, minus the configs whose
# BACKWARD exceeds v5e VMEM (proven deviceless via tools/flash_blocks_aot)
_FA_BLOCKS = ((128, 512), (128, 1024), (128, 2048), (256, 256), (256, 512),
              (256, 1024), (256, 2048), (512, 512), (512, 1024),
              (512, 2048), (1024, 512), (2048, 512))


def _fa_candidates(shape):
    sq, sk = int(shape["sq"]), int(shape["sk"])
    cands = [{"block_q": bq, "block_k": bk} for bq, bk in _FA_BLOCKS
             if bq <= sq and bk <= sk]
    default = _fa_defaults(shape)
    if default not in cands:
        cands.append(default)
    return cands


def _fa_build(shape, dtype, params, interpret=None):
    import jax

    from apex_tpu.ops.pallas.flash_attention import flash_attention_fwd

    b, h = int(shape.get("b", 4)), int(shape.get("h", 16))
    sq, sk, d = int(shape["sq"]), int(shape["sk"]), int(shape["d"])
    causal = bool(shape.get("causal", True))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, sq, d), dtype) * 0.2
    k = jax.random.normal(ks[1], (b, h, sk, d), dtype) * 0.2
    v = jax.random.normal(ks[2], (b, h, sk, d), dtype) * 0.2
    scale = 1.0 / (d ** 0.5)
    bq, bk = params["block_q"], params["block_k"]

    def step(i, q, k, v):
        o, _ = flash_attention_fwd(q, k, v, scale=scale, causal=causal,
                                   block_q=bq, block_k=bk,
                                   interpret=interpret)
        return o.astype(q.dtype)

    return step, q, (k, v)


# ----------------------------------------------------- decode_attention


def _da_shape_key(shape) -> ShapeKey:
    # max_len keyed exactly (layout-defining static engine constant; the
    # winner must divide it) — matches serve.attention.resolve_block_k.
    # page_size is a second exact geometry axis (0 = slot cache): a paged
    # chunk must live inside one page, so a winner tuned at one page size
    # cannot apply to another (or to the slot layout) — CODE_VERSIONS
    # bumped to 2 when this axis landed so v1 entries invalidate.
    # tp_shards (1 = single chip) is a third: a tensor-parallel engine
    # runs this kernel per mesh rank with `heads` = its PER-SHARD head
    # count, and a winner timed unsharded must not apply to a sharded
    # instance (or vice versa) — CODE_VERSIONS bumped to 3 with it so v2
    # entries invalidate cleanly.
    return (("max_len", int(shape["max_len"])),
            ("page_size", int(shape.get("page_size", 0))),
            ("heads", int(shape["heads"])),
            ("d", int(shape["d"])),
            ("tp_shards", int(shape.get("tp_shards", 1))))


def _da_unit(shape) -> int:
    """The span a chunk must divide: the page (paged) or the whole key
    axis (slot cache)."""
    ps = int(shape.get("page_size", 0))
    return ps if ps else int(shape["max_len"])


def _da_defaults(shape):
    from apex_tpu.ops.pallas.tiling import decode_attention_block

    return {"block_k": decode_attention_block(_da_unit(shape))}


def _da_candidates(shape):
    unit = _da_unit(shape)
    cands = [{"block_k": bk} for bk in (128, 256, 512, 1024, 2048)
             if bk <= unit and unit % bk == 0]
    default = _da_defaults(shape)
    if default not in cands:
        cands.append(default)
    return cands


def _da_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    b = int(shape.get("b", 8))
    L, h, d = (int(shape["max_len"]), int(shape["heads"]),
               int(shape["d"]))
    ps = int(shape.get("page_size", 0))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, d), dtype) * 0.2
    positions = jnp.full((b,), L - 1, jnp.int32)  # worst case: full cache
    bk = params["block_k"]

    if ps:
        # paged layout: time the page-table gather path at full residency
        # (every slot's table maps distinct live pages, like a busy pool)
        from apex_tpu.serve.attention import paged_attention

        mp = L // ps
        P = b * mp + 1                         # +1: the reserved null page
        kc = jax.random.normal(ks[1], (P, ps, h, d), dtype) * 0.2
        vc = jax.random.normal(ks[2], (P, ps, h, d), dtype) * 0.2
        table = jnp.arange(1, P, dtype=jnp.int32).reshape(b, mp)

        def step(i, q, kc, vc):
            return paged_attention(q, kc, vc, table, positions,
                                   block_k=bk, interpret=interpret)

        return step, q, (kc, vc)

    from apex_tpu.serve.attention import cached_attention

    kc = jax.random.normal(ks[1], (b, L, h, d), dtype) * 0.2
    vc = jax.random.normal(ks[2], (b, L, h, d), dtype) * 0.2

    def step(i, q, kc, vc):
        return cached_attention(q, kc, vc, positions, block_k=bk,
                                interpret=interpret)

    return step, q, (kc, vc)


# ------------------------------------------------------ flat optimizers


def _flat_shape_key(shape) -> ShapeKey:
    rows = int(shape["numel"]) // 128
    return (("rows", pow2_bucket(rows)),)


def _flat_defaults(shape):
    from apex_tpu.ops.pallas.fused_adam_kernel import _pick_block_rows

    return {"block_rows": _pick_block_rows(int(shape["numel"]) // 128)}


def _flat_candidates(shape):
    rows = int(shape["numel"]) // 128
    cands = [{"block_rows": br}
             for br in _row_block_candidates(rows, ceiling=2048, floor=64)]
    default = _flat_defaults(shape)
    if default not in cands:
        cands.append(default)
    return cands


def _adam_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_adam_kernel import fused_adam_flat

    n = int(shape["numel"])
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), dtype) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), dtype)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    br = params["block_rows"]

    def step(i, st, g):
        p, m, v = st
        return tuple(fused_adam_flat(p, g, m, v, lr=1e-3, step=i + 1,
                                     block_rows=br, interpret=interpret))

    return step, (p, m, v), (g,)


def _lamb_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_opt_kernels import fused_lamb_flat

    n = int(shape["numel"])
    rows = n // 128
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), dtype) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), dtype)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    # one-tensor buffer: every row belongs to segment 0
    row_ids = jnp.zeros((rows,), jnp.int32)
    br = params["block_rows"]

    def step(i, st, g, row_ids):
        p, m, v = st
        p, m, v, _ = fused_lamb_flat(p, g, m, v, row_ids, num_tensors=1,
                                     lr=1e-3, step=i + 1, block_rows=br,
                                     interpret=interpret)
        return (p, m, v)

    return step, (p, m, v), (g, row_ids)


def _novograd_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_opt_kernels import fused_novograd_flat

    n = int(shape["numel"])
    rows = n // 128
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), dtype) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), dtype)
    m = jnp.zeros((n,), jnp.float32)
    vt = jnp.zeros((1,), jnp.float32)  # per-tensor 2nd-moment norm state
    row_ids = jnp.zeros((rows,), jnp.int32)
    br = params["block_rows"]

    def step(i, st, g, row_ids):
        p, m, vt = st
        return tuple(fused_novograd_flat(
            p, g, m, vt, row_ids, num_tensors=1, lr=1e-3, step=i + 1,
            block_rows=br, interpret=interpret))

    return step, (p, m, vt), (g, row_ids)


def _adagrad_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_opt_kernels import fused_adagrad_flat

    n = int(shape["numel"])
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), dtype) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), dtype)
    h = jnp.zeros((n,), jnp.float32)
    br = params["block_rows"]

    def step(i, st, g):
        p, h = st
        return tuple(fused_adagrad_flat(p, g, h, lr=1e-3, block_rows=br,
                                        interpret=interpret))

    return step, (p, h), (g,)


def _sgd_build(shape, dtype, params, interpret=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_sgd_kernel import fused_sgd_flat

    n = int(shape["numel"])
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), dtype) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), dtype)
    buf = jnp.zeros((n,), jnp.float32)
    br = params["block_rows"]

    def step(i, st, g):
        p, buf = st
        return tuple(fused_sgd_flat(p, g, buf, lr=1e-3, momentum=0.9,
                                    block_rows=br, interpret=interpret))

    return step, (p, buf), (g,)


SPECS: Dict[str, KernelSpec] = {}


def _register(spec: KernelSpec) -> None:
    SPECS[spec.name] = spec


_register(KernelSpec(
    "layer_norm", _ln_shape_key, _ln_defaults, _ln_candidates, _ln_build,
    default_shapes=({"rows": 8192, "hidden": 4096},)))
_register(KernelSpec(
    "softmax", _sm_shape_key, _sm_defaults, _sm_candidates, _sm_build,
    default_shapes=({"B": 128, "sq": 1024, "sk": 1024},)))
_register(KernelSpec(
    "softmax_causal_chunked", _smc_shape_key, _smc_defaults,
    _smc_candidates, _smc_build,
    default_shapes=({"B": 128, "sq": 1024, "sk": 1024},)))
_register(KernelSpec(
    "group_norm", _gn_shape_key, _gn_defaults, _gn_candidates, _gn_build,
    default_shapes=({"n": 2, "hw": 4096, "c": 256, "groups": 32},)))
_register(KernelSpec(
    "flash_attention", _fa_shape_key, _fa_defaults, _fa_candidates,
    _fa_build,
    default_shapes=({"b": 4, "h": 16, "sq": 2048, "sk": 2048, "d": 64,
                     "causal": True},)))
_register(KernelSpec(
    "decode_attention", _da_shape_key, _da_defaults, _da_candidates,
    _da_build,
    # both layouts warm by default: the slot cache and the paged pool at
    # the serving default page size (page_size=0 means slot layout)
    default_shapes=({"b": 8, "max_len": 2048, "heads": 16, "d": 64},
                    {"b": 8, "max_len": 2048, "page_size": 256,
                     "heads": 16, "d": 64})))
_register(KernelSpec(
    "fused_adam", _flat_shape_key, _flat_defaults, _flat_candidates,
    _adam_build, default_shapes=({"numel": 134_217_728},),
    dtype_agnostic=True))
_register(KernelSpec(
    "fused_sgd", _flat_shape_key, _flat_defaults, _flat_candidates,
    _sgd_build, default_shapes=({"numel": 134_217_728},),
    dtype_agnostic=True))
_register(KernelSpec(
    "fused_lamb", _flat_shape_key, _flat_defaults, _flat_candidates,
    _lamb_build, default_shapes=({"numel": 134_217_728},),
    dtype_agnostic=True))
_register(KernelSpec(
    "fused_novograd", _flat_shape_key, _flat_defaults, _flat_candidates,
    _novograd_build, default_shapes=({"numel": 134_217_728},),
    dtype_agnostic=True))
_register(KernelSpec(
    "fused_adagrad", _flat_shape_key, _flat_defaults, _flat_candidates,
    _adagrad_build, default_shapes=({"numel": 134_217_728},),
    dtype_agnostic=True))


def spec(kernel: str) -> KernelSpec:
    try:
        return SPECS[kernel]
    except KeyError:
        raise KeyError(
            f"unknown tunable kernel {kernel!r}; known: "
            f"{sorted(SPECS)}") from None


def kernels() -> Sequence[str]:
    return sorted(SPECS)
