"""``apex-tpu-tune`` — warm the shape-keyed kernel autotune cache.

Usage::

    apex-tpu-tune [--kernels layer_norm,flash_attention | all]
                  [--spec workload.json] [--cache PATH]
                  [--iters N] [--max-candidates N]
                  [--telemetry-jsonl PATH]

``--spec`` points at a JSON workload description — a list of
``{"kernel": ..., "shape": {...}, "dtype": "bfloat16"}`` entries; without
it, each selected kernel tunes its registry ``default_shapes`` (the bench
shapes). ``--cache`` overrides the cache file (else
``APEX_TPU_TUNE_CACHE`` / ``~/.cache/apex_tpu/tune_cache.json``).

Every search publishes ``kernel_autotune`` events on the process event
bus; ``--telemetry-jsonl`` attaches a :class:`apex_tpu.monitor.Telemetry`
sink so those events (tuning provenance: key, winning params, timings)
land in a JSONL next to your training telemetry. One JSON line per tuned
(kernel, shape) is printed to stdout as it completes; the last line is a
summary ``{"tuned": N, "cache": PATH, ...}``.

Off-TPU the kernels run in interpret mode — the timings are meaningless
for real tuning (the CLI says so on stderr) but the full pipeline
(search → cache write → events) runs, which is what the CPU smoke test
exercises. Real warming happens on the chip, typically via the
background chip worker (docs/performance.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List


def build_workload(args) -> List[Dict[str, Any]]:
    from apex_tpu.tune import registry

    if args.spec:
        with open(args.spec) as f:
            doc = json.load(f)
        if not isinstance(doc, list):
            raise SystemExit(f"--spec {args.spec}: expected a JSON list of "
                             "{kernel, shape, dtype?} entries")
        for entry in doc:
            registry.spec(entry["kernel"])  # fail fast on unknown kernels
            if not isinstance(entry.get("shape"), dict):
                raise SystemExit(f"--spec entry missing 'shape': {entry}")
        return doc

    if args.kernels in (None, "", "all"):
        names = list(registry.kernels())
    else:
        names = [k.strip() for k in args.kernels.split(",") if k.strip()]
    workload = []
    for name in names:
        spec = registry.spec(name)
        for shape in spec.default_shapes or ():
            workload.append({"kernel": name, "shape": dict(shape)})
    return workload


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="apex-tpu-tune",
        description="warm the Pallas kernel autotune cache for a workload")
    ap.add_argument("--kernels", default="all",
                    help="comma-separated kernel subset (default: all)")
    ap.add_argument("--spec", default=None,
                    help="JSON workload file: [{kernel, shape, dtype?}]")
    ap.add_argument("--cache", default=None,
                    help="cache file (default: APEX_TPU_TUNE_CACHE or "
                         "~/.cache/apex_tpu/tune_cache.json)")
    ap.add_argument("--iters", type=int, default=None,
                    help="timed steps per candidate (default: 10 on TPU, "
                         "2 off-TPU)")
    ap.add_argument("--max-candidates", type=int, default=None,
                    help="cap the per-shape candidate sweep")
    ap.add_argument("--telemetry-jsonl", default=None,
                    help="mirror kernel_autotune events into this JSONL "
                         "via apex_tpu.monitor.Telemetry")
    args = ap.parse_args(argv)

    if args.cache:
        os.environ["APEX_TPU_TUNE_CACHE"] = args.cache

    from apex_tpu.tune import cache as tune_cache
    from apex_tpu.tune.search import warm_cache

    tune_cache.invalidate()  # respect a just-set --cache path

    import jax

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        print("[apex-tpu-tune] no TPU backend: kernels run in interpret "
              "mode — cache entries are smoke artifacts, not real tuning",
              file=sys.stderr)
    iters = args.iters if args.iters is not None else (10 if on_tpu else 2)

    workload = build_workload(args)
    if not workload:
        print("[apex-tpu-tune] empty workload", file=sys.stderr)
        return 2

    tel = None
    if args.telemetry_jsonl:
        from apex_tpu.monitor import Telemetry

        tel = Telemetry(args.telemetry_jsonl)

    failures = 0
    try:
        results = []
        for entry in workload:
            res = warm_cache([entry], iters=iters,
                             max_candidates=args.max_candidates)[0]
            results.append(res)
            line = {k: res.get(k) for k in
                    ("kernel", "key", "best", "best_ms", "default_ms",
                     "speedup_vs_default", "error") if res.get(k) is not None}
            print(json.dumps(line), flush=True)
            if "error" in res:
                failures += 1
    finally:
        if tel is not None:
            tel.close()

    path = tune_cache.default_cache().save()
    tune_cache.invalidate()  # consumers in this process reload the file
    print(json.dumps({"tuned": len(results) - failures,
                      "failed": failures,
                      "entries": len(tune_cache.default_cache()),
                      "backend": "tpu" if on_tpu else "interpret",
                      "cache": path}))
    return 1 if failures and failures == len(results) else 0


if __name__ == "__main__":
    sys.exit(main())
