"""Autotune search: time real compiled kernel calls per candidate tile
geometry and persist the winner.

Timing rides :func:`apex_tpu.utils.benchtime.timed_steps` — K chained
steps inside one jitted ``fori_loop`` with a data-dependent host fetch —
the same methodology as ``bench.py`` (per-dispatch wall clock is
meaningless on tunneled/async runtimes; see docs/performance.md). On a
CPU host the kernels run in interpret mode, which only exercises the
machinery (the CLI smoke test); real tuning needs the chip (typically
via the background chip worker). ``APEX_TPU_FORCE_COMPILED`` is NOT a
tuning path: under it ``tuned_params`` deliberately skips the cache
(deviceless AOT has no trustworthy device identity), so entries warmed
that way would be dead on arrival.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from apex_tpu.tune import registry
from apex_tpu.tune.api import record_tuned
from apex_tpu.tune.cache import cache_key, code_version, device_key
from apex_tpu.utils.logging import publish_event


def autotune_kernel(kernel: str, shape: Dict[str, Any], dtype=None, *,
                    iters: int = 10, floor_s: Optional[float] = None,
                    max_candidates: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    save: bool = True) -> Dict[str, Any]:
    """Search the candidate geometries for ``kernel`` at ``shape`` and
    store the fastest in the tune cache.

    Returns a result record ``{kernel, key, best, best_ms, default,
    default_ms, candidates: [...]}``. Candidates that fail to compile or
    run are recorded with an ``error`` and skipped — a geometry that
    exceeds VMEM must not kill the warm-up sweep.
    """
    import jax.numpy as jnp

    from apex_tpu.utils.benchtime import measure_fetch_floor, timed_steps

    spec = registry.spec(kernel)
    if dtype is None:
        dtype = jnp.bfloat16
    dtype = jnp.dtype(dtype)
    if floor_s is None:
        floor_s = measure_fetch_floor()
    # the softmax-family heuristics are itemsize-dependent; derive it from
    # the ACTUAL dtype unless the workload pinned it, so the registry's
    # "default" candidate is exactly what the kernel call site would pick
    shape = dict(shape)
    shape.setdefault("itemsize", dtype.itemsize)
    # flat optimizers key dtype=None: one entry serves bf16 params, fp32
    # master weights, and every other element type (same row streaming)
    key_dtype = None if spec.dtype_agnostic else dtype
    defaults = spec.defaults(shape)
    cands = spec.candidates(shape)
    if max_candidates is not None:
        max_candidates = max(1, max_candidates)
    if max_candidates is not None and len(cands) > max_candidates:
        # keep the default in the truncated sweep: the heuristic must
        # always be allowed to win
        kept = cands[:max_candidates]
        if defaults not in kept:
            kept[-1] = defaults
        cands = kept

    rows: List[Dict[str, Any]] = []
    best: Optional[Dict[str, Any]] = None
    default_ms: Optional[float] = None
    for params in cands:
        row: Dict[str, Any] = {"params": dict(params)}
        try:
            t0 = time.perf_counter()
            step, state, consts = spec.build(shape, dtype, params,
                                             interpret=interpret)
            ms = timed_steps(step, state, iters=iters, consts=consts,
                             floor_s=floor_s, donate=False)
            row["ms"] = round(ms, 4)
            row["wall_s"] = round(time.perf_counter() - t0, 2)
        except Exception as e:  # VMEM blowout / Mosaic reject: skip
            row["error"] = f"{type(e).__name__}: {e}"
            rows.append(row)
            continue
        rows.append(row)
        if params == defaults:
            default_ms = row["ms"]
        if best is None or row["ms"] < best["ms"]:
            best = row

    # one allocator sample per kernel sweep (hbm_snapshot on the bus):
    # tuning is an AOT point — a candidate geometry that balloons HBM
    # shows up in the run's memory accounting, not just its timing.
    # Silent off-TPU (CPU backends report no allocator stats).
    from apex_tpu.monitor.memory import sample_device_memory

    sample_device_memory(f"tune:{kernel}", candidates=len(rows))

    result: Dict[str, Any] = {
        "kernel": kernel,
        "shape": dict(shape),
        "dtype": str(dtype.name),
        "device": device_key(),
        "default": defaults,
        "default_ms": default_ms,
        "candidates": rows,
    }
    if best is None:
        result["error"] = "no candidate completed"
        result["key"] = cache_key(kernel, spec.shape_key(shape), key_dtype,
                                  device_key(), code_version(kernel))
        publish_event("kernel_autotune_failed", kernel=kernel,
                      key=result["key"], emit=False)
        return result

    result["best"] = best["params"]
    result["best_ms"] = best["ms"]
    if default_ms and default_ms > 0:
        result["speedup_vs_default"] = round(default_ms / best["ms"], 3)
    result["key"] = record_tuned(
        kernel, spec.shape_key(shape), best["params"], dtype=key_dtype,
        meta={"ms": best["ms"], "default_ms": default_ms,
              "iters": iters, "shape": dict(shape)},
        save=save)
    return result


def warm_cache(workload: List[Dict[str, Any]], *, iters: int = 10,
               max_candidates: Optional[int] = None,
               interpret: Optional[bool] = None) -> List[Dict[str, Any]]:
    """Run :func:`autotune_kernel` for every ``{kernel, shape, dtype?}``
    entry of a workload spec; returns the result records. The cache file
    is saved after each kernel (a mid-sweep crash keeps earlier wins)."""
    results = []
    for entry in workload:
        results.append(autotune_kernel(
            entry["kernel"], entry["shape"], entry.get("dtype"),
            iters=iters, max_candidates=max_candidates,
            interpret=interpret, save=True))
    return results
