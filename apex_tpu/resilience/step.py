"""Overflow-resilient train-step wrapping (the compute-side guard rail).

The reference's hysteresis state machine (csrc/update_scale_hysteresis.cu,
ported as ``amp.DynamicGradScaler``) assumes overflows are occasional. An
overflow *storm* — bad data shard, diverging run, or an injected NaN burst —
makes every step non-finite: each one halves the scale, and within ~40 steps
the scale underflows to zero and every subsequent gradient silently flushes
to nothing. The loss curve goes flat and nobody is told why.

:class:`ResilientStep` composes with the scaler to fail loudly and degrade
gracefully instead:

- every non-finite step is **skipped** (parameters keep their old values —
  the jitted ``where`` keeps the whole flow on device);
- the scale never backs off below ``scale_floor``;
- after ``max_consecutive_overflows`` consecutive bad steps the wrapper
  enters degraded mode: scale growth is frozen and a single
  ``structured_warning`` (event ``overflow_storm``) is emitted for the
  monitoring pipeline. ``reset_degraded()`` re-arms growth once the cause
  is fixed.

The one host sync per step is a scalar ``found_inf`` fetch — the value the
loop needs anyway to count skips.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from apex_tpu.amp.grad_scaler import DynamicGradScaler, ScalerState
from apex_tpu.utils.logging import publish_event, structured_warning

DEFAULT_SCALE_FLOOR = 2.0 ** -14  # smallest normal bf16/fp16-safe scale


def skip_on_overflow(new_tree: Any, old_tree: Any, found_inf) -> Any:
    """Per-leaf ``where``: keep the old value when this step overflowed.
    Jit-safe; the apex 'skipped step' semantics for functional updates."""
    return jax.tree_util.tree_map(
        lambda new, old: jnp.where(found_inf, old, new), new_tree, old_tree)


class ResilientStep:
    """Wrap ``step_fn(params, sstate, *batch) -> (new_params, found_inf,
    *aux)`` with skip-on-overflow and storm degradation.

    Returns ``(params, sstate, found_inf, *aux)`` — params unchanged and
    scale backed off (never below ``scale_floor``) on overflow steps. Use
    via :func:`resilient_step` or directly::

        step = resilient_step(train_step, scaler)
        params, sstate, found_inf, loss = step(params, sstate, batch)
        if step.degraded: ...  # storm happened; growth is frozen

    With ``telemetry`` (an :class:`apex_tpu.monitor.Telemetry`), every call
    also collects a :class:`~apex_tpu.monitor.metrics.TrainMetrics` INSIDE
    the jitted post-step — param norm of the kept params, norm of the
    attempted update, overflow flag, post-update loss scale — and logs it
    (``aux[0]``, when present, is logged as ``loss``). Metric values stay
    on device; the only host traffic is the ``found_inf`` fetch the loop
    needs anyway. The latest collected pytree is also kept on
    ``self.last_metrics``.
    """

    def __init__(self, step_fn: Callable, scaler: DynamicGradScaler, *,
                 max_consecutive_overflows: int = 8,
                 scale_floor: float = DEFAULT_SCALE_FLOOR,
                 telemetry=None, tracer=None):
        self.step_fn = step_fn
        self.scaler = scaler
        self.telemetry = telemetry
        # span-tree tracing (monitor.trace): one trace per train step —
        # ``train_step`` root, ``forward_backward`` and
        # ``unscale_grad_norm`` children — so a step's phases line up
        # with the device trace and land in the flight recorder's ring
        self.tracer = tracer
        self.last_metrics = None
        self._step_index = 0
        self.max_consecutive_overflows = max_consecutive_overflows
        # the floor is applied in this wrapper's own (jitted) post-step, not
        # by mutating the caller's scaler — a scaler shared with another
        # loop keeps its configured backoff semantics. An explicit
        # scaler.min_scale still applies (the tighter of the two wins).
        self.scale_floor = scale_floor
        self.consecutive_overflows = 0
        self.skipped_steps = 0
        self.degraded = False
        # trace counter (the serve engine's decode_traces idiom): bumps as
        # a Python side effect each time jax TRACES _post, so a warm
        # restart that recompiles nothing keeps it flat — tier-1's
        # zero-recompile-restart proof for the trainer reads it
        self.post_traces = 0

        def _post(new_params, params, sstate, found_inf, *, freeze_growth,
                  with_metrics):
            self.post_traces += 1
            kept = skip_on_overflow(new_params, params, found_inf)
            sstate = self.scaler.update(sstate, found_inf,
                                        freeze_growth=freeze_growth)
            sstate = sstate._replace(
                scale=jnp.maximum(sstate.scale, jnp.float32(scale_floor)))
            tm = None
            if with_metrics:
                from apex_tpu.monitor.metrics import collect_metrics

                # update_norm is the ATTEMPTED update (pre-skip): on a
                # storm step it shows the non-finite/huge step that was
                # discarded, which is the diagnostic signal
                tm = collect_metrics(
                    params=kept,
                    updates=jax.tree_util.tree_map(
                        lambda n, o: n.astype(jnp.float32)
                        - o.astype(jnp.float32), new_params, params),
                    scaler_state=sstate, found_inf=found_inf)
            return kept, sstate, tm

        # one trace per (freeze_growth, with_metrics) value; everything but
        # the scalar found_inf fetch below stays on device
        self._post = jax.jit(
            _post, static_argnames=("freeze_growth", "with_metrics"))

    def _span(self, name: str, **attrs):
        """A tracer span, or a free nullcontext when tracing is off — the
        wrapped step pays one attribute check per phase, nothing more."""
        if self.tracer is not None and self.tracer.enabled:
            return self.tracer.span(name, **attrs)
        return contextlib.nullcontext()

    def __call__(self, params: Any, sstate: ScalerState, *batch):
        with self._span("train_step", step=self._step_index):
            return self._call(params, sstate, *batch)

    def _call(self, params: Any, sstate: ScalerState, *batch):
        with self._span("forward_backward"):
            new_params, found_inf, *aux = self.step_fn(params, sstate,
                                                       *batch)
        with_metrics = self.telemetry is not None
        with self._span("unscale_grad_norm"):
            params, sstate, tm = self._post(new_params, params, sstate,
                                            found_inf,
                                            freeze_growth=self.degraded,
                                            with_metrics=with_metrics)
        skipped = bool(found_inf)
        if with_metrics:
            self.last_metrics = tm
            self.telemetry.log_step(
                self._step_index, metrics=tm,
                loss=aux[0] if aux else None, skipped=skipped)
        self._step_index += 1
        if skipped:
            # bus-only (emit=False): per-step records must not spam stderr,
            # but the goodput ledger counts every discarded update
            publish_event("overflow_step_skipped",
                          consecutive=self.consecutive_overflows + 1)
            self.skipped_steps += 1
            self.consecutive_overflows += 1
            if (not self.degraded and self.consecutive_overflows
                    >= self.max_consecutive_overflows):
                self.degraded = True
                structured_warning(
                    "overflow_storm",
                    consecutive_overflows=self.consecutive_overflows,
                    scale=float(sstate.scale),
                    scale_floor=self.scale_floor,
                    action="loss-scale growth frozen; steps skipped until "
                           "gradients are finite")
        else:
            self.consecutive_overflows = 0
        return (params, sstate, found_inf, *aux)

    def reset_degraded(self) -> None:
        """Re-arm scale growth after the storm's cause is resolved."""
        if self.degraded:
            structured_warning("overflow_storm_cleared",
                               skipped_steps=self.skipped_steps)
        self.degraded = False
        self.consecutive_overflows = 0

    @property
    def stats(self) -> Dict[str, Any]:
        return {"skipped_steps": self.skipped_steps,
                "consecutive_overflows": self.consecutive_overflows,
                "degraded": self.degraded}


def resilient_step(step_fn: Callable, scaler: DynamicGradScaler,
                   **kwargs) -> ResilientStep:
    """Convenience constructor for :class:`ResilientStep` (see class doc)."""
    return ResilientStep(step_fn, scaler, **kwargs)
