"""Fault-tolerant step-numbered checkpointing (the durability layer).

The reference stack survives long runs on optimizer ``state_dict`` save/load;
this manager adds what a preemptible TPU slice actually needs on top of the
raw serializers in :mod:`apex_tpu.utils.checkpoint`:

- **Atomic commit** — every checkpoint is staged into ``<dir>.tmp`` and
  published with a single ``os.replace``. A kill at ANY point leaves either
  the previous committed set or an uncommitted ``.tmp`` that is garbage-
  collected on the next save; readers never observe a half-written step.
  (Re-saving an already-committed step swaps via rename — the old commit is
  never deleted before the new one lands; a kill between the two renames
  degrades that one step to its predecessor, nothing is ever half-gone.)
- **Manifest + checksums** — ``manifest.json`` records per-leaf shape,
  dtype, byte length, and CRC32 of the serialized bytes. ``restore``
  verifies all of it; silent corruption (bit rot, truncation that keeps a
  parseable npy header) is detected, not loaded.
- **Retention** — ``max_to_keep`` committed steps are kept; older steps and
  stale ``.tmp`` staging dirs are pruned best-effort after each commit.
- **Retry with backoff** — transient ``OSError`` (EIO on flaky NFS, brief
  ENOSPC) retries the whole staged write with exponential backoff before
  giving up; the commit point is still atomic per attempt.
- **restore_latest** — walks committed steps newest-first, validates each
  manifest, and transparently skips corrupt/partial checkpoints, resuming
  from the newest step that verifies. Skips are reported via
  ``structured_warning`` and the corrupt step is quarantined (renamed to
  ``<step>.corrupt`` with a ``checkpoint_quarantined`` event) so retention
  only counts steps that verify.

All filesystem access goes through a :class:`Filesystem` seam so the fault
harness (:mod:`apex_tpu.resilience.fault_injection`) can inject torn writes
and I/O errors deterministically. ``tools/check_durability.py`` statically
enforces that no checkpoint-writing code bypasses the ``.tmp`` +
``os.replace`` discipline.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from apex_tpu.utils.logging import (is_rank_zero, publish_event,
                                    structured_warning)

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1
_STEP_FMT = "step_{:08d}"
_STEP_RE = re.compile(r"^step_(\d{8})$")
_TMP_SUFFIX = ".tmp"
_OLD_SUFFIX = ".old"
CORRUPT_SUFFIX = ".corrupt"


class CheckpointError(RuntimeError):
    """Base class for checkpoint-manager failures."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint exists on disk but fails manifest/checksum validation."""


class CheckpointLayoutError(CheckpointCorruptError):
    """A checkpoint is valid but written in a layout this manager cannot
    assemble (dense vs. sharded). ``restore_latest`` skips it WITHOUT
    quarantining — the data is fine, the manager is wrong."""


class Filesystem:
    """Injectable filesystem seam. The manager performs every write through
    these methods, giving the fault harness one deterministic place to
    interpose torn writes, EIO/ENOSPC, and crash points."""

    def write_bytes(self, path: str, data: bytes) -> None:
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def rmtree(self, path: str) -> None:
        shutil.rmtree(path, ignore_errors=True)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def sync_dir(self, path: str) -> None:
        """fsync a directory so the rename itself is durable (best effort —
        not every platform/filesystem supports directory fds)."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)


LOCAL_FS = Filesystem()


def _leaf_bytes(leaf: Any) -> bytes:
    """Serialize one pytree leaf to .npy bytes (extension dtypes such as
    bfloat16 round-trip as raw void bytes, same as utils.checkpoint)."""
    buf = io.BytesIO()
    np.save(buf, np.asarray(leaf), allow_pickle=False)
    return buf.getvalue()


def _leaf_from_bytes(data: bytes, ref: Any) -> Any:
    arr = np.load(io.BytesIO(data), allow_pickle=False)
    if arr.dtype.kind == "V" and hasattr(ref, "dtype"):
        arr = arr.view(ref.dtype)
    return jax.numpy.asarray(arr)


class CheckpointManager:
    """Step-numbered checkpoints with atomic commit and validated restore.

    Layout under ``directory``::

        step_00000100/              # one committed checkpoint
            manifest.json           # step, per-leaf shape/dtype/crc32
            leaf_00000.npy ...      # one .npy per pytree leaf
        step_00000200.tmp/          # in-flight staging (never read)

    ``save(step, tree)`` stages everything into ``step_XXXXXXXX.tmp`` and
    publishes with one ``os.replace`` — the commit point. ``restore(step,
    like)`` validates the manifest and every leaf checksum before returning;
    ``restore_latest(like)`` additionally skips checkpoints that fail
    validation and returns the newest good ``(step, tree)``.
    """

    def __init__(self, directory: str, *, max_to_keep: Optional[int] = 3,
                 retries: int = 3, backoff_base: float = 0.1,
                 fs: Optional[Filesystem] = None, sleep=time.sleep,
                 quarantine_corrupt: bool = True):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.retries = retries
        self.backoff_base = backoff_base
        self.fs = fs or LOCAL_FS
        self._sleep = sleep
        self.quarantine_corrupt = quarantine_corrupt
        # restore observability (read by the trainer after restore_latest):
        # the layout block of the manifest that actually restored, and the
        # steps quarantined while walking to it
        self.last_restored_layout: Optional[Dict[str, Any]] = None
        self.last_quarantined: List[Dict[str, Any]] = []
        self._last_manifest: Optional[Dict[str, Any]] = None
        self.fs.makedirs(self.directory)

    def _is_rank0(self) -> bool:
        """Which process performs shared-directory mutations (quarantine,
        prune) and owns console announcements. The single-process manager
        asks jax; the sharded subclass asks its coordinator."""
        return is_rank_zero()

    # ---- paths ----------------------------------------------------------
    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, _STEP_FMT.format(step))

    def all_steps(self) -> List[int]:
        """Committed (published) steps, ascending. ``.tmp`` staging dirs are
        by construction never included."""
        steps = []
        for name in self.fs.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ---- save -----------------------------------------------------------
    def save(self, step: int, tree: Any, *,
             layout: Optional[Dict[str, Any]] = None) -> str:
        """Write checkpoint ``step`` atomically; returns the committed path.

        ``layout`` (optional) is the writer's topology block
        (:func:`apex_tpu.resilience.topology.layout_block`) — stamped into
        the manifest under ``"layout"`` with ``"storage": "dense"`` so a
        restore onto a different topology is observable. Omitted, the
        manifest stays byte-compatible with pre-layout checkpoints.

        Transient ``OSError`` retries up to ``retries`` times with
        exponential backoff (each attempt restages from scratch). Any other
        exception — including a simulated crash from the fault harness —
        propagates with the staging dir left uncommitted.
        """
        t_start = time.perf_counter()
        final = self.step_path(step)
        tmp = final + _TMP_SUFFIX
        leaves, _ = jax.tree_util.tree_flatten(tree)

        last_err: Optional[OSError] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self.backoff_base * (2.0 ** (attempt - 1))
                structured_warning(
                    "checkpoint_save_retry", step=int(step),
                    attempt=attempt, delay_s=delay, error=str(last_err))
                self._sleep(delay)
            try:
                self.fs.rmtree(tmp)
                self.fs.makedirs(tmp)
                # stream one leaf at a time: at most one serialized blob is
                # ever live on the host (a full-blob list would double the
                # checkpoint's RAM footprint for the whole retry loop)
                entries = []
                for i, leaf in enumerate(leaves):
                    blob = _leaf_bytes(leaf)
                    entry = {
                        "file": f"leaf_{i:05d}.npy",
                        "shape": list(np.asarray(leaf).shape),
                        "dtype": str(getattr(leaf, "dtype",
                                             np.asarray(leaf).dtype)),
                        "nbytes": len(blob),
                        "crc32": zlib.crc32(blob),
                        # blake2b of the BLOB bytes (crc32's cryptographic
                        # twin): the jax-free tools/ckpt_inspect.py verifies
                        # leaves against this without parsing npy
                        "blake2b": hashlib.blake2b(
                            blob, digest_size=16).hexdigest(),
                    }
                    self.fs.write_bytes(os.path.join(tmp, entry["file"]),
                                        blob)
                    entries.append(entry)
                manifest = {
                    "format_version": MANIFEST_VERSION,
                    "step": int(step),
                    "created": time.time(),
                    "num_leaves": len(leaves),
                    "leaves": entries,
                }
                if layout is not None:
                    manifest["layout"] = {"storage": "dense",
                                          **dict(layout)}
                # manifest last: its presence marks a fully staged set
                self.fs.write_bytes(os.path.join(tmp, MANIFEST_NAME),
                                    json.dumps(manifest, indent=1).encode())
                # re-saving an existing step: move the old commit aside with
                # a rename (never rmtree before the commit point — a crash
                # mid-delete would lose the committed step); the only
                # remaining window is between the two renames, and it
                # degrades to the previous step, not to data loss mid-tree
                old = final + _OLD_SUFFIX
                if self.fs.exists(final):
                    self.fs.rmtree(old)
                    self.fs.replace(final, old)
                self.fs.replace(tmp, final)  # commit point
                self.fs.sync_dir(self.directory)
                self.fs.rmtree(old)
                break
            except OSError as e:
                last_err = e
        else:
            raise CheckpointError(
                f"checkpoint save for step {step} failed after "
                f"{self.retries + 1} attempts: {last_err}") from last_err

        self._prune()
        # bus-only stall record: the goodput ledger (apex_tpu.monitor)
        # charges synchronous save time against run goodput
        publish_event("checkpoint_save_stall", step=int(step),
                      seconds=round(time.perf_counter() - t_start, 6))
        return final

    def _prune(self) -> None:
        """Best-effort retention: drop oldest committed steps beyond
        ``max_to_keep`` and any stale staging dirs from crashed saves."""
        try:
            names = self.fs.listdir(self.directory)
        except OSError:
            return
        for name in names:
            for suffix in (_TMP_SUFFIX, _OLD_SUFFIX):
                if name.endswith(suffix) and _STEP_RE.match(
                        name[:-len(suffix)]):
                    self.fs.rmtree(os.path.join(self.directory, name))
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        for s in steps[:max(0, len(steps) - self.max_to_keep)]:
            self.fs.rmtree(self.step_path(s))
        # quarantined (.corrupt) steps are kept for postmortem but bounded
        # by the same retention count — they no longer count against the
        # GOOD-step budget above, which is the whole point of quarantine
        corrupt = sorted(
            n for n in names if n.endswith(CORRUPT_SUFFIX)
            and _STEP_RE.match(n[:-len(CORRUPT_SUFFIX)]))
        for n in corrupt[:max(0, len(corrupt) - self.max_to_keep)]:
            self.fs.rmtree(os.path.join(self.directory, n))

    def _quarantine(self, step: int, reason: str) -> None:
        """Move a checkpoint that failed validation aside (``<step>.corrupt``)
        so retention only ever counts steps that verify, while the evidence
        stays on disk for postmortem. Rank 0 performs the rename (the
        directory is shared); every rank already skipped the step."""
        if not self.quarantine_corrupt or not self._is_rank0():
            return
        src = self.step_path(step)
        dst = src + CORRUPT_SUFFIX
        try:
            if not self.fs.exists(src):
                return  # already quarantined (or raced away)
            self.fs.rmtree(dst)
            self.fs.replace(src, dst)
        except OSError as e:
            structured_warning("checkpoint_quarantine_failed",
                               step=int(step), reason=str(e))
            return
        self.last_quarantined.append({"step": int(step), "path": dst,
                                      "reason": reason})
        structured_warning("checkpoint_quarantined", step=int(step),
                           path=dst, reason=reason)

    # ---- restore --------------------------------------------------------
    def validate(self, step: int,
                 _blobs: Optional[Dict[str, bytes]] = None) -> Dict[str, Any]:
        """Parse + verify the manifest and every leaf checksum for ``step``.
        Returns the manifest; raises :class:`CheckpointCorruptError` on any
        missing file, bad JSON, or checksum mismatch. ``_blobs`` (internal)
        collects the verified leaf bytes so :meth:`restore` deserializes
        exactly what was checksummed without a second read of every file."""
        path = self.step_path(step)
        mpath = os.path.join(path, MANIFEST_NAME)
        if not self.fs.exists(mpath):
            raise CheckpointCorruptError(f"{path}: missing {MANIFEST_NAME}")
        try:
            manifest = json.loads(self.fs.read_bytes(mpath))
        except (ValueError, OSError) as e:
            raise CheckpointCorruptError(f"{mpath}: unreadable manifest "
                                         f"({e})") from e
        if manifest.get("format_version") != MANIFEST_VERSION or \
                manifest.get("step") != step:
            raise CheckpointCorruptError(
                f"{mpath}: bad header (version="
                f"{manifest.get('format_version')}, "
                f"step={manifest.get('step')}, expected {step})")
        layout = manifest.get("layout")
        if layout is not None and not (isinstance(layout, dict)
                                       and layout.get("storage")
                                       == "dense"):
            # a sharded (or future-layout) step: not corrupt, but this
            # manager cannot assemble it — fail validation cleanly rather
            # than KeyError mid-restore. A dict with storage="dense" is
            # this manager's own topology stamp; anything else belongs to
            # another manager.
            raise CheckpointLayoutError(
                f"{mpath}: layout {manifest['layout']!r} requires the "
                f"matching manager (ShardedCheckpointManager)")
        leaves = manifest.get("leaves")
        if not isinstance(leaves, list) or \
                len(leaves) != manifest.get("num_leaves"):
            raise CheckpointCorruptError(f"{mpath}: leaf table truncated")
        for entry in leaves:
            fpath = os.path.join(path, entry["file"])
            if not self.fs.exists(fpath):
                raise CheckpointCorruptError(f"{fpath}: missing leaf file")
            data = self.fs.read_bytes(fpath)
            if len(data) != entry["nbytes"] or \
                    zlib.crc32(data) != entry["crc32"]:
                raise CheckpointCorruptError(
                    f"{fpath}: checksum mismatch (torn or corrupt write)")
            if "blake2b" in entry and hashlib.blake2b(
                    data, digest_size=16).hexdigest() != entry["blake2b"]:
                raise CheckpointCorruptError(
                    f"{fpath}: blake2b digest mismatch (crc collision or "
                    f"manifest tamper)")
            if _blobs is not None:
                _blobs[entry["file"]] = data
        self._last_manifest = manifest
        return manifest

    def restore(self, step: int, like: Any) -> Any:
        """Validated restore of ``step`` into the structure of ``like``."""
        blobs: Dict[str, bytes] = {}
        manifest = self.validate(step, _blobs=blobs)
        path = self.step_path(step)
        refs, treedef = jax.tree_util.tree_flatten(like)
        if len(refs) != manifest["num_leaves"]:
            raise CheckpointCorruptError(
                f"{path}: has {manifest['num_leaves']} leaves, restore "
                f"target has {len(refs)}")
        out = [
            _leaf_from_bytes(blobs.pop(entry["file"]), ref)
            for entry, ref in zip(manifest["leaves"], refs)]
        return jax.tree_util.tree_unflatten(treedef, out)

    def restore_latest(self, like: Any) -> Optional[Tuple[int, Any]]:
        """Restore the newest checkpoint that passes validation.

        Corrupt or partial steps (torn write that still got committed, bit
        rot, truncated manifest) are skipped with a ``structured_warning``
        and — unless ``quarantine_corrupt=False`` — renamed to
        ``<step>.corrupt`` (with a ``checkpoint_quarantined`` event) so they
        stop counting toward ``max_to_keep`` retention: without the rename a
        run accumulating corrupt steps would silently rotate its *good*
        checkpoints out while keeping the bad ones. Returns ``(step, tree)``
        or ``None`` when no valid checkpoint exists.
        """
        t_start = time.perf_counter()
        self.last_restored_layout = None
        self.last_quarantined = []
        for step in reversed(self.all_steps()):
            try:
                out = step, self.restore(step, like)
                layout = (self._last_manifest or {}).get("layout")
                self.last_restored_layout = (dict(layout)
                                             if isinstance(layout, dict)
                                             else None)
                publish_event(
                    "checkpoint_restore_stall", step=int(step),
                    seconds=round(time.perf_counter() - t_start, 6))
                return out
            except CheckpointCorruptError as e:
                structured_warning("checkpoint_skipped_corrupt",
                                   step=step, reason=str(e))
                # layout mismatches skip but never quarantine: the step is
                # valid data under the OTHER manager, not damage
                if not isinstance(e, CheckpointLayoutError):
                    self._quarantine(step, reason=str(e))
        return None
