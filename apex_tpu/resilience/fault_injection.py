"""Deterministic fault injection for resilience testing.

Production failure modes, reproduced on a laptop with a seed:

- **Filesystem faults** — interpose on the :class:`Filesystem` seam of
  :class:`~apex_tpu.resilience.checkpoint_manager.CheckpointManager`:
  ``fail_write(nth)`` raises ``OSError`` (EIO/ENOSPC) on the Nth write
  call, ``torn_write(nth)`` writes a prefix of the bytes and then raises
  :class:`SimulatedCrash` — exactly what a power cut or preemption leaves
  on disk mid-save.
- **Preemption** — ``fire_preemption()`` delivers a real SIGTERM to this
  process so :class:`~apex_tpu.resilience.preemption.PreemptionGuard` runs
  the same code path the scheduler triggers.
- **NaN/Inf gradient bursts** — ``nan_burst(start, length)`` schedules a
  window of steps whose gradients ``poison_grads`` fills with NaN/Inf
  (choice seeded), reproducing the overflow storms that collapse a dynamic
  loss scale.

Everything is deterministic: the same seed + schedule produces the same
faults on the same call sequence, so a failing resilience test replays
bit-for-bit.
"""

from __future__ import annotations

import errno
import os
import random
import signal
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.resilience.checkpoint_manager import Filesystem


class SimulatedCrash(RuntimeError):
    """Raised by an injected torn write: models the process dying mid-save.

    Tests catch this where a real run would simply be gone; everything the
    'dead' process left on disk is the state under test.
    """


class _WriteFault:
    def __init__(self, kind: str, err: int = errno.EIO,
                 fraction: float = 0.5):
        self.kind = kind  # "error" | "torn"
        self.err = err
        self.fraction = fraction


class _InjectedFilesystem(Filesystem):
    """Filesystem that consults the injector's fault schedule on each
    write. Reads and directory ops pass through untouched — faults target
    the durability path."""

    def __init__(self, injector: "FaultInjector"):
        self._injector = injector

    def write_bytes(self, path: str, data: bytes) -> None:
        fault = self._injector._next_write_fault()
        if fault is None:
            return super().write_bytes(path, data)
        if fault.kind == "error":
            raise OSError(fault.err, os.strerror(fault.err), path)
        # torn write: a prefix reaches the disk, then the process "dies"
        keep = int(len(data) * fault.fraction)
        super().write_bytes(path, data[:keep])
        raise SimulatedCrash(
            f"torn write: {keep}/{len(data)} bytes of {path}")


class FaultInjector:
    """Seeded, scheduled fault source for the resilience test harness."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._write_calls = 0
        self._write_faults: Dict[int, _WriteFault] = {}
        self._bursts: List[Tuple[int, int]] = []

    # ---- filesystem faults ---------------------------------------------
    def filesystem(self) -> Filesystem:
        """A Filesystem to hand to CheckpointManager(fs=...)."""
        return _InjectedFilesystem(self)

    def fail_write(self, nth: int, err: int = errno.EIO,
                   count: int = 1) -> "FaultInjector":
        """Raise ``OSError(err)`` on write calls ``nth .. nth+count-1``
        (1-based, counted across the injected filesystem's lifetime)."""
        for n in range(nth, nth + count):
            self._write_faults[n] = _WriteFault("error", err=err)
        return self

    def torn_write(self, nth: int, fraction: float = 0.5) -> "FaultInjector":
        """On the Nth write, persist only ``fraction`` of the bytes and
        raise :class:`SimulatedCrash`."""
        self._write_faults[nth] = _WriteFault("torn", fraction=fraction)
        return self

    @property
    def write_calls(self) -> int:
        return self._write_calls

    def _next_write_fault(self) -> Optional[_WriteFault]:
        self._write_calls += 1
        return self._write_faults.pop(self._write_calls, None)

    # ---- preemption -----------------------------------------------------
    def fire_preemption(self, sig: int = signal.SIGTERM) -> None:
        """Deliver a real signal to this process (handled at the next
        bytecode boundary of the main thread — same path as the scheduler's
        SIGTERM)."""
        os.kill(os.getpid(), sig)

    # ---- gradient corruption -------------------------------------------
    def nan_burst(self, start: int, length: int) -> "FaultInjector":
        """Schedule steps ``start .. start+length-1`` to produce non-finite
        gradients via :meth:`poison_grads`."""
        self._bursts.append((start, length))
        return self

    def grads_faulty(self, step: int) -> bool:
        return any(s <= step < s + n for s, n in self._bursts)

    def poison_grads(self, grads: Any, step: int) -> Any:
        """Return ``grads`` with every leaf filled with NaN or Inf when
        ``step`` falls in a scheduled burst (seeded choice), else
        unchanged."""
        if not self.grads_faulty(step):
            return grads
        bad = jnp.nan if self.rng.random() < 0.5 else jnp.inf
        return jax.tree_util.tree_map(
            lambda g: jnp.full_like(g, bad), grads)
