"""Deterministic fault injection for resilience testing.

Production failure modes, reproduced on a laptop with a seed:

- **Filesystem faults** — interpose on the :class:`Filesystem` seam of
  :class:`~apex_tpu.resilience.checkpoint_manager.CheckpointManager`:
  ``fail_write(nth)`` raises ``OSError`` (EIO/ENOSPC) on the Nth write
  call, ``torn_write(nth)`` writes a prefix of the bytes and then raises
  :class:`SimulatedCrash` — exactly what a power cut or preemption leaves
  on disk mid-save.
- **Preemption** — ``fire_preemption()`` delivers a real SIGTERM to this
  process so :class:`~apex_tpu.resilience.preemption.PreemptionGuard` runs
  the same code path the scheduler triggers.
- **Distributed scenarios** — ``crash_on_write(pattern)`` kills the
  "process" the moment it touches a matching path (death between the
  per-process shard commit and the global-manifest publish = pattern on
  the global manifest), ``crash_on_replace(pattern)`` dies just before the
  atomic publish itself, ``drop_write(pattern)`` silently loses a shard
  file's bytes, ``straggler(rank, delay_s)`` delays one fake process's
  barrier arrival (what a hung host looks like to the collective
  watchdog), and ``lose_shard``/``duplicate_shard`` corrupt a *committed*
  sharded checkpoint in place.
- **Serving aborts** — ``abort_request(request_id, at_step)`` schedules a
  mid-stream request cancellation that the serve scheduler
  (:class:`~apex_tpu.serve.scheduler.ServeScheduler`) consumes before the
  given decode step — a client disconnect at a replayable point.
- **Serving chaos** — ``crash_on_decode_step(at_step)`` raises
  :class:`SimulatedCrash` the instant the scheduler would issue that
  decode step (a fatal XLA/runtime error mid-tick — the warm-restart
  path's trigger), ``latency_spike(at_step, seconds)`` stalls one tick
  (a straggling device, a host hiccup — what drives deadline expiry
  deterministically), and ``queue_storm(at_step, count, ...)`` injects a
  seeded burst of synthetic requests through the normal submit path (the
  admission-control/load-shedding workload). The tier-1 chaos suite runs
  all three under one schedule and asserts every submitted request
  reaches exactly one terminal status.
- **Fleet chaos** — replica-level failures for the serving fleet
  (:mod:`apex_tpu.serve.fleet`): ``kill_replica(rid, at_tick)`` raises
  :class:`SimulatedCrash` inside the replica's worker loop (the process
  is gone — heartbeats stop, the registry sweep escalates, the router
  re-dispatches), ``partition_replica(rid, at_tick, ticks)`` drops the
  replica's heartbeats AND result channel for a tick window while it
  keeps decoding (the router must not double-complete when the
  partition heals — ``heal_replica`` ends the window), and
  ``straggler_replica(rid, delay_s, at_tick, ticks)`` stalls each of
  its ticks (what drives hedged dispatch deterministically). The tier-1
  fleet smoke runs kill + partition + straggler in one seeded schedule
  and asserts every submitted request reaches exactly one terminal
  status fleet-wide.
- **Disaggregation chaos** — faults on the prefill→decode KV page
  handoff (:mod:`apex_tpu.serve.disagg`):
  ``kill_prefill_replica(rid, at_tick)`` kills a prefill replica with
  handoffs possibly in flight (abandoned handoffs fall back to local
  re-prefill, bit-exactly), ``corrupt_page_in_flight(nth)`` flips one
  bit in the nth migrated page transfer (the receiver's digest
  certification must refuse it — ``serve_handoff_refused`` — never
  decode from it), and ``stall_handoff(delay_s, at_handoff)`` defers
  one handoff's delivery (a slow interconnect; charges
  ``serve_handoff_wait``). The tier-1 disaggregation smoke mixes all
  three in one seeded schedule and asserts greedy completions
  bit-identical to a no-fault unified fleet.
- **Trainer chaos** — step-level failure for the production trainer
  (:mod:`apex_tpu.train`): ``crash_on_train_step(at_step)`` raises
  :class:`SimulatedCrash` the instant a rank would run that train step
  (a fatal XLA/runtime error mid-step — the supervisor's warm-restart
  trigger; ``times > 1`` re-fires after each rollback, driving the
  restart budget), ``crash_during_checkpoint_save(step)`` kills the
  process on its first write into that step's ``.tmp`` staging (a
  preemption landing mid-save — the previous committed step must stay
  restorable), ``preempt_at_step(at_step, rank)`` feeds one rank's
  :class:`~apex_tpu.resilience.preemption.PreemptionGuard` through the
  programmatic ``request_stop`` path (the coordinated-drain workload),
  and ``straggler_rank(rank, delay_s, at_step)`` stalls one rank's step
  window (what the collective watchdog must surface on the gradient
  exchange). The tier-1 chaos smoke mixes all of them in one seeded
  schedule and asserts bit-identical final params vs an uninterrupted
  run.
- **Checkpoint storage rot** — ``corrupt_checkpoint_blob(step, leaf)``
  flips one bit in a COMMITTED step's leaf blob at read time (bit rot
  discovered on restore, not a torn write): the manager's checksum
  verification must quarantine exactly that step and fall back to the
  last good one bit-exactly, while a torn/unparseable manifest is
  refused loudly — never silently read around.
- **NaN/Inf gradient bursts** — ``nan_burst(start, length)`` schedules a
  window of steps whose gradients ``poison_grads`` fills with NaN/Inf
  (choice seeded), reproducing the overflow storms that collapse a dynamic
  loss scale.

Everything is deterministic: the same seed + schedule produces the same
faults on the same call sequence, so a failing resilience test replays
bit-for-bit.
"""

from __future__ import annotations

import errno
import os
import random
import re
import shutil
import signal
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.resilience.checkpoint_manager import Filesystem


class SimulatedCrash(RuntimeError):
    """Raised by an injected torn write: models the process dying mid-save.

    Tests catch this where a real run would simply be gone; everything the
    'dead' process left on disk is the state under test.
    """


class _WriteFault:
    def __init__(self, kind: str, err: int = errno.EIO,
                 fraction: float = 0.5):
        self.kind = kind  # "error" | "torn"
        self.err = err
        self.fraction = fraction


class _InjectedFilesystem(Filesystem):
    """Filesystem that consults the injector's fault schedule on each
    write — and, for scheduled blob rot, on reads of committed leaf
    files (every other read and all directory ops pass through
    untouched)."""

    def __init__(self, injector: "FaultInjector"):
        self._injector = injector

    def read_bytes(self, path: str) -> bytes:
        return self._injector._maybe_corrupt_blob(
            path, super().read_bytes(path))

    def write_bytes(self, path: str, data: bytes) -> None:
        inj = self._injector
        fault = inj._next_write_fault()
        if fault is not None:
            if fault.kind == "error":
                raise OSError(fault.err, os.strerror(fault.err), path)
            # torn write: a prefix reaches the disk, then the process "dies"
            keep = int(len(data) * fault.fraction)
            super().write_bytes(path, data[:keep])
            raise SimulatedCrash(
                f"torn write: {keep}/{len(data)} bytes of {path}")
        if inj._ckpt_crash_due(path):
            # trainer chaos: the process dies on its first write into the
            # scheduled step's .tmp staging — a preemption mid-save; the
            # previous committed step must remain the restore target
            raise SimulatedCrash(
                f"process died mid-checkpoint-save writing {path}")
        if inj._matches(inj._crash_write_patterns, path):
            # the process dies the instant it reaches this file — nothing
            # of it lands on disk (e.g. between the per-process shard
            # commit and the global-manifest publish)
            raise SimulatedCrash(f"process died before writing {path}")
        if inj._matches(inj._drop_write_patterns, path):
            return  # the bytes silently vanish: a lost shard file
        super().write_bytes(path, data)

    def replace(self, src: str, dst: str) -> None:
        if self._injector._matches(self._injector._crash_replace_patterns,
                                   dst):
            # death at the commit point itself: staging is complete but the
            # atomic publish never happened
            raise SimulatedCrash(f"process died before replace -> {dst}")
        super().replace(src, dst)


class FaultInjector:
    """Seeded, scheduled fault source for the resilience test harness."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._write_calls = 0
        self._write_faults: Dict[int, _WriteFault] = {}
        self._bursts: List[Tuple[int, int]] = []
        self._crash_write_patterns: List[re.Pattern] = []
        self._drop_write_patterns: List[re.Pattern] = []
        self._crash_replace_patterns: List[re.Pattern] = []
        self._stragglers: List[List[Any]] = []  # [rank, name|None, delay_s]
        self._serve_aborts: Dict[int, List[Any]] = {}  # step -> request ids
        self._decode_crashes: Dict[int, int] = {}      # step -> remaining
        self._latency_spikes: Dict[int, float] = {}    # step -> seconds
        self._storms: Dict[int, List[Dict[str, Any]]] = {}  # step -> specs
        self._storm_serial = 0
        # fleet chaos: replica id -> schedule (worker-loop tick units)
        self._replica_kills: Dict[str, int] = {}
        self._partitions: Dict[str, List[int]] = {}    # [start, end)
        self._replica_straggles: Dict[str, List[float]] = {}
        # disaggregation chaos (page-transfer / handoff ordinals, 1-based
        # across the fleet's lifetime)
        self._page_corruptions: set = set()            # nth migrated page
        self._page_transfer_count = 0
        self._handoff_stalls: Dict[int, float] = {}    # nth handoff -> s
        self._handoff_count = 0
        # trainer chaos (train-step units / checkpoint step numbers)
        self._train_crashes: Dict[int, int] = {}       # step -> remaining
        self._ckpt_crash_steps: set = set()            # checkpoint steps
        self._train_preempts: List[List[int]] = []     # [rank, at_step]
        self._rank_straggles: Dict[int, List[float]] = {}  # rank -> window
        self._blob_corruptions: set = set()            # (step, leaf index)

    # ---- filesystem faults ---------------------------------------------
    def filesystem(self) -> Filesystem:
        """A Filesystem to hand to CheckpointManager(fs=...)."""
        return _InjectedFilesystem(self)

    def fail_write(self, nth: int, err: int = errno.EIO,
                   count: int = 1) -> "FaultInjector":
        """Raise ``OSError(err)`` on write calls ``nth .. nth+count-1``
        (1-based, counted across the injected filesystem's lifetime)."""
        for n in range(nth, nth + count):
            self._write_faults[n] = _WriteFault("error", err=err)
        return self

    def torn_write(self, nth: int, fraction: float = 0.5) -> "FaultInjector":
        """On the Nth write, persist only ``fraction`` of the bytes and
        raise :class:`SimulatedCrash`."""
        self._write_faults[nth] = _WriteFault("torn", fraction=fraction)
        return self

    @property
    def write_calls(self) -> int:
        return self._write_calls

    def _next_write_fault(self) -> Optional[_WriteFault]:
        self._write_calls += 1
        return self._write_faults.pop(self._write_calls, None)

    @staticmethod
    def _matches(patterns: List[re.Pattern], path: str) -> bool:
        # match against a normalized path so patterns work across platforms
        norm = path.replace(os.sep, "/")
        return any(p.search(norm) for p in patterns)

    # ---- distributed: crash points --------------------------------------
    def crash_on_write(self, pattern: str) -> "FaultInjector":
        """Raise :class:`SimulatedCrash` the moment a write targets a path
        matching ``pattern`` (regex, ``/``-normalized) — nothing of that
        file reaches disk. With the sharded manager, ``r"/manifest\\.json$"``
        is exactly "the process died after committing its own shards but
        before the global-manifest publish"."""
        self._crash_write_patterns.append(re.compile(pattern))
        return self

    def crash_on_replace(self, pattern: str) -> "FaultInjector":
        """Raise :class:`SimulatedCrash` just before an ``os.replace`` whose
        destination matches ``pattern`` — death at the commit point with a
        fully staged ``.tmp`` on disk."""
        self._crash_replace_patterns.append(re.compile(pattern))
        return self

    def drop_write(self, pattern: str) -> "FaultInjector":
        """Silently discard writes to paths matching ``pattern`` — the
        caller believes the shard landed; restore finds it missing."""
        self._drop_write_patterns.append(re.compile(pattern))
        return self

    # ---- distributed: stragglers ----------------------------------------
    def straggler(self, rank: int, delay_s: float,
                  name: Optional[str] = None) -> "FaultInjector":
        """Delay fake-process ``rank``'s next barrier arrival by
        ``delay_s`` (optionally only a barrier whose name contains
        ``name``) — the stuck-host signature the collective watchdog must
        surface. One-shot: each scheduled delay fires once."""
        self._stragglers.append([rank, name, delay_s])
        return self

    def barrier_delay(self, rank: int, name: str = "") -> float:
        """Consumed by coordinator barriers: seconds this rank should lag
        behind its peers before arriving at ``name``."""
        for ent in self._stragglers:
            if ent[0] == rank and (ent[1] is None or ent[1] in name):
                self._stragglers.remove(ent)
                return float(ent[2])
        return 0.0

    # ---- distributed: committed-checkpoint damage -----------------------
    @staticmethod
    def _shard_files(ckpt_dir: str, match: str) -> List[str]:
        names = sorted(n for n in os.listdir(ckpt_dir)
                       if re.search(match, n) and not n.endswith(".json"))
        return [os.path.join(ckpt_dir, n) for n in names]

    def lose_shard(self, ckpt_dir: str, match: str = r"leaf_") -> str:
        """Delete one committed shard file (bit-rot/eviction after commit).
        Returns the removed path; restore must detect the gap."""
        files = self._shard_files(ckpt_dir, match)
        if not files:
            raise ValueError(f"no shard files matching {match!r} in "
                             f"{ckpt_dir}")
        victim = files[self.rng.randrange(len(files))]
        os.remove(victim)
        return victim

    def duplicate_shard(self, ckpt_dir: str,
                        match: str = r"leaf_") -> Tuple[str, str]:
        """Overwrite one shard file with a *different* shard's bytes (a
        misdirected retry / duplicated object) — same file present, wrong
        content. Returns ``(src, clobbered)``; the checksum must catch it.
        """
        files = self._shard_files(ckpt_dir, match)
        if len(files) < 2:
            raise ValueError(f"need >= 2 shard files matching {match!r} in "
                             f"{ckpt_dir}")
        i = self.rng.randrange(len(files) - 1)
        src, dst = files[i], files[i + 1]
        shutil.copyfile(src, dst)
        return src, dst

    # ---- serving: scripted mid-stream aborts ----------------------------
    def abort_request(self, request_id: Any, at_step: int
                      ) -> "FaultInjector":
        """Schedule a serving-request abort: the
        :class:`~apex_tpu.serve.scheduler.ServeScheduler` polls
        :meth:`serve_aborts_due` before decode step ``at_step`` and
        aborts the request — a client disconnect / cancellation at an
        exact, replayable point in the decode stream. Tier-1 uses this to
        prove the other slots' outputs are bit-identical with and without
        the abort."""
        self._serve_aborts.setdefault(int(at_step), []).append(request_id)
        return self

    def serve_aborts_due(self, step: int) -> List[Any]:
        """Request ids scheduled to abort before decode step ``step``
        (consumed: each schedule fires once)."""
        return self._serve_aborts.pop(int(step), [])

    # ---- serving: decode crashes / latency spikes / queue storms --------
    def crash_on_decode_step(self, at_step: int,
                             times: int = 1) -> "FaultInjector":
        """Raise :class:`SimulatedCrash` when the scheduler issues the
        decode step after ``at_step`` completed steps — a fatal XLA or
        runtime error inside the jitted step, at an exact replayable
        tick. ``times > 1`` re-fires on the same tick after each warm
        restart (the snapshot rolls ``decode_steps`` back, so the
        recovered scheduler reaches the same count again) — how the
        restart-budget-exhaustion path is driven."""
        self._decode_crashes[int(at_step)] = \
            self._decode_crashes.get(int(at_step), 0) + max(1, int(times))
        return self

    def maybe_crash_decode(self, step: int) -> None:
        """Consumed by the scheduler just before the decode call; raises
        when a crash is scheduled for ``step`` (each scheduled firing
        consumed exactly once)."""
        left = self._decode_crashes.get(int(step), 0)
        if left <= 0:
            return
        if left == 1:
            self._decode_crashes.pop(int(step), None)
        else:
            self._decode_crashes[int(step)] = left - 1
        raise SimulatedCrash(
            f"injected fatal decode-step error at step {step}")

    def latency_spike(self, at_step: int,
                      seconds: float) -> "FaultInjector":
        """Stall the decode tick after ``at_step`` completed steps by
        ``seconds`` (host sleep before the compiled call) — a straggling
        device or host hiccup; the deterministic way to push a request
        past its ``deadline_ms``. One-shot."""
        self._latency_spikes[int(at_step)] = float(seconds)
        return self

    def latency_spike_due(self, step: int) -> float:
        """Seconds the scheduler should stall this tick (consumed)."""
        return self._latency_spikes.pop(int(step), 0.0)

    def queue_storm(self, at_step: int, count: int, *,
                    prompt_len: int = 6, vocab: int = 97,
                    max_new_tokens: int = 4,
                    deadline_ms: Optional[float] = None,
                    priority: int = 0) -> "FaultInjector":
        """Schedule a burst of ``count`` synthetic requests (seeded token
        content, ids ``storm-<n>``) that the scheduler submits through
        its NORMAL admission path before the given decode step — the
        workload that drives bounded-queue rejection, shed policies, and
        degraded mode, deterministically."""
        specs = self._storms.setdefault(int(at_step), [])
        for _ in range(int(count)):
            spec: Dict[str, Any] = {
                "request_id": f"storm-{self._storm_serial}",
                "tokens": [self.rng.randrange(int(vocab))
                           for _ in range(int(prompt_len))],
                "max_new_tokens": int(max_new_tokens),
                "priority": int(priority),
            }
            if deadline_ms is not None:
                spec["deadline_ms"] = float(deadline_ms)
            specs.append(spec)
            self._storm_serial += 1
        return self

    def serve_storm_due(self, step: int) -> List[Dict[str, Any]]:
        """Request-constructor kwargs for the burst scheduled before
        decode step ``step`` (consumed)."""
        return self._storms.pop(int(step), [])

    # ---- serving fleet: replica-level chaos -----------------------------
    def kill_replica(self, replica_id: Any,
                     at_tick: int = 1) -> "FaultInjector":
        """Kill a fleet replica's worker at its ``at_tick``-th loop tick:
        :class:`SimulatedCrash` inside the worker — heartbeats stop, the
        registry sweep escalates suspect → dead, and the router fails
        the replica's live requests over to survivors. One-shot."""
        self._replica_kills[str(replica_id)] = int(at_tick)
        return self

    def replica_kill_due(self, replica_id: Any, tick: int) -> bool:
        """Consumed by the replica worker loop each tick."""
        at = self._replica_kills.get(str(replica_id))
        if at is not None and tick >= at:
            del self._replica_kills[str(replica_id)]
            return True
        return False

    def partition_replica(self, replica_id: Any, at_tick: int = 1,
                          ticks: int = 10**9) -> "FaultInjector":
        """Network-partition a replica for a window of worker-loop
        ticks: heartbeats are dropped AND results stop crossing to the
        router, but the replica keeps decoding — the router declares it
        dead and re-dispatches, and when the partition heals (the window
        ends, or :meth:`heal_replica`) its duplicate completions must
        lose the first-terminal-wins race, never double-complete."""
        self._partitions[str(replica_id)] = [int(at_tick),
                                             int(at_tick) + int(ticks)]
        return self

    def replica_partitioned(self, replica_id: Any, tick: int) -> bool:
        """Window check (NOT consumed) — the worker evaluates it every
        tick so the partition ends exactly when the window does."""
        win = self._partitions.get(str(replica_id))
        return bool(win and win[0] <= tick < win[1])

    def heal_replica(self, replica_id: Any) -> "FaultInjector":
        """End a replica's partition window now (the heal the
        no-double-complete test drives explicitly)."""
        self._partitions.pop(str(replica_id), None)
        return self

    def straggler_replica(self, replica_id: Any, delay_s: float,
                          at_tick: int = 1,
                          ticks: int = 1) -> "FaultInjector":
        """Stall each of a replica's worker ticks in ``[at_tick,
        at_tick + ticks)`` by ``delay_s`` — a slow host/device that is
        alive but late: the deterministic way to make the router's
        hedged dispatch fire."""
        self._replica_straggles[str(replica_id)] = [
            float(at_tick), float(at_tick) + float(ticks),
            float(delay_s)]
        return self

    def replica_straggle_due(self, replica_id: Any, tick: int) -> float:
        """Seconds this replica's worker should stall this tick."""
        ent = self._replica_straggles.get(str(replica_id))
        if ent and ent[0] <= tick < ent[1]:
            return ent[2]
        return 0.0

    # ---- disaggregated serving: handoff chaos ---------------------------
    def kill_prefill_replica(self, replica_id: Any,
                             at_tick: int = 1) -> "FaultInjector":
        """Kill a PREFILL replica's worker at its ``at_tick``-th tick —
        the disaggregation death scenario: prompts it already committed
        may be mid-handoff (the controller abandons them and the decode
        replica re-prefills locally, bit-exactly), and prompts it never
        reached dispatch without pages. Mechanically the same one-shot
        as :meth:`kill_replica`; the dedicated name keeps chaos
        schedules self-describing."""
        return self.kill_replica(replica_id, at_tick)

    def corrupt_page_in_flight(self, nth: int = 1,
                               count: int = 1) -> "FaultInjector":
        """Flip one bit in migrated KV page transfers ``nth ..
        nth+count-1`` (1-based, counted across every handoff the fleet
        delivers). The receiver's payload-digest certification must
        refuse the page (``serve_handoff_refused``) and the request must
        complete bit-exactly via local re-prefill — never decode from
        the corrupted bytes."""
        for n in range(int(nth), int(nth) + int(count)):
            self._page_corruptions.add(n)
        return self

    def page_corrupt_due(self) -> bool:
        """Consumed by the disaggregation controller once per page
        transfer, in delivery order: True when THIS transfer should be
        corrupted in flight."""
        self._page_transfer_count += 1
        if self._page_transfer_count in self._page_corruptions:
            self._page_corruptions.discard(self._page_transfer_count)
            return True
        return False

    def stall_handoff(self, delay_s: float,
                      at_handoff: int = 1) -> "FaultInjector":
        """Delay delivery of the ``at_handoff``-th committed handoff
        (1-based, fleet lifetime order) by ``delay_s`` — a slow
        interconnect between the prefill and decode pools. The
        controller defers delivery (no sleep — the stall charges
        ``serve_handoff_wait``, it must not wedge the control thread),
        and a stalled handoff racing a drain or a death must still
        settle exactly once."""
        self._handoff_stalls[int(at_handoff)] = float(delay_s)
        return self

    def handoff_stall_due(self) -> float:
        """Consumed by the disaggregation controller once per committed
        handoff, in commit order: seconds this handoff's delivery should
        be deferred (0.0 = deliver on the next pump)."""
        self._handoff_count += 1
        return self._handoff_stalls.pop(self._handoff_count, 0.0)

    # ---- trainer chaos --------------------------------------------------
    def crash_on_train_step(self, at_step: int,
                            times: int = 1) -> "FaultInjector":
        """Raise :class:`SimulatedCrash` when a trainer rank would run
        train step ``at_step`` — a fatal XLA/runtime error mid-step, at an
        exact replayable point. ``times > 1`` re-fires after each warm
        restart (the checkpoint rollback makes the trainer reach the same
        step again) — how the restart-budget-exhaustion path is driven."""
        self._train_crashes[int(at_step)] = \
            self._train_crashes.get(int(at_step), 0) + max(1, int(times))
        return self

    def maybe_crash_train(self, step: int, rank: int = 0) -> None:
        """Consulted by every trainer rank just before the step runs;
        raises on all ranks while a firing is scheduled for ``step``.
        Only rank 0's call consumes the firing — one scheduled crash is
        one job-attempt failure, however many rank threads reach the
        step before the group aborts (per-rank consumption would burn
        ``times > 1`` budgets world-times faster, and a single rank
        decrementing also keeps the bookkeeping race-free)."""
        left = self._train_crashes.get(int(step), 0)
        if left <= 0:
            return
        if int(rank) == 0:
            if left == 1:
                self._train_crashes.pop(int(step), None)
            else:
                self._train_crashes[int(step)] = left - 1
        raise SimulatedCrash(
            f"injected fatal train-step error at step {step} "
            f"(rank {rank})")

    def crash_during_checkpoint_save(self, step: int) -> "FaultInjector":
        """Kill the process on its first write into checkpoint ``step``'s
        ``.tmp`` staging directory (the trainer must run with
        ``fs=injector.filesystem()``) — a preemption landing mid-save.
        The atomic-commit discipline means the previous committed step
        stays fully restorable; the retried save (the schedule is
        consumed) then commits cleanly. Keyed by the step being saved, so
        the schedule is deterministic regardless of save cadence."""
        self._ckpt_crash_steps.add(int(step))
        return self

    def _ckpt_crash_due(self, path: str) -> bool:
        """Consumed by the injected filesystem on every write (one firing
        per scheduled step)."""
        if not self._ckpt_crash_steps:
            return False
        m = re.search(r"step_(\d{8})\.tmp/", path.replace(os.sep, "/"))
        if m and int(m.group(1)) in self._ckpt_crash_steps:
            self._ckpt_crash_steps.discard(int(m.group(1)))
            return True
        return False

    def corrupt_checkpoint_blob(self, step: int,
                                leaf: int = 0) -> "FaultInjector":
        """Flip one bit in COMMITTED checkpoint ``step``'s leaf ``leaf``
        blob, at read time (the trainer must run with
        ``fs=injector.filesystem()``): bit rot that happened on disk
        after the commit, discovered only when restore reads the file.
        Matches both the dense manager's ``leaf_NNNNN.npy`` and the
        sharded manager's ``leaf_NNNNN.part_MMM.npy`` (first part read);
        staging (``.tmp``) paths never match — the rot targets durable
        bytes, not in-flight ones. One-shot: consumed on the first
        matching read, so the post-quarantine walk to the previous step
        reads clean bytes."""
        self._blob_corruptions.add((int(step), int(leaf)))
        return self

    def _maybe_corrupt_blob(self, path: str, data: bytes) -> bytes:
        """Consumed by the injected filesystem on every read."""
        if not self._blob_corruptions or not data:
            return data
        m = re.search(r"step_(\d{8})/leaf_(\d{5})[^/]*\.npy$",
                      path.replace(os.sep, "/"))
        if not m:
            return data
        key = (int(m.group(1)), int(m.group(2)))
        if key not in self._blob_corruptions:
            return data
        self._blob_corruptions.discard(key)
        # flip the low bit of the LAST byte: array payload, not the npy
        # header — the shape/dtype still parse, only the crc32/blake2b
        # verification can catch it
        return data[:-1] + bytes([data[-1] ^ 0x01])

    def preempt_at_step(self, at_step: int,
                        rank: int = 0) -> "FaultInjector":
        """Deliver a programmatic preemption to one trainer rank before
        train step ``at_step``: the rank calls ``guard.request_stop()``,
        and in coordinated mode every rank agrees to drain at the same
        step boundary — exactly the path a scheduler SIGTERM takes,
        without a real signal (thread-faked ranks cannot install
        handlers). One-shot per schedule: the window fires on the first
        step >= ``at_step`` the rank actually reaches."""
        self._train_preempts.append([int(rank), int(at_step)])
        return self

    def train_preempt_due(self, rank: int, step: int) -> bool:
        """Consumed by the trainer loop each step (fires once)."""
        for ent in self._train_preempts:
            if ent[0] == int(rank) and int(step) >= ent[1]:
                self._train_preempts.remove(ent)
                return True
        return False

    def straggler_rank(self, rank: int, delay_s: float, at_step: int = 1,
                       steps: int = 1) -> "FaultInjector":
        """Stall each of one trainer rank's steps in ``[at_step,
        at_step + steps)`` by ``delay_s`` — a slow host that is alive but
        late: peers block in the gradient exchange, which is what the
        collective watchdog must surface as a ``collective_stall``."""
        self._rank_straggles[int(rank)] = [
            float(at_step), float(at_step) + float(steps), float(delay_s)]
        return self

    def train_straggle_due(self, rank: int, step: int) -> float:
        """Seconds this trainer rank should stall this step."""
        ent = self._rank_straggles.get(int(rank))
        if ent and ent[0] <= step < ent[1]:
            return ent[2]
        return 0.0

    # ---- preemption -----------------------------------------------------
    def fire_preemption(self, sig: int = signal.SIGTERM) -> None:
        """Deliver a real signal to this process (handled at the next
        bytecode boundary of the main thread — same path as the scheduler's
        SIGTERM)."""
        os.kill(os.getpid(), sig)

    # ---- gradient corruption -------------------------------------------
    def nan_burst(self, start: int, length: int) -> "FaultInjector":
        """Schedule steps ``start .. start+length-1`` to produce non-finite
        gradients via :meth:`poison_grads`."""
        self._bursts.append((start, length))
        return self

    def grads_faulty(self, step: int) -> bool:
        return any(s <= step < s + n for s, n in self._bursts)

    def poison_grads(self, grads: Any, step: int) -> Any:
        """Return ``grads`` with every leaf filled with NaN or Inf when
        ``step`` falls in a scheduled burst (seeded choice), else
        unchanged."""
        if not self.grads_faulty(step):
            return grads
        bad = jnp.nan if self.rng.random() < 0.5 else jnp.inf
        return jax.tree_util.tree_map(
            lambda g: jnp.full_like(g, bad), grads)
