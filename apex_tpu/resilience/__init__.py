"""apex_tpu.resilience — fault-tolerant checkpointing + training resilience.

Four cooperating layers for surviving what production training actually
throws at a run:

- :mod:`~apex_tpu.resilience.checkpoint_manager` — step-numbered atomic
  checkpoints with manifests/checksums, retention, retry-with-backoff, and
  a ``restore_latest`` that skips (and quarantines) corrupt/partial steps.
- :mod:`~apex_tpu.resilience.distributed` — the multi-chip counterpart:
  :class:`ShardedCheckpointManager` (per-process shard staging, two-phase
  atomic commit, elastic restore across mesh shapes), the
  :class:`Coordinator` rendezvous seam, and the :class:`CollectiveWatchdog`
  that turns hung collectives into ``collective_stall`` events instead of
  silent stalls.
- :mod:`~apex_tpu.resilience.preemption` — SIGTERM/SIGINT-aware
  ``PreemptionGuard`` for save-and-stop on slice eviction, with a
  coordinated mode (any host's signal stops every process at the same
  step).
- :mod:`~apex_tpu.resilience.step` + :mod:`~apex_tpu.resilience.fault_injection`
  — overflow-storm guard rails around ``amp.DynamicGradScaler`` and the
  deterministic fault harness that proves all of the above under torn
  writes, EIO, preemption, NaN bursts, mid-commit deaths, stragglers, and
  lost/duplicated shard files.

See docs/robustness.md for the checkpoint layouts and protocol semantics.
"""

from apex_tpu.resilience.checkpoint_manager import (  # noqa: F401
    CORRUPT_SUFFIX, CheckpointCorruptError, CheckpointError,
    CheckpointLayoutError, CheckpointManager, Filesystem)
from apex_tpu.resilience.distributed import (  # noqa: F401
    CollectiveStallError, CollectiveWatchdog, Coordinator, JaxCoordinator,
    ShardedCheckpointManager, SingleProcessCoordinator, ThreadProcessGroup,
    default_coordinator)
from apex_tpu.resilience.fault_injection import (  # noqa: F401
    FaultInjector, SimulatedCrash)
from apex_tpu.resilience.preemption import (  # noqa: F401
    PreemptionGuard, PreemptionInterrupt)
from apex_tpu.resilience.step import (  # noqa: F401
    DEFAULT_SCALE_FLOOR, ResilientStep, resilient_step, skip_on_overflow)

__all__ = [
    "CORRUPT_SUFFIX", "CheckpointCorruptError", "CheckpointError",
    "CheckpointLayoutError", "CheckpointManager", "Filesystem",
    "CollectiveStallError",
    "CollectiveWatchdog", "Coordinator", "JaxCoordinator",
    "ShardedCheckpointManager", "SingleProcessCoordinator",
    "ThreadProcessGroup", "default_coordinator", "FaultInjector",
    "SimulatedCrash", "PreemptionGuard", "PreemptionInterrupt",
    "DEFAULT_SCALE_FLOOR", "ResilientStep", "resilient_step",
    "skip_on_overflow",
]
