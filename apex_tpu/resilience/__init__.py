"""apex_tpu.resilience — fault-tolerant checkpointing + training resilience.

Three cooperating layers for surviving what production training actually
throws at a run:

- :mod:`~apex_tpu.resilience.checkpoint_manager` — step-numbered atomic
  checkpoints with manifests/checksums, retention, retry-with-backoff, and
  a ``restore_latest`` that skips corrupt/partial steps.
- :mod:`~apex_tpu.resilience.preemption` — SIGTERM/SIGINT-aware
  ``PreemptionGuard`` for save-and-stop on slice eviction.
- :mod:`~apex_tpu.resilience.step` + :mod:`~apex_tpu.resilience.fault_injection`
  — overflow-storm guard rails around ``amp.DynamicGradScaler`` and the
  deterministic fault harness that proves all of the above under torn
  writes, EIO, preemption, and NaN bursts.

See docs/robustness.md for the checkpoint layout and semantics.
"""

from apex_tpu.resilience.checkpoint_manager import (  # noqa: F401
    CheckpointCorruptError, CheckpointError, CheckpointManager, Filesystem)
from apex_tpu.resilience.fault_injection import (  # noqa: F401
    FaultInjector, SimulatedCrash)
from apex_tpu.resilience.preemption import (  # noqa: F401
    PreemptionGuard, PreemptionInterrupt)
from apex_tpu.resilience.step import (  # noqa: F401
    DEFAULT_SCALE_FLOOR, ResilientStep, resilient_step, skip_on_overflow)

__all__ = [
    "CheckpointCorruptError", "CheckpointError", "CheckpointManager",
    "Filesystem", "FaultInjector", "SimulatedCrash", "PreemptionGuard",
    "PreemptionInterrupt", "DEFAULT_SCALE_FLOOR", "ResilientStep",
    "resilient_step", "skip_on_overflow",
]
