"""Topology-portable checkpoint layouts — the ``layout`` block + ``reshard``.

A checkpoint written by ANY ``(dp world, grad_shards, tp)`` topology must
restore onto ANY other. Two orthogonal facts make that true, and this
module is where both are stated:

- **dp / storage topology never changes values.** The trainer's logical
  tree (params, moments, scaler state) is identical at every data-parallel
  world size and every tp degree — tp shards are raw-axis chunks of the
  SAME dense values (the gather-compute-slice grad mechanism never lays
  params out differently). The sharded manager already reassembles leaves
  topology-independently; the ``layout`` block in the manifest records
  which topology *wrote* the step so a restore onto a different one can be
  observed (``train_topology_restored``) instead of silently absorbed.
- **the TP *serving* layout is a pure column permutation.** The engine's
  head-major qkv re-lay (:func:`apex_tpu.serve.tp.permute_qkv`) moves
  bytes, never combines them — so ``dense → tp_serving → dense`` is
  byte-identical, and :func:`reshard` proves it on every call with a
  blake2b-digest-verified round trip.

Everything here is numpy + stdlib: the layout block and the reshard
transform are storage-layer concepts, usable without jax (the jax-free
``tools/ckpt_inspect.py`` reads the same block).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# the two logical value-layouts a tree can be in. "dense" is the stock
# flax/training layout; "tp_serving" is the engine's head-major qkv
# permutation (rank r's contiguous q|k|v block occupies columns
# [r*3*loc, (r+1)*3*loc)). dp axes are storage topology, never a format.
FORMAT_DENSE = "dense"
FORMAT_TP_SERVING = "tp_serving"
_FORMATS = (FORMAT_DENSE, FORMAT_TP_SERVING)


class ReshardError(ValueError):
    """A reshard request the transform cannot honor (unknown format,
    missing model geometry, or a round-trip digest mismatch)."""


def layout_block(*, world: int = 1, grad_shards: int = 1, tp: int = 1,
                 fmt: str = FORMAT_DENSE, n_head: Optional[int] = None,
                 head_dim: Optional[int] = None) -> Dict[str, Any]:
    """The manifest ``layout`` block: which topology wrote this step.

    ``storage`` (dense vs sharded files) is stamped by the manager that
    writes the manifest; everything else is the writer's logical
    topology. ``n_head``/``head_dim`` ride along whenever a tp_serving
    reshard of the tree is meaningful — the inverse permutation needs
    them."""
    if fmt not in _FORMATS:
        raise ReshardError(f"unknown layout format {fmt!r} "
                           f"(expected one of {_FORMATS})")
    block: Dict[str, Any] = {"world": int(world),
                             "grad_shards": int(grad_shards),
                             "tp": int(tp), "format": fmt}
    if n_head is not None:
        block["n_head"] = int(n_head)
    if head_dim is not None:
        block["head_dim"] = int(head_dim)
    return block


def tree_digests(tree: Any) -> Dict[str, str]:
    """blake2b-128 of every leaf's raw array bytes, keyed by ``/``-joined
    path — the storage-format-independent fingerprint reshard round-trips
    are verified against (a dense blob and its reassembled sharded twin
    digest identically)."""
    out: Dict[str, str] = {}
    for path, leaf in _walk(tree, ()):
        arr = np.asarray(leaf)
        out["/".join(path)] = hashlib.blake2b(
            arr.tobytes(), digest_size=16).hexdigest()
    return out


def _walk(tree: Any, path: Tuple[str, ...]):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk(tree[k], path + (str(k),))
    else:
        yield path, tree


def _permute_qkv(kernel: np.ndarray, bias: np.ndarray, n_head: int,
                 head_dim: int, tp: int) -> Tuple[np.ndarray, np.ndarray]:
    """Head-major qkv column permutation — the same transform as
    :func:`apex_tpu.serve.tp.permute_qkv`, restated numpy-only here so
    the storage layer never imports the serving stack (tier-1 holds the
    two bit-identical)."""
    wq, wk, wv = np.split(np.asarray(kernel), 3, axis=1)
    bq, bk, bv = np.split(np.asarray(bias), 3)
    loc = (n_head // tp) * head_dim
    ks: List[np.ndarray] = []
    bs: List[np.ndarray] = []
    for r in range(tp):
        sl = slice(r * loc, (r + 1) * loc)
        ks += [wq[:, sl], wk[:, sl], wv[:, sl]]
        bs += [bq[sl], bk[sl], bv[sl]]
    return np.concatenate(ks, axis=1), np.concatenate(bs)


def _unpermute_qkv(kernel: np.ndarray, bias: np.ndarray, n_head: int,
                   head_dim: int, tp: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact inverse of :func:`_permute_qkv` — gather each projection's
    per-rank blocks back into contiguous ``[Wq | Wk | Wv]``."""
    kernel = np.asarray(kernel)
    bias = np.asarray(bias)
    loc = (n_head // tp) * head_dim
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for r in range(tp):
        base = r * 3 * loc
        qs.append(kernel[:, base:base + loc])
        ks.append(kernel[:, base + loc:base + 2 * loc])
        vs.append(kernel[:, base + 2 * loc:base + 3 * loc])
        bqs.append(bias[base:base + loc])
        bks.append(bias[base + loc:base + 2 * loc])
        bvs.append(bias[base + 2 * loc:base + 3 * loc])
    return (np.concatenate(qs + ks + vs, axis=1),
            np.concatenate(bqs + bks + bvs))


def _map_qkv(tree: Any, fn) -> Any:
    """Apply ``fn(kernel, bias) -> (kernel, bias)`` to every
    ``attn_qkv`` node; every other leaf passes through as numpy (the
    transform is host-side by design — callers re-place on device)."""
    if not isinstance(tree, dict):
        return np.asarray(tree)
    out = {}
    for k, v in tree.items():
        if k == "attn_qkv" and isinstance(v, dict) \
                and {"kernel", "bias"} <= set(v):
            kernel, bias = fn(v["kernel"], v["bias"])
            out[k] = {"kernel": kernel, "bias": bias}
        else:
            out[k] = _map_qkv(v, fn)
    return out


def _geometry(layout: Dict[str, Any]) -> Tuple[int, int, int]:
    tp = int(layout.get("tp", 1))
    n_head, head_dim = layout.get("n_head"), layout.get("head_dim")
    if n_head is None or head_dim is None:
        raise ReshardError(
            "a tp_serving reshard needs n_head/head_dim in the layout "
            "block (the qkv permutation is head-geometry-dependent)")
    return tp, int(n_head), int(head_dim)


def reshard(tree: Any, src_layout: Dict[str, Any],
            dst_layout: Dict[str, Any], *, verify: bool = True) -> Any:
    """Convert a logical tree between layouts; returns the converted tree
    (numpy leaves — callers place on their own mesh).

    The dp axes (``world``/``grad_shards``/``tp`` as *storage* sharding)
    are value-identity by construction — only the ``format`` axis moves
    bytes, and it moves them by pure permutation. With ``verify=True``
    (the default) every conversion round-trips back to the source format
    and asserts blake2b digest equality against the input — the
    digest-verified contract ``dense → tp_serving → dense`` byte-identical
    rides on, enforced at runtime, not just in tests."""
    src_fmt = src_layout.get("format", FORMAT_DENSE)
    dst_fmt = dst_layout.get("format", FORMAT_DENSE)
    for fmt in (src_fmt, dst_fmt):
        if fmt not in _FORMATS:
            raise ReshardError(f"unknown layout format {fmt!r} "
                               f"(expected one of {_FORMATS})")
    if src_fmt == dst_fmt:
        return _map_qkv(tree, lambda k, b: (np.asarray(k),
                                            np.asarray(b)))
    if dst_fmt == FORMAT_TP_SERVING:
        tp, n_head, head_dim = _geometry(dst_layout)
        fwd = lambda k, b: _permute_qkv(k, b, n_head, head_dim, tp)  # noqa: E731
        inv = lambda k, b: _unpermute_qkv(k, b, n_head, head_dim, tp)  # noqa: E731
    else:
        tp, n_head, head_dim = _geometry(src_layout)
        fwd = lambda k, b: _unpermute_qkv(k, b, n_head, head_dim, tp)  # noqa: E731
        inv = lambda k, b: _permute_qkv(k, b, n_head, head_dim, tp)  # noqa: E731
    out = _map_qkv(tree, fwd)
    if verify:
        back = _map_qkv(out, inv)
        want, got = tree_digests(tree), tree_digests(back)
        if want != got:
            bad = sorted(k for k in want if got.get(k) != want[k])
            raise ReshardError(
                f"reshard round-trip digest mismatch on {bad} — the "
                f"transform is not the pure permutation it claims to be")
    return out
