"""Distributed resilience: sharded elastic checkpoints, coordinated
preemption, and a collective watchdog.

The PR-1 resilience layer is strictly single-process: ``CheckpointManager``
serializes the full unsharded tree, ``PreemptionGuard`` acts per host, and
a hung collective stalls forever undiagnosed. This module is the
multi-chip counterpart (the TPU analog of the reference's ZeRO +
NCCL-orchestration pillar):

- :class:`ShardedCheckpointManager` — each process stages only the leaf
  shards it *owns* (deduced from ``jax.sharding`` device/index maps, with
  replica dedup), a two-phase commit publishes per-process manifests and
  then one rank-0 global manifest behind the same atomic
  ``.tmp`` + ``os.replace`` discipline as PR-1, and **elastic restore**
  reassembles leaves from shard metadata — save on one mesh shape, restore
  bit-exact onto another.
- :class:`Coordinator` — the tiny rendezvous seam (barrier + OR-reduce +
  device→process map) everything above rides. :class:`JaxCoordinator` is
  the real multi-host implementation; :class:`ThreadProcessGroup` fakes N
  processes with N threads so every protocol step is testable on a CPU
  laptop, stragglers and mid-commit deaths included.
- :class:`CollectiveWatchdog` — a heartbeat thread that turns "a collective
  has been stuck for longer than ``timeout_s``" into a structured
  ``collective_stall`` event (charged to the goodput ledger), an optional
  all-thread stack dump, and an optional clean abort — instead of an
  infinite silent hang.

All shared-directory writes stay inside ``<step>.tmp`` staging until the
single rank-0 ``os.replace`` that commits the step; a kill on ANY host at
ANY point leaves the previous committed step intact
(``tools/check_durability.py`` lints this statically, and the
kill-at-every-write-point property test in
``tests/test_resilience_distributed.py`` proves it dynamically). The
checkpoint directory must be shared storage (GCS/NFS) in real multi-host
runs — the same requirement every sharded-checkpoint system has.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

# imported at module load, NOT in the escalation path: a first-time
# package import on the watchdog thread while the main thread is wedged
# (possibly holding an import lock) could block the very dump that is
# supposed to diagnose the wedge
from apex_tpu.monitor.flight import thread_stacks
from apex_tpu.resilience.checkpoint_manager import (
    _OLD_SUFFIX, _TMP_SUFFIX, MANIFEST_NAME, MANIFEST_VERSION,
    CheckpointCorruptError, CheckpointError, CheckpointLayoutError,
    CheckpointManager)
from apex_tpu.utils.logging import publish_event, structured_warning

LAYOUT_SHARDED = "sharded"
PROC_MANIFEST_FMT = "pmanifest_{:05d}.json"
PROC_MANIFEST_RE = re.compile(r"^pmanifest_(\d{5})\.json$")


class CollectiveStallError(RuntimeError):
    """A collective (barrier/agreement) could not complete: a peer died or
    exceeded the configured timeout. Raised instead of hanging forever."""


# --------------------------------------------------------------------------
# Coordinator seam
# --------------------------------------------------------------------------

class Coordinator:
    """Rendezvous seam for the distributed resilience protocol.

    Three primitives cover everything this module needs:

    - ``barrier(name)`` — all processes arrive before any proceeds;
    - ``all_any(flag)`` — OR-reduce one bool (the preemption agreement);
    - ``device_rank(device)`` — which process *owns* a device, used to
      dedup shard writes (exactly one process writes each unique shard
      region, chosen from the globally-known device assignment with zero
      communication).

    Implementations: :class:`SingleProcessCoordinator` (world 1, no-ops),
    :class:`JaxCoordinator` (real multi-host via
    ``jax.experimental.multihost_utils``), and the view objects handed out
    by :class:`ThreadProcessGroup` (N threads faking N processes for
    tests/CPU).
    """

    process_index: int = 0
    process_count: int = 1

    def barrier(self, name: str = "",
                timeout_s: Optional[float] = None) -> None:
        raise NotImplementedError

    def all_any(self, flag: bool) -> bool:
        raise NotImplementedError

    def all_gather_object(self, obj: Any) -> List[Any]:
        """Gather one per-process payload into a rank-indexed list (every
        process returns the same list) — the data-parallel trainer's
        gradient-exchange seam (:mod:`apex_tpu.train`). A world of one
        short-circuits; implementations define the payload contract
        (the jax coordinator requires a pytree of equal-shape arrays,
        the thread harness passes any object by reference)."""
        if self.process_count == 1:
            return [obj]
        raise NotImplementedError

    def device_rank(self, device) -> int:
        return int(getattr(device, "process_index", 0))


class SingleProcessCoordinator(Coordinator):
    """World of one: every primitive degenerates to a local no-op."""

    def barrier(self, name: str = "",
                timeout_s: Optional[float] = None) -> None:
        return None

    def all_any(self, flag: bool) -> bool:
        return bool(flag)


class JaxCoordinator(Coordinator):
    """The real thing: rank/world from the jax runtime, barrier via
    ``multihost_utils.sync_global_devices``, agreement via a tiny host
    allgather. On a single-process backend every primitive short-circuits
    locally (no compilation, no device traffic)."""

    def __init__(self):
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()

    def barrier(self, name: str = "",
                timeout_s: Optional[float] = None) -> None:
        if self.process_count == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name or "apex_tpu_barrier")

    def all_any(self, flag: bool) -> bool:
        if self.process_count == 1:
            return bool(flag)
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([bool(flag)], dtype=np.bool_))
        return bool(np.any(flags))

    def all_gather_object(self, obj: Any) -> List[Any]:
        """Real multi-host gather via ``process_allgather``: ``obj`` must
        be a pytree of arrays with identical structure and shapes on every
        process (the trainer's equal-shards-per-rank contract guarantees
        this). Leaves come back stacked along a leading process axis and
        are unstacked into the rank-indexed list."""
        if self.process_count == 1:
            return [obj]
        from jax.experimental import multihost_utils

        stacked = multihost_utils.process_allgather(obj)
        return [jax.tree_util.tree_map(lambda x: x[r], stacked)
                for r in range(self.process_count)]


class ThreadProcessGroup:
    """N threads standing in for N processes (the CPU test double).

    ``group.coordinator(rank)`` returns rank ``rank``'s view; ``run(fn)``
    spawns one thread per rank calling ``fn(coordinator, rank)`` and
    returns per-rank ``(result, exception)`` pairs. Semantics match a real
    multi-host job where it matters for resilience testing:

    - barriers consult the :class:`~apex_tpu.resilience.fault_injection.
      FaultInjector` straggler schedule before arriving;
    - when one "process" dies (raises), the group aborts its barrier so
      surviving peers get :class:`CollectiveStallError` instead of a
      forever-hang — what a production job sees when a host disappears;
    - ``device_rank`` partitions the (single-process) jax devices into
      contiguous fake-process blocks via
      :func:`apex_tpu.parallel.mesh.device_process_map`, so shard
      ownership exercises the same dedup logic real multi-host does.
    """

    def __init__(self, world: int, *, devices=None, injector=None,
                 barrier_timeout_s: float = 30.0):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = world
        self.injector = injector
        self.barrier_timeout_s = barrier_timeout_s
        self._barrier = threading.Barrier(world)
        self._flags = [False] * world
        self._mailbox: List[Any] = [None] * world
        from apex_tpu.parallel.mesh import device_process_map

        devs = devices if devices is not None else jax.devices()
        self._device_rank = {d: r
                             for d, r in device_process_map(devs,
                                                            world).items()}

    def coordinator(self, rank: int) -> "_ThreadCoordinator":
        if not 0 <= rank < self.world:
            raise ValueError(f"rank {rank} outside world {self.world}")
        return _ThreadCoordinator(self, rank)

    def abort(self) -> None:
        """Break every pending and future barrier wait — a peer died."""
        self._barrier.abort()

    def run(self, fn) -> List[Tuple[Any, Optional[BaseException]]]:
        """Run ``fn(coordinator, rank)`` on one thread per rank; a raising
        rank aborts the group's barriers (peers unblock with
        :class:`CollectiveStallError`). Returns ``[(result, exc), ...]``
        indexed by rank."""
        out: List[Tuple[Any, Optional[BaseException]]] = [
            (None, None)] * self.world

        def _target(rank: int) -> None:
            try:
                out[rank] = (fn(self.coordinator(rank), rank), None)
            except BaseException as e:  # noqa: BLE001 — reported per rank
                out[rank] = (None, e)
                self.abort()

        threads = [threading.Thread(target=_target, args=(r,), daemon=True)
                   for r in range(self.world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out


class _ThreadCoordinator(Coordinator):
    def __init__(self, group: ThreadProcessGroup, rank: int):
        self.group = group
        self.process_index = rank
        self.process_count = group.world

    def barrier(self, name: str = "",
                timeout_s: Optional[float] = None) -> None:
        inj = self.group.injector
        if inj is not None:
            delay = inj.barrier_delay(self.process_index, name)
            if delay:
                time.sleep(delay)
        try:
            self.group._barrier.wait(
                timeout_s if timeout_s is not None
                else self.group.barrier_timeout_s)
        except threading.BrokenBarrierError:
            raise CollectiveStallError(
                f"barrier {name!r} broken on rank {self.process_index}: "
                f"a peer died or timed out") from None

    def all_any(self, flag: bool) -> bool:
        self.group._flags[self.process_index] = bool(flag)
        self.barrier("all_any:write")
        result = any(self.group._flags)
        self.barrier("all_any:read")
        return result

    def all_gather_object(self, obj: Any) -> List[Any]:
        # same two-barrier discipline as all_any: the read barrier keeps a
        # fast rank's NEXT round's write from clobbering a slot a slow
        # rank has not read yet (threads share one process, so payloads —
        # device arrays included — cross by reference, no serialization)
        self.group._mailbox[self.process_index] = obj
        self.barrier("all_gather:write")
        result = list(self.group._mailbox)
        self.barrier("all_gather:read")
        return result

    def device_rank(self, device) -> int:
        # the fake topology: contiguous device blocks per fake process
        # (falls back to the real process_index for foreign devices)
        rank = self.group._device_rank.get(device)
        return rank if rank is not None else super().device_rank(device)


def default_coordinator() -> Coordinator:
    """The coordinator a production entry point should use: rank/world from
    the jax runtime (after :func:`apex_tpu.parallel.mesh.init_distributed`),
    degenerating to free no-ops on a single process."""
    return JaxCoordinator()


# --------------------------------------------------------------------------
# Collective watchdog
# --------------------------------------------------------------------------

class CollectiveWatchdog:
    """Detect stuck collectives/straggler hosts instead of hanging forever.

    A daemon heartbeat thread checks every *watched* region against its
    deadline. The first breach publishes a structured ``collective_stall``
    event (console on rank 0, bus everywhere — the goodput ledger charges
    the ``collective_stall`` cause), then optionally escalates:

    - ``escalate="event"`` (default) — event only; the region keeps
      waiting (the straggler may still arrive).
    - ``escalate="dump"`` — also dump every thread's Python stack to
      stderr, the "where is it stuck" diagnostic a hung job never gives.
    - ``escalate="abort"`` — dump, then call ``abort_fn`` (default: raise
      ``SIGABRT`` in this process) so the scheduler restarts the job from
      the last committed checkpoint rather than burning the reservation on
      a wedged collective.

    Usage::

        wd = CollectiveWatchdog(timeout_s=300)
        with wd.watch("allreduce:grads"):
            psum(...)            # or coordinator.barrier(...)
        wd.stop()

    When a stalled region eventually completes, a bus-only
    ``collective_stall_cleared`` event carries the residual lost seconds,
    so the ledger's ``collective_stall`` cause totals the *actual* stall
    time, not just the detection threshold.
    """

    def __init__(self, timeout_s: float = 300.0, *,
                 poll_s: Optional[float] = None, escalate: str = "event",
                 abort_fn=None, coordinator: Optional[Coordinator] = None):
        if escalate not in ("event", "dump", "abort"):
            raise ValueError(f"escalate must be event|dump|abort, "
                             f"got {escalate!r}")
        self.timeout_s = float(timeout_s)
        self.poll_s = (poll_s if poll_s is not None
                       else min(max(self.timeout_s / 4.0, 0.005), 1.0))
        self.escalate = escalate
        self.abort_fn = abort_fn
        self.coordinator = coordinator
        self.stalls: List[Dict[str, Any]] = []
        self._regions: Dict[int, Dict[str, Any]] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> "CollectiveWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._heartbeat, name="apex-tpu-collective-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "CollectiveWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- watched regions ------------------------------------------------
    def watch(self, name: str, timeout_s: Optional[float] = None):
        """Context manager: the enclosed blocking region (a barrier, an
        allreduce, a checkpoint phase) must finish within ``timeout_s``
        (default: the watchdog's) or the heartbeat reports a stall."""
        return _WatchedRegion(self, name,
                              timeout_s if timeout_s is not None
                              else self.timeout_s)

    def _begin(self, name: str, timeout_s: float) -> int:
        self.start()
        now = time.perf_counter()
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._regions[rid] = {"name": name, "t0": now,
                                  "deadline": now + timeout_s,
                                  "timeout_s": timeout_s,
                                  "reported_waited": None}
        return rid

    def _end(self, rid: int) -> None:
        now = time.perf_counter()
        with self._lock:
            reg = self._regions.pop(rid, None)
        if reg is not None and reg["reported_waited"] is not None:
            total = now - reg["t0"]
            publish_event(
                "collective_stall_cleared", name=reg["name"],
                seconds=round(max(0.0, total - reg["reported_waited"]), 6),
                total_s=round(total, 6))

    # ---- heartbeat ------------------------------------------------------
    def _rank0(self) -> bool:
        if self.coordinator is not None:
            return self.coordinator.process_index == 0
        from apex_tpu.utils.logging import is_rank_zero

        return is_rank_zero()

    def _heartbeat(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            now = time.perf_counter()
            breached = []
            with self._lock:
                for reg in self._regions.values():
                    if reg["reported_waited"] is None and \
                            now > reg["deadline"]:
                        reg["reported_waited"] = now - reg["t0"]
                        breached.append(dict(reg))
            for reg in breached:
                rec = publish_event(
                    "collective_stall", level="warning",
                    emit=self._rank0(), name=reg["name"],
                    waited_s=round(reg["reported_waited"], 6),
                    timeout_s=reg["timeout_s"],
                    seconds=round(reg["reported_waited"], 6),
                    escalate=self.escalate,
                    rank=(self.coordinator.process_index
                          if self.coordinator is not None else 0))
                # under the lock: appended here on the heartbeat thread,
                # read from the owning thread (tests, run reports)
                with self._lock:
                    self.stalls.append(rec)
                if self.escalate in ("dump", "abort"):
                    self._dump_stacks(reg["name"])
                if self.escalate == "abort":
                    self._abort(reg["name"])

    def _dump_stacks(self, name: str, stream=None) -> None:
        """All-thread Python stack dump — the diagnostic a silent hang never
        yields. Shares :func:`apex_tpu.monitor.flight.thread_stacks`
        (pure ``sys._current_frames``, works where faulthandler can't) so
        the stderr dump and a flight-recorder postmortem show the same
        stacks. An attached :class:`~apex_tpu.monitor.flight.
        FlightRecorder` also auto-dumps on this escalation — the
        ``collective_stall`` record it sees carries ``escalate``."""
        stream = stream or sys.stderr
        try:
            stacks = thread_stacks()
            # not rank-0-gated on purpose: the straggler's own stacks are
            # the diagnostic, and only the stuck host can print them
            print(f"collective_stall[{name}]: dumping "  # apexlint: disable=APX005 -- every-rank postmortem: the stuck host must dump its own stacks
                  f"{len(stacks)} thread stacks", file=stream)
            for label, frames in stacks.items():
                print(f"--- thread {label} ---", file=stream)  # apexlint: disable=APX005 -- every-rank postmortem: the stuck host must dump its own stacks
                for line in frames:
                    print(line, file=stream)  # apexlint: disable=APX005 -- every-rank postmortem: the stuck host must dump its own stacks
            stream.flush()
        except Exception:
            pass  # diagnostics must never take down the watchdog thread

    def _abort(self, name: str) -> None:
        structured_warning("collective_stall_abort", name=name,
                           action="aborting so the scheduler restarts from "
                                  "the last committed checkpoint")
        if self.abort_fn is not None:
            self.abort_fn(name)
        else:
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGABRT)


class _WatchedRegion:
    def __init__(self, wd: CollectiveWatchdog, name: str, timeout_s: float):
        self._wd = wd
        self._name = name
        self._timeout_s = timeout_s
        self._rid: Optional[int] = None

    def __enter__(self) -> "_WatchedRegion":
        self._rid = self._wd._begin(self._name, self._timeout_s)
        return self

    def __exit__(self, *exc) -> None:
        if self._rid is not None:
            self._wd._end(self._rid)
            self._rid = None


# --------------------------------------------------------------------------
# Sharded checkpoints
# --------------------------------------------------------------------------

def _leaf_spec(leaf: Any) -> Tuple[Tuple[int, ...], str,
                                   List[Tuple[Tuple[Tuple[int, int], ...],
                                              Any]]]:
    """``(global_shape, dtype_str, regions)`` for one pytree leaf.

    ``regions`` is the deterministic list of ``(region_key, owner_device)``
    pairs covering the leaf exactly once: every device's index from
    ``sharding.devices_indices_map`` is normalized to concrete
    ``(start, stop)`` bounds, replicas of the same region dedup to the
    lowest device id (globally known — zero communication), and the list is
    sorted so every process derives identical shard ordinals and file
    names. Unsharded leaves (plain numpy, single-device arrays) are one
    full-extent region owned by rank 0 (``owner None``).
    """
    shape = tuple(getattr(leaf, "shape", np.asarray(leaf).shape))
    dtype = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
    sharding = getattr(leaf, "sharding", None)
    if sharding is None or not hasattr(sharding, "devices_indices_map"):
        return shape, dtype, [(tuple((0, d) for d in shape), None)]
    owners: Dict[Tuple[Tuple[int, int], ...], Any] = {}
    for dev, idx in sharding.devices_indices_map(shape).items():
        key = tuple(sl.indices(d)[:2] for sl, d in zip(idx, shape))
        cur = owners.get(key)
        if cur is None or dev.id < cur.id:
            owners[key] = dev
    return shape, dtype, sorted(owners.items(), key=lambda kv: kv[0])


def _region_array(leaf: Any, key: Tuple[Tuple[int, int], ...],
                  owner: Any) -> np.ndarray:
    """Host bytes for one owned region — straight off the owner device's
    shard when addressable (no gather), else sliced from the leaf."""
    if owner is not None and hasattr(leaf, "addressable_shards"):
        for sh in leaf.addressable_shards:
            if sh.device == owner:
                return np.asarray(sh.data)
    if not key:
        return np.asarray(leaf)
    return np.asarray(leaf[tuple(slice(s, e) for s, e in key)])


def _region_size(key: Sequence[Tuple[int, int]]) -> int:
    n = 1
    for s, e in key:
        n *= max(0, e - s)
    return n


class ShardedCheckpointManager(CheckpointManager):
    """Multi-process sharded checkpoints with two-phase atomic commit and
    elastic (topology-independent) restore.

    Layout under ``directory`` (shared storage in real multi-host runs)::

        step_00000100/                  # one committed checkpoint
            manifest.json               # rank-0 global manifest (layout=
                                        #   sharded, per-leaf shard table)
            pmanifest_00000.json ...    # one per process: its staged shards
            leaf_00000.part_000.npy ... # one .npy per unique shard region
        step_00000200.tmp/              # in-flight staging (never read)

    Commit protocol (``save``):

    1. rank 0 clears and creates ``<step>.tmp``; **barrier**;
    2. every process writes the shard regions it owns (replica-deduped),
       then its ``pmanifest_<rank>.json`` — the per-process commit mark;
       local ``OSError`` retries stay process-local;
    3. **barrier**, then an ``all_any`` agreement aborts every rank if any
       rank failed its staging (no half-staged set can ever publish);
    4. rank 0 aggregates the per-process manifests, validates shard
       coverage, writes the global ``manifest.json`` into staging, and
       publishes with ONE ``os.replace`` — the commit point;
    5. **barrier**, a second agreement propagates a rank-0 publish failure
       to every rank, then rank 0 prunes retention.

    A kill on any host at any point before step 4's replace leaves only an
    uncommitted ``.tmp``: ``restore_latest`` still returns the previous
    committed step. Elastic restore: ``restore(step, like)`` reassembles
    every leaf from the manifest's shard index metadata — the mesh/process
    count at save time is irrelevant — and places it with ``like``'s leaf
    shardings (``jax.make_array_from_callback``), so a tree saved on an
    8-way mesh restores bit-exact onto 4-way, 1-way, or any other shape.
    """

    def __init__(self, directory: str, *,
                 coordinator: Optional[Coordinator] = None,
                 watchdog: Optional[CollectiveWatchdog] = None, **kw):
        self.coordinator = (coordinator if coordinator is not None
                            else default_coordinator())
        self.watchdog = watchdog
        super().__init__(directory, **kw)

    # ---- plumbing -------------------------------------------------------
    def _is_rank0(self) -> bool:
        return self.coordinator.process_index == 0

    def _barrier(self, name: str) -> None:
        if self.watchdog is not None:
            with self.watchdog.watch(name):
                self.coordinator.barrier(name)
        else:
            self.coordinator.barrier(name)

    def _owns(self, owner: Any) -> bool:
        if owner is None:  # unsharded/host leaf: rank 0 writes it
            return self._is_rank0()
        return (self.coordinator.device_rank(owner)
                == self.coordinator.process_index)

    # ---- save -----------------------------------------------------------
    def save(self, step: int, tree: Any, *,
             layout: Optional[Dict[str, Any]] = None) -> str:
        """Commit ``step`` via the 5-phase protocol below; ``layout``
        (optional) is the writer's topology block, stamped into the
        global manifest as ``{"storage": "sharded", **layout}`` — without
        it the manifest keeps the legacy ``"sharded"`` string, so
        pre-topology checkpoints and their readers are untouched."""
        t_start = time.perf_counter()
        rank = self.coordinator.process_index
        world = self.coordinator.process_count
        final = self.step_path(step)
        tmp = final + _TMP_SUFFIX
        leaves, _ = jax.tree_util.tree_flatten(tree)
        specs = [_leaf_spec(leaf) for leaf in leaves]

        # phase 0: rank 0 resets staging (a stale .tmp from a crashed save
        # may carry another attempt's shards); nobody stages before it
        if rank == 0:
            self.fs.rmtree(tmp)
            self.fs.makedirs(tmp)
        self._barrier(f"ckpt_stage_ready:{step}")

        # phase 1: stage owned shards + the per-process manifest.
        # Transient OSError retries are process-local (no barrier inside).
        failed = not self._stage_local(step, tmp, leaves, specs, rank, world)
        self._barrier(f"ckpt_staged:{step}")
        if self.coordinator.all_any(failed):
            raise CheckpointError(
                f"sharded save for step {step}: staging failed on at least "
                f"one process (rank {rank} local failure: {failed})")

        # phase 2: rank 0 publishes. The commit point is its single
        # replace; every other rank learns the outcome via the agreement —
        # a rank-0 failure must reach the barrier, not bypass it (peers
        # would hang), so only a simulated-death/BaseException escapes here
        publish_err: Optional[Exception] = None
        if rank == 0:
            try:
                self._publish(step, tmp, final, specs, world,
                              layout=layout)
            except (OSError, CheckpointError) as e:
                structured_warning("checkpoint_publish_failed",
                                   step=int(step), reason=str(e))
                publish_err = e
        self._barrier(f"ckpt_committed:{step}")
        if self.coordinator.all_any(publish_err is not None):
            raise CheckpointError(
                f"sharded save for step {step}: rank 0 failed to publish "
                f"the global manifest"
                + (f": {publish_err}" if publish_err is not None else "")
            ) from publish_err

        if rank == 0:
            self._prune()
        publish_event("checkpoint_save_stall", step=int(step),
                      seconds=round(time.perf_counter() - t_start, 6),
                      rank=rank)
        return final

    def _stage_local(self, step: int, tmp: str, leaves: List[Any],
                     specs: List[Any], rank: int, world: int) -> bool:
        """Write this process's shards + pmanifest into ``tmp`` staging.
        Returns True on success; False after exhausting retries (the
        caller turns that into an all-rank abort — never a raise *before*
        the barrier, which would leave peers hanging)."""
        last_err: Optional[OSError] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self.backoff_base * (2.0 ** (attempt - 1))
                structured_warning(
                    "checkpoint_save_retry", step=int(step), rank=rank,
                    attempt=attempt, delay_s=delay, error=str(last_err))
                self._sleep(delay)
            try:
                self.fs.makedirs(tmp)
                entries = []
                for i, (leaf, (shape, dtype, regions)) in enumerate(
                        zip(leaves, specs)):
                    for ordinal, (key, owner) in enumerate(regions):
                        if not self._owns(owner):
                            continue
                        # one serialized blob live at a time (same RAM
                        # discipline as the single-process manager)
                        buf = io.BytesIO()
                        np.save(buf, _region_array(leaf, key, owner),
                                allow_pickle=False)
                        blob = buf.getvalue()
                        entry = {
                            "leaf": i,
                            "file": f"leaf_{i:05d}.part_{ordinal:03d}.npy",
                            "index": [list(se) for se in key],
                            "nbytes": len(blob),
                            "crc32": zlib.crc32(blob),
                            # blake2b of the blob bytes (see the dense
                            # manager): tools/ckpt_inspect.py verifies
                            # shard files jax-free against this
                            "blake2b": hashlib.blake2b(
                                blob, digest_size=16).hexdigest(),
                        }
                        self.fs.write_bytes(os.path.join(tmp, entry["file"]),
                                            blob)
                        entries.append(entry)
                pmanifest = {
                    "format_version": MANIFEST_VERSION,
                    "layout": LAYOUT_SHARDED,
                    "step": int(step),
                    "process": rank,
                    "world": world,
                    "shards": entries,
                }
                # pmanifest last: its presence marks this process's shards
                # as fully staged (the per-process commit mark)
                self.fs.write_bytes(
                    os.path.join(tmp, PROC_MANIFEST_FMT.format(rank)),
                    json.dumps(pmanifest, indent=1).encode())
                return True
            except OSError as e:
                last_err = e
        return False

    def _publish(self, step: int, tmp: str, final: str, specs: List[Any],
                 world: int,
                 layout: Optional[Dict[str, Any]] = None) -> None:
        """Rank 0: aggregate per-process manifests, validate coverage,
        write the global manifest into staging, publish atomically."""
        leaves_meta: List[Dict[str, Any]] = [
            {"shape": list(shape), "dtype": dtype, "shards": []}
            for shape, dtype, _ in specs]
        for r in range(world):
            ppath = os.path.join(tmp, PROC_MANIFEST_FMT.format(r))
            if not self.fs.exists(ppath):
                raise CheckpointError(
                    f"step {step}: process {r} staged no manifest "
                    f"(died before its per-process commit?)")
            pm = json.loads(self.fs.read_bytes(ppath))
            if pm.get("step") != step or pm.get("world") != world:
                raise CheckpointError(
                    f"{ppath}: stale staging (step={pm.get('step')}, "
                    f"world={pm.get('world')}, expected {step}/{world})")
            for ent in pm["shards"]:
                leaves_meta[ent["leaf"]]["shards"].append(
                    {k: ent[k] for k in ("file", "index", "nbytes",
                                         "crc32", "blake2b")
                     if k in ent})
        for i, ((shape, _dtype, _regions), meta) in enumerate(
                zip(specs, leaves_meta)):
            total = int(np.prod(shape)) if shape else 1
            covered = sum(_region_size(ent["index"])
                          for ent in meta["shards"])
            if covered != total:
                raise CheckpointError(
                    f"step {step} leaf {i}: shard coverage {covered}/"
                    f"{total} elements — a process staged too few or too "
                    f"many shards")
        manifest = {
            "format_version": MANIFEST_VERSION,
            "layout": ({"storage": LAYOUT_SHARDED, **dict(layout)}
                       if layout is not None else LAYOUT_SHARDED),
            "step": int(step),
            "created": time.time(),
            "world": world,
            "num_leaves": len(specs),
            "leaves": leaves_meta,
        }
        # manifest last inside staging, then the one atomic publish; a
        # re-save of an existing step moves the old commit aside by rename
        # (never rmtree before the commit point — same discipline and
        # failure analysis as the single-process manager)
        self.fs.write_bytes(os.path.join(tmp, MANIFEST_NAME),
                            json.dumps(manifest, indent=1).encode())
        old = final + _OLD_SUFFIX
        if self.fs.exists(final):
            self.fs.rmtree(old)
            self.fs.replace(final, old)
        self.fs.replace(tmp, final)  # THE commit point
        self.fs.sync_dir(self.directory)
        self.fs.rmtree(old)

    # ---- restore --------------------------------------------------------
    def validate(self, step: int,
                 _blobs: Optional[Dict[str, bytes]] = None) -> Dict[str, Any]:
        """Parse + verify the global manifest and every shard's checksum.
        Also proves per-leaf coverage is exact (a lost shard file reads as
        a gap, a duplicated region as overlap — both corrupt, both
        quarantinable), so a damaged step can never half-restore."""
        path = self.step_path(step)
        mpath = os.path.join(path, MANIFEST_NAME)
        if not self.fs.exists(mpath):
            raise CheckpointCorruptError(f"{path}: missing {MANIFEST_NAME}")
        try:
            manifest = json.loads(self.fs.read_bytes(mpath))
        except (ValueError, OSError) as e:
            raise CheckpointCorruptError(f"{mpath}: unreadable manifest "
                                         f"({e})") from e
        if manifest.get("format_version") != MANIFEST_VERSION or \
                manifest.get("step") != step:
            raise CheckpointCorruptError(
                f"{mpath}: bad header (version="
                f"{manifest.get('format_version')}, "
                f"step={manifest.get('step')}, expected {step})")
        layout = manifest.get("layout")
        sharded = (layout == LAYOUT_SHARDED  # legacy string stamp
                   or (isinstance(layout, dict)
                       and layout.get("storage") == LAYOUT_SHARDED))
        if not sharded:
            # a dense (single-process) step: valid data under the base
            # manager — skip without quarantining
            raise CheckpointLayoutError(
                f"{mpath}: layout {manifest.get('layout')!r} requires the "
                f"dense CheckpointManager")
        leaves = manifest.get("leaves")
        if not isinstance(leaves, list) or \
                len(leaves) != manifest.get("num_leaves"):
            raise CheckpointCorruptError(f"{mpath}: leaf table truncated")
        for li, leaf in enumerate(leaves):
            shape = tuple(leaf["shape"])
            total = int(np.prod(shape)) if shape else 1
            covered = 0
            seen = set()
            for ent in leaf["shards"]:
                key = tuple(tuple(se) for se in ent["index"])
                if key in seen:
                    raise CheckpointCorruptError(
                        f"{path} leaf {li}: duplicated shard region {key}")
                seen.add(key)
                covered += _region_size(key)
                fpath = os.path.join(path, ent["file"])
                if not self.fs.exists(fpath):
                    raise CheckpointCorruptError(
                        f"{fpath}: missing shard file (lost after commit)")
                data = self.fs.read_bytes(fpath)
                if len(data) != ent["nbytes"] or \
                        zlib.crc32(data) != ent["crc32"]:
                    raise CheckpointCorruptError(
                        f"{fpath}: checksum mismatch (torn, corrupt, or "
                        f"duplicated-over write)")
                if "blake2b" in ent and hashlib.blake2b(
                        data,
                        digest_size=16).hexdigest() != ent["blake2b"]:
                    raise CheckpointCorruptError(
                        f"{fpath}: blake2b digest mismatch (crc collision "
                        f"or manifest tamper)")
                if _blobs is not None:
                    _blobs[ent["file"]] = data
            if covered != total:
                raise CheckpointCorruptError(
                    f"{path} leaf {li}: shard coverage {covered}/{total} "
                    f"elements (lost shard file)")
        self._last_manifest = manifest
        return manifest

    def restore(self, step: int, like: Any) -> Any:
        """Validated elastic restore into the structure *and topology* of
        ``like``: leaves reassemble from shard index metadata (whatever
        mesh they were saved under) and are placed with each ``like``
        leaf's sharding — bit-exact across mesh shapes and process
        counts."""
        blobs: Dict[str, bytes] = {}
        manifest = self.validate(step, _blobs=blobs)
        refs, treedef = jax.tree_util.tree_flatten(like)
        if len(refs) != manifest["num_leaves"]:
            raise CheckpointCorruptError(
                f"{self.step_path(step)}: has {manifest['num_leaves']} "
                f"leaves, restore target has {len(refs)}")
        out = [self._assemble_leaf(meta, blobs, ref)
               for meta, ref in zip(manifest["leaves"], refs)]
        return jax.tree_util.tree_unflatten(treedef, out)

    def _assemble_leaf(self, meta: Dict[str, Any], blobs: Dict[str, bytes],
                       ref: Any) -> Any:
        shape = tuple(meta["shape"])
        dtype = np.dtype(getattr(ref, "dtype", None)
                         or np.asarray(ref).dtype)
        buf = np.zeros(shape, dtype=dtype)
        for ent in meta["shards"]:
            arr = np.load(io.BytesIO(blobs.pop(ent["file"])),
                          allow_pickle=False)
            if arr.dtype != buf.dtype:
                if arr.dtype.kind == "V":
                    # extension dtypes (bfloat16, fp8) round-trip as raw
                    # bytes; re-view through the restore target's dtype
                    arr = arr.view(buf.dtype)
                else:
                    raise CheckpointCorruptError(
                        f"{ent['file']}: dtype {arr.dtype} does not match "
                        f"restore target {buf.dtype}")
            sl = tuple(slice(s, e) for s, e in ent["index"])
            if sl:
                buf[sl] = arr
            else:
                buf[()] = arr
        sharding = getattr(ref, "sharding", None)
        if sharding is not None and hasattr(sharding, "devices_indices_map"):
            # only the addressable pieces materialize on device — in a real
            # multi-host restore each process places just its own shards
            # (np.asarray, not ascontiguousarray: the latter promotes 0-d
            # scalars to 1-d and the shard shapes stop matching)
            return jax.make_array_from_callback(
                shape, sharding, lambda idx: np.asarray(buf[idx]))
        return jax.numpy.asarray(buf)
