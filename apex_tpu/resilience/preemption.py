"""Preemption handling for interruptible training (TPU slices get evicted).

Cloud TPU (and most batch schedulers) deliver SIGTERM with a short grace
window before the slice disappears. :class:`PreemptionGuard` converts that
into cooperative shutdown: the signal handler only records the request (no
I/O in handler context), the training loop polls ``should_stop()`` at step
boundaries, and ``finalize()`` runs the registered final synchronous save
exactly once. A second signal restores default handling so an operator's
repeated Ctrl-C still kills a wedged process.

Multi-host runs get a **coordinated** mode: pass a
:class:`~apex_tpu.resilience.distributed.Coordinator` and ``should_stop()``
becomes a tiny agreement collective — a SIGTERM delivered to ANY host makes
*every* process return True at the same step boundary, so all hosts enter
the same final sharded save together instead of one host saving step N
while another saves N+1 (which a sharded checkpoint could never commit).
Console announcements are gated to rank 0; the structured
``preemption_requested`` bus event still fires on every rank.

Postmortems need no wiring here: an attached
:class:`~apex_tpu.monitor.flight.FlightRecorder` auto-dumps on the
``preemption_requested`` bus record itself, so a preempted run leaves its
last-N events, open spans, and memory snapshot on disk alongside the
final checkpoint — whichever of ``should_stop()``/``finalize()``/the
``raise_on_signal`` unwind announces the preemption first.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Iterable, Optional

from apex_tpu.utils.logging import is_rank_zero, publish_event

_PROGRAMMATIC = -1  # request_stop() with no signal attached


class PreemptionInterrupt(BaseException):
    """Raised in the main thread by a guard with ``raise_on_signal=True``.

    A ``BaseException`` (like ``KeyboardInterrupt``) so straight-line code
    with broad ``except Exception`` handlers — a benchmark, a data pipeline
    — still unwinds promptly to the guard's ``with`` block.
    """

    def __init__(self, signum: int):
        super().__init__(f"preempted by signal {signum}")
        self.signum = signum


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers and expose ``should_stop()``.

    Usage::

        with PreemptionGuard(on_preempt=lambda: mgr.save(step, state)) as g:
            for step in range(steps):
                state = train_step(state)
                if g.should_stop():
                    break
        # __exit__ runs finalize() (the final synchronous save) when a
        # preemption was requested, then restores the previous handlers.

    ``on_preempt`` runs in normal (loop) context, never inside the signal
    handler — a save interrupted by its own trigger can't tear itself.
    Signal handlers can only be installed from the main thread; elsewhere
    the guard degrades to an inert ``should_stop() == False`` with a
    structured warning rather than failing the training script.

    For straight-line work with no step boundary to poll (a benchmark, a
    one-shot export), ``raise_on_signal=True`` makes the handler raise
    :class:`PreemptionInterrupt` in the main thread instead — the ``with``
    body unwinds immediately and ``__exit__`` still runs ``on_preempt``.

    **Coordinated (distributed) mode** — with ``coordinator`` set and a
    world size > 1, every ``should_stop()`` call is a collective: the
    local stop flag is OR-reduced across processes, so the whole job
    agrees on the same stop step no matter which host the scheduler
    signalled. All processes must therefore poll ``should_stop()`` at the
    same step cadence (it is a collective, like any other). Once agreement
    is reached the result is cached — later calls (``__exit__``,
    ``finalize``) are local and cheap. ``request_stop()`` feeds the same
    path programmatically (an orchestrator's drain command, or a test).
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT),
                 on_preempt: Optional[Callable[[], None]] = None,
                 raise_on_signal: bool = False,
                 coordinator=None):
        self.signals = tuple(signals)
        self.on_preempt = on_preempt
        self.raise_on_signal = raise_on_signal
        self.coordinator = coordinator
        self._stop = threading.Event()
        self._agreed = False
        self._finalized = False
        self._announced = False
        self._received: Optional[int] = None
        self._prev = {}
        self._installed = False

    # ---- lifecycle ------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        try:
            for sig in self.signals:
                self._prev[sig] = signal.signal(sig, self._handler)
            self._installed = True
        except ValueError:  # not the main thread, or a bad signal number
            # undo any handlers already installed this call — a half-armed
            # guard the caller believes is inert must not keep intercepting
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass
            self._prev.clear()
            publish_event(
                "preemption_guard_inert", level="warning",
                emit=self._rank0(),
                reason="signal handlers require the main thread and valid "
                       "signal numbers")
        return self

    def restore(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, exc_type, exc, tb) -> bool:
        handled = exc_type is not None and issubclass(exc_type,
                                                      PreemptionInterrupt)
        try:
            if self.should_stop() and (exc_type is None or handled):
                self.finalize()
        finally:
            self.restore()
        # a PreemptionInterrupt we raised ourselves is handled here, not an
        # error to propagate out of the with-block
        return handled

    # ---- signal path ----------------------------------------------------
    def _handler(self, signum, frame) -> None:
        # no I/O here: stderr may be mid-write in the interrupted frame and
        # CPython forbids reentering a buffered writer — only record the
        # request; the announcement happens in loop context (_announce)
        first = not self._stop.is_set()
        self._stop.set()
        self._received = signum
        if first:
            if self.raise_on_signal:
                raise PreemptionInterrupt(signum)
        else:
            # second signal: operator insists — restore default handling
            # and re-deliver so THIS signal terminates the process
            self.restore()
            os.kill(os.getpid(), signum)

    def request_stop(self, signum: int = _PROGRAMMATIC) -> None:
        """Programmatic preemption: an orchestrator's drain command (or a
        test's fake signal) follows the exact save-and-stop path a SIGTERM
        does — including the cross-process agreement in coordinated mode."""
        if self._received is None:
            self._received = signum
        self._stop.set()

    def _rank0(self) -> bool:
        if self.coordinator is not None:
            return self.coordinator.process_index == 0
        return is_rank_zero()

    def _announce(self) -> None:
        if self._announced or not self._stop.is_set():
            return
        self._announced = True
        # console banner on rank 0 only (an N-host preemption must not
        # print N interleaved banners); the bus record fires on every rank
        # so per-host consumers (goodput ledger, JSONL mirror) all see it
        publish_event(
            "preemption_requested", level="warning", emit=self._rank0(),
            signal=(int(self._received)
                    if self._received is not None else None),
            origin=("peer" if self._received is None else
                    "request_stop" if self._received == _PROGRAMMATIC
                    else "signal"),
            action="finishing step, then final save")

    # ---- loop API -------------------------------------------------------
    def should_stop(self) -> bool:
        """True once a preemption has been agreed (cheap; poll every step).

        Local mode: true once this process received a signal. Coordinated
        mode (``coordinator`` with world > 1): a collective OR of every
        process's local flag — all processes flip to True at the same call,
        and the agreed result is cached so only pre-agreement polls pay the
        (tiny) collective.
        """
        if self._agreed:
            self._announce()
            return True
        local = self._stop.is_set()
        coord = self.coordinator
        if coord is not None and coord.process_count > 1:
            stop = bool(coord.all_any(local))
            if stop and not local:
                self._stop.set()  # peer-initiated; _received stays None
        else:
            stop = local
        if stop:
            self._agreed = True
            self._announce()
        return stop

    @property
    def received_signal(self) -> Optional[int]:
        return self._received

    def finalize(self) -> bool:
        """Run the registered final synchronous save exactly once. Returns
        True iff the callback ran (idempotent on repeat calls)."""
        self._announce()
        if self._finalized or self.on_preempt is None:
            return False
        self._finalized = True
        self.on_preempt()
        return True
