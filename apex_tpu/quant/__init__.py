"""Block-scale low-precision subsystem (ROADMAP item 4, *MXNorm*).

The framework's first-class quantization layer, built from four pieces:

- :mod:`~apex_tpu.quant.blockscale` — the codec core: symmetric int8
  and MXFP8-style shared-exponent encode/decode with a static block
  size, plus the pure-fp32 numpy reference implementations that stay
  the oracle (property-tested round-trip error bounds in tier-1).
- :mod:`~apex_tpu.quant.kv` — the KV-cache codec glue the serve engine
  consumes: per-token per-HEAD scales (block = ``head_dim``, the only
  granularity compatible with incremental decode appends), storage
  dtypes, and the build-time codec validation every CLI surfaces as
  exit 2.
- :mod:`~apex_tpu.quant.matmul` — per-block weight scales for the
  projection matmuls, block size keyed alongside the tune registry
  (``tuned_params("quant_matmul", ...)``).
- :mod:`~apex_tpu.quant.norms` — the MXNorm layer_norm: mean/variance
  from per-block integer sums rescaled by the SAME block scales the
  quantized matmul carries, instead of re-reducing the dequantized
  activations.

Quality policy (docs/quantization.md): quantized paths are gated by a
TOLERANCE oracle (perplexity delta vs the fp32 engine, documented
bound) — deliberately unlike the serve engine's bit-exact oracles. The
fp32 reference implementations in :mod:`blockscale` are themselves
held bit-exact against the jax codecs, so the tolerance is spent on
quantization error alone, never on implementation drift.
"""

from apex_tpu.quant.blockscale import (decode_int8, decode_int8_ref,
                                       decode_mxfp8, decode_mxfp8_ref,
                                       encode_int8, encode_int8_ref,
                                       encode_mxfp8, encode_mxfp8_ref,
                                       has_float8, int8_error_bound,
                                       mxfp8_error_bound)
from apex_tpu.quant.kv import (KV_CODECS, check_kv_codec, decode_kv,
                               encode_kv, kv_storage_dtype)
from apex_tpu.quant.matmul import (quant_matmul, quantize_weight,
                                   resolve_quant_block)
from apex_tpu.quant.norms import mx_layer_norm

__all__ = [
    "encode_int8", "decode_int8", "encode_mxfp8", "decode_mxfp8",
    "encode_int8_ref", "decode_int8_ref", "encode_mxfp8_ref",
    "decode_mxfp8_ref",
    "has_float8", "int8_error_bound", "mxfp8_error_bound",
    "KV_CODECS", "check_kv_codec", "encode_kv", "decode_kv",
    "kv_storage_dtype",
    "quantize_weight", "quant_matmul", "resolve_quant_block",
    "mx_layer_norm",
]
