"""Block-scale codec core: symmetric int8 and MXFP8 shared-exponent.

Both codecs share one shape contract: the input's LAST axis is split
into contiguous blocks of a static ``block`` size, each block gets one
fp32 scale, and encode returns ``(codes, scales)`` where ``codes`` has
the input shape (storage dtype) and ``scales`` has the input shape
with the last axis divided by ``block``.

Codecs
------
``int8``   symmetric linear: ``scale = amax / 127`` per block,
           ``q = clip(round(x / scale), -127, 127)`` stored as int8.
           Zero blocks take ``scale = 1.0`` so decode is exact there.
``mxfp8``  MXFP-style shared exponent: the per-block scale is the
           smallest POWER OF TWO ``2**e`` such that ``amax / 2**e``
           fits in float8_e4m3fn (max normal 448); the payload is the
           rescaled value cast to ``float8_e4m3fn`` (1 byte). e4m3fn
           has no inf — overflow saturates via an explicit clamp.

Oracles: ``encode_*_ref`` / ``decode_*_ref`` are pure-numpy fp32
implementations, property-tested against the jax codecs in
``tests/test_quant.py``. int8 is BIT-EXACT both ways. mxfp8 scales
are bit-exact; the payload may differ by at most ONE e4m3 grid step
on near-tie values — XLA's compiled f32->f8 convert double-rounds
through an intermediate precision (observed on CPU: -11.49896 casts
to -12 where ml_dtypes' direct round-to-nearest gives -11). Both
spellings stay inside the round-trip error bound below, which is the
contract the engine's quality gate rides on.

Error bounds (tested, not just documented):

- int8:  ``|x - dec(enc(x))| <= scale / 2`` per element (round-to-
  nearest on a linear grid of pitch ``scale``).
- mxfp8: ``|x - dec(enc(x))| <= |x| * 2**-3 + scale * 2**-9`` — e4m3
  has 3 mantissa bits (relative error ``2**-3`` covers round-to-
  nearest-even generously) and the subnormal grid near zero has pitch
  ``2**-9`` in rescaled units.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

INT8_QMAX = 127.0
# float8_e4m3fn: max normal = 1.75 * 2**8 = 448, no inf (overflow is
# NaN without the clamp below), smallest subnormal = 2**-9.
MXFP8_MAX = 448.0
_F32 = jnp.float32


def has_float8() -> bool:
    """Whether this jax build exposes ``float8_e4m3fn`` storage."""
    return hasattr(jnp, "float8_e4m3fn")


def _check_block(x_shape, block: int) -> None:
    block = int(block)
    if block <= 0:
        raise ValueError(f"quant block must be positive, got {block}")
    last = int(x_shape[-1])
    if last % block != 0:
        raise ValueError(
            f"quant block {block} does not divide last axis {last}")


def _blocked(x: jnp.ndarray, block: int) -> jnp.ndarray:
    return x.reshape(x.shape[:-1] + (x.shape[-1] // block, block))


# ---------------------------------------------------------------- int8

def encode_int8(x: jnp.ndarray, block: int):
    """Symmetric per-block int8. Returns ``(codes int8, scales f32)``."""
    _check_block(x.shape, block)
    xb = _blocked(x.astype(_F32), block)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, jnp.ones_like(amax))
    q = jnp.clip(jnp.round(xb / scale[..., None]), -INT8_QMAX, INT8_QMAX)
    return q.astype(jnp.int8).reshape(x.shape), scale


def decode_int8(codes: jnp.ndarray, scales: jnp.ndarray, block: int):
    _check_block(codes.shape, block)
    qb = _blocked(codes.astype(_F32), block)
    return (qb * scales[..., None]).reshape(codes.shape)


def encode_int8_ref(x: np.ndarray, block: int):
    """Pure-numpy fp32 reference; bit-exact vs :func:`encode_int8`."""
    _check_block(x.shape, block)
    xb = np.asarray(x, np.float32)
    xb = xb.reshape(xb.shape[:-1] + (xb.shape[-1] // block, block))
    amax = np.max(np.abs(xb), axis=-1)
    scale = np.where(amax > 0, amax / np.float32(INT8_QMAX),
                     np.float32(1.0)).astype(np.float32)
    q = np.clip(np.round(xb / scale[..., None]), -INT8_QMAX, INT8_QMAX)
    return q.astype(np.int8).reshape(x.shape), scale


def decode_int8_ref(codes: np.ndarray, scales: np.ndarray, block: int):
    qb = np.asarray(codes, np.float32)
    qb = qb.reshape(qb.shape[:-1] + (qb.shape[-1] // block, block))
    out = qb * np.asarray(scales, np.float32)[..., None]
    return out.astype(np.float32).reshape(codes.shape)


def int8_error_bound(scales, block: int, shape) -> np.ndarray:
    """Per-element bound on ``|x - roundtrip(x)|``: half a grid step."""
    s = np.asarray(scales, np.float32)[..., None]
    b = np.broadcast_to(s / 2, s.shape[:-1] + (block,))
    return b.reshape(shape) + np.float32(1e-7)


# --------------------------------------------------------------- mxfp8

def _mxfp8_scale(amax: jnp.ndarray) -> jnp.ndarray:
    # Smallest power of two 2**e with amax / 2**e <= 448. ceil(log2)
    # over-shoots by at most one binade, which only costs the bottom
    # subnormal bit — the error bound below already covers it. ldexp,
    # NOT exp2: XLA lowers exp2 through a polynomial whose result is
    # off by an ulp at large |e| (2**-29 came back 1.8626442e-09),
    # silently breaking the exact-power-of-two scale contract.
    tiny = jnp.float32(np.finfo(np.float32).tiny)
    e = jnp.ceil(jnp.log2(jnp.maximum(amax, tiny) / MXFP8_MAX))
    pow2 = jnp.ldexp(jnp.ones_like(amax), e.astype(jnp.int32))
    return jnp.where(amax > 0, pow2, jnp.ones_like(amax))


def encode_mxfp8(x: jnp.ndarray, block: int):
    """MXFP8: power-of-two block scale + float8_e4m3fn payload."""
    if not has_float8():
        raise ValueError(
            "mxfp8 codec requires jax.numpy.float8_e4m3fn support")
    _check_block(x.shape, block)
    xb = _blocked(x.astype(_F32), block)
    scale = _mxfp8_scale(jnp.max(jnp.abs(xb), axis=-1))
    y = jnp.clip(xb / scale[..., None], -MXFP8_MAX, MXFP8_MAX)
    return y.astype(jnp.float8_e4m3fn).reshape(x.shape), scale


def decode_mxfp8(codes: jnp.ndarray, scales: jnp.ndarray, block: int):
    _check_block(codes.shape, block)
    qb = _blocked(codes.astype(_F32), block)
    return (qb * scales[..., None]).reshape(codes.shape)


def encode_mxfp8_ref(x: np.ndarray, block: int):
    """Pure-numpy reference: same scale rule, payload via ml_dtypes."""
    import ml_dtypes  # ships with jax; not a new dependency
    _check_block(x.shape, block)
    xb = np.asarray(x, np.float32)
    xb = xb.reshape(xb.shape[:-1] + (xb.shape[-1] // block, block))
    amax = np.max(np.abs(xb), axis=-1)
    tiny = np.finfo(np.float32).tiny
    e = np.ceil(np.log2(np.maximum(amax, tiny) / np.float32(MXFP8_MAX)))
    pow2 = np.ldexp(np.float32(1.0), e.astype(np.int32))
    scale = np.where(amax > 0, pow2, np.float32(1.0)).astype(np.float32)
    y = np.clip(xb / scale[..., None], -MXFP8_MAX, MXFP8_MAX)
    codes = y.astype(ml_dtypes.float8_e4m3fn).reshape(x.shape)
    return codes, scale


def decode_mxfp8_ref(codes: np.ndarray, scales: np.ndarray, block: int):
    qb = np.asarray(codes, np.float32)
    qb = qb.reshape(qb.shape[:-1] + (qb.shape[-1] // block, block))
    out = qb * np.asarray(scales, np.float32)[..., None]
    return out.astype(np.float32).reshape(codes.shape)


def mxfp8_error_bound(x, scales, block: int) -> np.ndarray:
    """Per-element bound: 3 mantissa bits + subnormal grid pitch."""
    xa = np.abs(np.asarray(x, np.float32))
    s = np.asarray(scales, np.float32)[..., None]
    s = np.broadcast_to(s, s.shape[:-1] + (block,)).reshape(xa.shape)
    return xa * np.float32(2.0 ** -3) + s * np.float32(2.0 ** -9)
