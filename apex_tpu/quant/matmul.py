"""Quantized projection matmuls with per-block weight scales.

Weights are quantized along the REDUCTION axis in contiguous blocks of
``block`` elements: ``w [in, out]`` becomes int8 codes ``[in, out]``
plus fp32 scales ``[in // block, out]``. The matmul accumulates one
fp32 partial per block and applies that block's scale before the final
sum:

    y[s, o] = sum_n ( sum_b x[s, n*B + b] * q[n*B + b, o] ) * scale[n, o]

Dequantize-then-matmul and blockwise-rescale differ only in float
association, so the oracle here is a TOLERANCE against the fp32
matmul of the dequantized weight (see docs/quantization.md) — unlike
the serve engine's bit-exact oracles.

The block size is keyed alongside the tune registry
(``tuned_params("quant_matmul", ...)``) so a tuning sweep can pin a
different block per (shape-bucket, dtype, chip) exactly like Pallas
tile geometry; the default is the largest power of two ≤ 128 dividing
the reduction dim.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from apex_tpu.quant import blockscale
from apex_tpu.tune.api import pow2_bucket, tuned_params

_F32 = jnp.float32


def _default_block(in_dim: int) -> int:
    b = 1
    while b * 2 <= min(int(in_dim), 128) and in_dim % (b * 2) == 0:
        b *= 2
    return b


def resolve_quant_block(in_dim: int, out_dim: int, *, dtype=jnp.int8,
                        block: Optional[int] = None,
                        interpret: Optional[bool] = None) -> int:
    """Pick the weight-scale block for an ``[in_dim, out_dim]`` matmul:
    explicit override > tuned cache entry > largest pow2 divisor ≤ 128."""
    if block is not None:
        if in_dim % int(block) != 0:
            raise ValueError(
                f"quant block {block} does not divide in_dim {in_dim}")
        return int(block)
    shape_key = (("in", int(in_dim)), ("out", pow2_bucket(int(out_dim))))
    params = tuned_params(
        "quant_matmul", shape_key, {"block": _default_block(in_dim)},
        dtype=dtype, interpret=interpret,
        validate=lambda p: in_dim % int(p["block"]) == 0)
    return int(params["block"])


def quantize_weight(w: jnp.ndarray, block: int):
    """Encode ``w [in, out]`` -> int8 codes ``[in, out]`` + fp32 scales
    ``[in // block, out]`` (per-block along the reduction axis)."""
    if w.ndim != 2:
        raise ValueError(f"quantize_weight expects [in, out], got {w.shape}")
    codes_t, scales_t = blockscale.encode_int8(w.T, block)
    return codes_t.T, scales_t.T


def quant_matmul(x: jnp.ndarray, codes: jnp.ndarray, scales: jnp.ndarray,
                 block: int) -> jnp.ndarray:
    """``x [..., in] @ dequant(codes, scales) [in, out]`` with the scale
    applied per reduction block on the fp32 partials."""
    in_dim, out_dim = codes.shape
    if x.shape[-1] != in_dim:
        raise ValueError(
            f"x last axis {x.shape[-1]} != weight in_dim {in_dim}")
    n = in_dim // block
    lead = x.shape[:-1]
    xb = x.astype(_F32).reshape((-1, n, block))
    wb = codes.astype(_F32).reshape((n, block, out_dim))
    partials = jnp.einsum("snb,nbo->sno", xb, wb)
    y = jnp.sum(partials * scales.astype(_F32)[None], axis=1)
    return y.reshape(lead + (out_dim,))
