"""MXNorm: layer_norm that REUSES the matmul's block scales.

A quantized activation already carries per-block scales from the
codec. The naive quantized layer_norm dequantizes and re-reduces the
fp32 vector twice (mean, then variance). MXNorm (PAPERS.md) observes
that both moments factor through the scales:

    sum(x)   = sum_n scale[n] *  sum_b q[n, b]
    sum(x^2) = sum_n scale[n]^2 * sum_b q[n, b]^2

so the inner reductions run on the raw codes — on hardware with int8
reduction units that halves the normalization bandwidth, and in XLA it
keeps the moment math in one rescale per block instead of one per
element. The normalized output still needs the per-element dequant
(that part is irreducible), but the statistics never touch it.

Tolerance oracle: ``manual_layer_norm(dequant(x))``. The blockwise
moment association and the ``E[x^2] - mean^2`` variance form both
differ from the reference's two-pass fp32 reduction only in float
association, so the test bound is a documented tolerance, not
bit-exactness.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_F32 = jnp.float32


def mx_layer_norm(codes: jnp.ndarray, scales: jnp.ndarray,
                  weight, bias, block: int, eps: float = 1e-5):
    """Layer-normalize a block-quantized activation ``[..., H]`` whose
    per-block scales are ``[..., H // block]``, reusing those scales for
    the moment computation instead of re-reducing the dequantized
    vector."""
    h = int(codes.shape[-1])
    if h % int(block) != 0:
        raise ValueError(f"quant block {block} does not divide {h}")
    n = h // int(block)
    qb = codes.astype(_F32).reshape(codes.shape[:-1] + (n, int(block)))
    s = scales.astype(_F32)
    s1 = jnp.sum(qb, axis=-1)            # per-block integer sums
    s2 = jnp.sum(qb * qb, axis=-1)
    mean = jnp.sum(s1 * s, axis=-1, keepdims=True) / h
    ex2 = jnp.sum(s2 * s * s, axis=-1, keepdims=True) / h
    var = jnp.maximum(ex2 - mean * mean, 0.0)
    x = (qb * s[..., None]).reshape(codes.shape)   # per-element dequant
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(_F32)
    if bias is not None:
        y = y + bias.astype(_F32)
    return y
