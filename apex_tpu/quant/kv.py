"""KV-cache codec glue consumed by the serve engine.

Block granularity for KV is fixed at ONE (token, head) vector of
``head_dim`` elements. That is the only block shape compatible with
incremental decode: each step appends exactly one token row per head,
so its scale can be computed and written in the same masked
read-modify-write as the payload, and scales inherit every page
behaviour (prefix sharing, COW, LRU eviction, export/import streaming)
by living in arrays shaped like the payload minus the head_dim axis:

- slot layout:   k/v ``[n_layer, slots, max_len, heads, head_dim]``
                 scales ``[n_layer, slots, max_len, heads]``
- paged layout:  k/v ``[n_layer, pages, page_size, heads, head_dim]``
                 scales ``[n_layer, pages, page_size, heads]``

Scales are fp32. Per head_dim=D that is ``D * storage + 4`` bytes per
(token, head) vs ``4 * D`` unquantized — e.g. D=64: 68 vs 256 bytes,
a 3.76× capacity win; the ``resident_tokens_per_hbm_byte`` gate in
the bench holds the ≥~2× floor.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.quant import blockscale

KV_CODECS = ("int8", "mxfp8")


def check_kv_codec(codec) -> None:
    """Build-time validation; every CLI surfaces this as exit 2."""
    if codec is None:
        return
    if codec not in KV_CODECS:
        raise ValueError(
            f"unknown kv_quant codec {codec!r}; expected one of "
            f"{KV_CODECS} or None")
    if codec == "mxfp8" and not blockscale.has_float8():
        raise ValueError(
            "kv_quant='mxfp8' requires float8_e4m3fn support in this "
            "jax build")


def kv_storage_dtype(codec):
    """Storage dtype for K/V payload arrays under ``codec``."""
    check_kv_codec(codec)
    if codec is None:
        return None
    if codec == "int8":
        return jnp.int8
    return jnp.float8_e4m3fn


def encode_kv(codec: str, x: jnp.ndarray):
    """Encode ``[..., heads, head_dim]`` -> (codes, scales[..., heads])."""
    block = int(x.shape[-1])
    if codec == "int8":
        codes, scales = blockscale.encode_int8(x, block)
    elif codec == "mxfp8":
        codes, scales = blockscale.encode_mxfp8(x, block)
    else:
        raise ValueError(f"unknown kv_quant codec {codec!r}")
    # block == head_dim, so the blocked codec emits exactly one scale
    # per (token, head): drop that singleton block axis — KV scale
    # planes are shaped like the payload minus head_dim
    return codes, scales[..., 0]


def decode_kv(codes: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """Dequantize per-(token, head) codes back to fp32."""
    return codes.astype(jnp.float32) * scales[..., None]
