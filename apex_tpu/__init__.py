"""apex_tpu — a TPU-native (JAX/XLA/Pallas) framework with the capabilities of NVIDIA/apex.

Reference surface: ``apex/__init__.py:14-18`` exports ``optimizers`` and ``normalization``;
this package additionally re-exposes the capabilities of the removed-but-in-scope legacy
packages (``apex.amp``, ``apex.parallel``) and ``apex.contrib`` as TPU-idiomatic
equivalents (see SURVEY.md).

Design notes
------------
- Compute path is JAX/XLA with Pallas kernels for the hot ops (optimizer updates,
  normalization, softmax, attention). Everything is jittable and shardable with
  ``jax.sharding`` over a ``Mesh``.
- Mixed precision is bf16-first: the fp16 dynamic-loss-scaling machinery of the
  reference (``csrc/multi_tensor_scale_kernel.cu``, ``csrc/update_scale_hysteresis.cu``)
  exists as an optional, fully-jitted state machine in :mod:`apex_tpu.amp`.
- Distributed training rides XLA collectives over ICI/DCN (psum / psum_scatter /
  all_gather / ppermute) instead of NCCL; see :mod:`apex_tpu.parallel` and the
  distributed optimizers in :mod:`apex_tpu.optimizers`.
"""

from apex_tpu import optimizers  # noqa: F401
from apex_tpu import normalization  # noqa: F401
from apex_tpu import multi_tensor  # noqa: F401
from apex_tpu import amp  # noqa: F401
from apex_tpu import parallel  # noqa: F401
from apex_tpu import ops  # noqa: F401
from apex_tpu import contrib  # noqa: F401
from apex_tpu import utils  # noqa: F401
from apex_tpu import resilience  # noqa: F401
from apex_tpu import monitor  # noqa: F401
from apex_tpu import tune  # noqa: F401
from apex_tpu import serve  # noqa: F401
from apex_tpu import train  # noqa: F401

__version__ = "0.1.0"

from apex_tpu.utils.logging import (  # noqa: F401,E402
    deprecated_warning, one_time_warning)
