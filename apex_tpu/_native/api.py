"""Python API over the native helpers, with pure-Python fallbacks.

Used by utils.flatten (flat planning), parallel.ddp (bucket planning), and
checkpoint staging (pack/unpack). Each function works identically with or
without the compiled library.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Sequence, Tuple

import numpy as np

from apex_tpu._native.build import get_lib


def plan_flat(sizes: Sequence[int], align: int = 128
              ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Returns (offsets, padded_sizes, total)."""
    n = len(sizes)
    lib = get_lib()
    sizes_a = np.asarray(sizes, np.int64)
    offsets = np.empty(n, np.int64)
    padded = np.empty(n, np.int64)
    if lib is not None and n:
        total = lib.plan_flat(
            sizes_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, align,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            padded.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return offsets, padded, int(total)
    off = 0
    for i, s in enumerate(sizes_a):
        s = max(int(s), 1)
        p = (s + align - 1) // align * align
        offsets[i] = off
        padded[i] = p
        off += p
    return offsets, padded, off


def plan_buckets(sizes: Sequence[int], dtype_ids: Sequence[int],
                 message_size: int) -> Tuple[np.ndarray, int]:
    """Returns (bucket_id per leaf, n_buckets) — per-dtype greedy fill."""
    n = len(sizes)
    lib = get_lib()
    sizes_a = np.asarray(sizes, np.int64)
    dts = np.asarray(dtype_ids, np.int32)
    out = np.empty(n, np.int32)
    if lib is not None and n:
        nb = lib.plan_buckets(
            sizes_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            dts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n,
            message_size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out, int(nb)
    # python fallback (mirror of the C logic)
    next_bucket = 0
    seen = []
    for d in dts:
        if d not in seen:
            seen.append(d)
    for d in seen:
        cur, cur_n = -1, 0
        for i in range(n):
            if dts[i] != d:
                continue
            if cur < 0:
                cur = next_bucket
                next_bucket += 1
            out[i] = cur
            cur_n += max(int(sizes_a[i]), 1)
            if cur_n >= message_size:
                cur, cur_n = -1, 0
    return out, next_bucket


def pack_arrays(arrays: Sequence[np.ndarray], offsets_bytes: Sequence[int],
                total_bytes: int, num_threads: int = 0) -> np.ndarray:
    """Gather host arrays into one byte buffer (threaded memcpy)."""
    lib = get_lib()
    n = len(arrays)
    # zero-filled so alignment-padding gaps are deterministic (checkpoint
    # buffers get hashed/compared)
    dst = np.zeros(total_bytes, np.uint8)
    arrays = [np.ascontiguousarray(a) for a in arrays]
    if lib is not None and n:
        srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
        nbytes = np.asarray([a.nbytes for a in arrays], np.int64)
        offs = np.asarray(offsets_bytes, np.int64)
        nt = num_threads or min(os.cpu_count() or 1, 8)
        lib.pack_bytes(
            ctypes.cast(srcs, ctypes.POINTER(ctypes.c_void_p)),
            nbytes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), nt)
        return dst
    for a, off in zip(arrays, offsets_bytes):
        dst[off:off + a.nbytes] = a.view(np.uint8).ravel()
    return dst


def unpack_arrays(buf: np.ndarray, offsets_bytes: Sequence[int],
                  shapes: Sequence[tuple], dtypes: Sequence,
                  num_threads: int = 0) -> List[np.ndarray]:
    """Scatter a byte buffer back into arrays (threaded memcpy when native)."""
    n = len(offsets_bytes)
    outs = []
    nbytes = []
    for shape, dt in zip(shapes, dtypes):
        count = int(np.prod(shape)) if shape else 1
        outs.append(np.empty(shape, dt))
        nbytes.append(count * np.dtype(dt).itemsize)
    lib = get_lib()
    buf = np.ascontiguousarray(buf)
    if lib is not None and n:
        dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
        nb = np.asarray(nbytes, np.int64)
        offs = np.asarray(offsets_bytes, np.int64)
        nt = num_threads or min(os.cpu_count() or 1, 8)
        lib.unpack_bytes(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            nb.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            ctypes.cast(dsts, ctypes.POINTER(ctypes.c_void_p)), nt)
        return outs
    for o, off, nb_i in zip(outs, offsets_bytes, nbytes):
        o.view(np.uint8).reshape(-1)[:] = buf[off:off + nb_i]
    return outs


def plan_fragments(offsets: Sequence[int], sizes: Sequence[int],
                   shard_size: int):
    """ZeRO fragment table: per (leaf × shard) overlap ranges.

    Returns dict of arrays: leaf, shard, leaf_begin, leaf_end, shard_begin.
    """
    n = len(offsets)
    lib = get_lib()
    offs = np.asarray(offsets, np.int64)
    szs = np.asarray(sizes, np.int64)
    if lib is not None and n:
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        count = lib.plan_fragments(
            offs.ctypes.data_as(i64p), szs.ctypes.data_as(i64p), n,
            shard_size, None, None, None, None, None)
        leaf = np.empty(count, np.int32)
        shard = np.empty(count, np.int32)
        lb = np.empty(count, np.int64)
        le = np.empty(count, np.int64)
        sb = np.empty(count, np.int64)
        lib.plan_fragments(
            offs.ctypes.data_as(i64p), szs.ctypes.data_as(i64p), n,
            shard_size,
            leaf.ctypes.data_as(i32p), shard.ctypes.data_as(i32p),
            lb.ctypes.data_as(i64p), le.ctypes.data_as(i64p),
            sb.ctypes.data_as(i64p))
        return {"leaf": leaf, "shard": shard, "leaf_begin": lb,
                "leaf_end": le, "shard_begin": sb}
    leaf, shard, lb, le, sb = [], [], [], [], []
    for i in range(n):
        beg, end = int(offs[i]), int(offs[i] + szs[i])
        s = beg // shard_size
        while s * shard_size < end:
            s0, s1 = s * shard_size, (s + 1) * shard_size
            ob, oe = max(beg, s0), min(end, s1)
            if oe > ob:
                leaf.append(i)
                shard.append(s)
                lb.append(ob - beg)
                le.append(oe - beg)
                sb.append(ob - s0)
            s += 1
    return {"leaf": np.asarray(leaf, np.int32),
            "shard": np.asarray(shard, np.int32),
            "leaf_begin": np.asarray(lb, np.int64),
            "leaf_end": np.asarray(le, np.int64),
            "shard_begin": np.asarray(sb, np.int64)}
