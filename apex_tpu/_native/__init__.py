from apex_tpu._native.build import get_lib, native_available  # noqa: F401
from apex_tpu._native.api import (  # noqa: F401
    pack_arrays,
    plan_buckets,
    plan_flat,
    plan_fragments,
    unpack_arrays,
)
