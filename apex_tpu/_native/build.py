"""Build + load the native helper library (apex_tpu/_csrc/apex_tpu_native.cpp).

No pybind11 in this image → plain C ABI + ctypes. Compiled lazily on first
use with g++; failures degrade to the pure-Python paths (native is an
accelerator, never a requirement — unlike the reference, where a missing
extension disables the feature, setup.py:24-46).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                    "_csrc", "apex_tpu_native.cpp")
_OUT = os.path.join(os.path.dirname(__file__), "_apex_tpu_native.so")


def _compile() -> str | None:
    try:
        if os.path.exists(_OUT) and (not os.path.exists(_SRC)
                                     or os.path.getmtime(_OUT)
                                     >= os.path.getmtime(_SRC)):
            return _OUT
    except OSError:
        pass
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", _OUT],
            check=True, capture_output=True, timeout=120)
        return _OUT
    except Exception:
        return None


def get_lib():
    """Returns the loaded ctypes library or None (Python fallback)."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u8pp = ctypes.POINTER(ctypes.c_void_p)
        lib.plan_flat.restype = ctypes.c_int64
        lib.plan_flat.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64,
                                  i64p, i64p]
        lib.plan_buckets.restype = ctypes.c_int64
        lib.plan_buckets.argtypes = [i64p, i32p, ctypes.c_int64,
                                     ctypes.c_int64, i32p]
        lib.pack_bytes.restype = None
        lib.pack_bytes.argtypes = [u8pp, i64p, i64p, ctypes.c_int64,
                                   u8p, ctypes.c_int32]
        lib.unpack_bytes.restype = None
        lib.unpack_bytes.argtypes = [u8p, i64p, i64p, ctypes.c_int64,
                                     u8pp, ctypes.c_int32]
        lib.plan_fragments.restype = ctypes.c_int64
        lib.plan_fragments.argtypes = [i64p, i64p, ctypes.c_int64,
                                       ctypes.c_int64, i32p, i32p, i64p,
                                       i64p, i64p]
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return get_lib() is not None
