"""Weight-gradient GEMM with fp32 accumulation — TPU equivalent of
``fused_weight_gradient_mlp_cuda`` (csrc/megatron/fused_weight_gradient_dense.cpp:11-13:
wgrad GEMM accumulating directly into the main grad buffer in fp32/fp16).

This is the tensor-parallel wgrad primitive: low-precision activations/grads,
high-precision gradient accumulator that survives many micro-batches.
On TPU: one ``dot_general`` with ``preferred_element_type=f32`` (MXU
accumulates in fp32 natively) added into the donated main_grad buffer — XLA
fuses the add into the matmul epilogue, giving the same
"accumulate into main_grad without a round-trip" behavior.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def wgrad_gemm_accum_fp32(input_: jax.Array, grad_output: jax.Array,
                          main_grad: jax.Array) -> jax.Array:
    """main_grad += grad_output^T @ input, accumulated in fp32.

    input_: (..., in), grad_output: (..., out), main_grad: (out, in) fp32.
    Returns the updated main_grad (donate it under jit for in-place).
    """
    bdims = tuple(range(input_.ndim - 1))
    acc = jax.lax.dot_general(
        grad_output, input_, ((bdims, bdims), ((), ())),
        preferred_element_type=_f32)
    return main_grad + acc


def wgrad_gemm_accum_fp16(input_: jax.Array, grad_output: jax.Array,
                          main_grad: jax.Array) -> jax.Array:
    """Low-precision accumulator variant (``wgrad_gemm_accum_fp16``). The
    MXU still computes in fp32; only the accumulator storage is low precision."""
    bdims = tuple(range(input_.ndim - 1))
    acc = jax.lax.dot_general(
        grad_output, input_, ((bdims, bdims), ((), ())),
        preferred_element_type=_f32)
    return (main_grad.astype(_f32) + acc).astype(main_grad.dtype)
