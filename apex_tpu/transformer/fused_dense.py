"""Fused dense layers — TPU equivalent of ``fused_dense_cuda``
(csrc/fused_dense.cpp:161-166 API: linear_bias_forward/backward,
linear_gelu_linear_forward/backward over cuBLASLt epilogues) and the frontend
``apex/fused_dense/fused_dense.py`` (FusedDense :78, FusedDenseGeluDense :97).

TPU design: the cuBLASLt bias/GeLU epilogues exist because separate CUDA
kernels would round-trip HBM; XLA fuses bias-add and GeLU into the matmul's
epilogue automatically, so the functional forms below compile to exactly the
fused form the reference hand-builds. What we add on top:
- bf16-first matmuls with fp32 accumulation (``preferred_element_type``), the
  MXU-correct configuration;
- a custom VJP for dense_gelu_dense that saves only (x, pre-GeLU) — the same
  residual set the reference's fused backward consumes — instead of autodiff's
  default (which would also save the GeLU output);
- wgrad in fp32 regardless of IO dtype (matching fused wgrad behavior).
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

_f32 = jnp.float32


def linear_bias(x: jax.Array, weight: jax.Array,
                bias: Optional[jax.Array]) -> jax.Array:
    """y = x @ W^T + b (torch Linear convention: weight (out, in))."""
    y = jax.lax.dot_general(
        x, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=_f32)
    if bias is not None:
        y = y + bias.astype(_f32)
    return y.astype(x.dtype)


@jax.custom_vjp
def dense_gelu_dense(x, w1, b1, w2, b2):
    """GEMM → bias → GeLU → GEMM → bias, one fused fwd/bwd pair
    (≈ linear_gelu_linear_forward/backward, fused_dense.cpp:164-166)."""
    h = jax.lax.dot_general(x, w1, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    h = h + b1.astype(_f32)
    a = jax.nn.gelu(h, approximate=False)
    y = jax.lax.dot_general(a.astype(x.dtype), w2,
                            (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    y = y + b2.astype(_f32)
    return y.astype(x.dtype)


def _dgd_fwd(x, w1, b1, w2, b2):
    h = jax.lax.dot_general(x, w1, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    h = h + b1.astype(_f32)
    a = jax.nn.gelu(h, approximate=False)
    y = jax.lax.dot_general(a.astype(x.dtype), w2,
                            (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    y = y + b2.astype(_f32)
    # residuals: x and pre-GeLU h only (gelu output recomputed in bwd)
    return y.astype(x.dtype), (x, w1, w2, h)


def _gelu_grad(h):
    # exact gelu'(h), h in fp32
    import math
    c = 1.0 / math.sqrt(2.0)
    phi = 0.5 * (1.0 + jax.lax.erf(h * c))
    pdf = jnp.exp(-0.5 * h * h) / math.sqrt(2.0 * math.pi)
    return phi + h * pdf


def _dgd_bwd(res, dy):
    x, w1, w2, h = res
    dy32 = dy.astype(_f32)
    a = jax.nn.gelu(h, approximate=False)
    bdims = tuple(range(x.ndim - 1))
    # second GEMM grads (wgrad in fp32)
    dw2 = jax.lax.dot_general(dy32, a, ((bdims, bdims), ((), ())),
                              preferred_element_type=_f32)
    db2 = jnp.sum(dy32, axis=bdims)
    da = jax.lax.dot_general(dy32, w2.astype(_f32),
                             (((x.ndim - 1,), (0,)), ((), ())),
                             preferred_element_type=_f32)
    dh = da * _gelu_grad(h)
    dw1 = jax.lax.dot_general(dh, x.astype(_f32), ((bdims, bdims), ((), ())),
                              preferred_element_type=_f32)
    db1 = jnp.sum(dh, axis=bdims)
    dx = jax.lax.dot_general(dh, w1.astype(_f32),
                             (((x.ndim - 1,), (0,)), ((), ())),
                             preferred_element_type=_f32)
    return (dx.astype(x.dtype), dw1.astype(w1.dtype), db1.astype(w1.dtype),
            dw2.astype(w2.dtype), db2.astype(w2.dtype))


dense_gelu_dense.defvjp(_dgd_fwd, _dgd_bwd)


class FusedDense(nn.Module):
    """flax module ≈ apex.fused_dense.FusedDense (fused_dense.py:78)."""

    in_features: int
    out_features: int
    use_bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features),
                       self.param_dtype)
        b = (self.param("bias", nn.initializers.zeros, (self.out_features,),
                        self.param_dtype) if self.use_bias else None)
        return linear_bias(x, w, b)


class FusedDenseGeluDense(nn.Module):
    """flax module ≈ apex.fused_dense.FusedDenseGeluDense (fused_dense.py:97)."""

    in_features: int
    intermediate_features: int
    out_features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w1 = self.param("weight1", nn.initializers.lecun_normal(),
                        (self.intermediate_features, self.in_features),
                        self.param_dtype)
        b1 = self.param("bias1", nn.initializers.zeros,
                        (self.intermediate_features,), self.param_dtype)
        w2 = self.param("weight2", nn.initializers.lecun_normal(),
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = self.param("bias2", nn.initializers.zeros, (self.out_features,),
                        self.param_dtype)
        return dense_gelu_dense(x, w1, b1, w2, b2)
