"""Fused rotary positional embedding — TPU equivalent of
``fused_rotary_positional_embedding`` (csrc/megatron/fused_rotary_positional_embedding.{h,cu,cpp}).

Variants mirrored (fused_rotary_positional_embedding.cpp:176-193):
- ``fused_rope(t, freqs)``            sbhd layout (s, b, h, d)
- ``fused_rope_cached(t, cos, sin)``  precomputed cos/sin tables
- ``fused_rope_thd(t, cu_seqlens, freqs)``  packed variable-length batches
- ``fused_rope_2d(t, freqs_h, freqs_w)``    image (2D) rotary

Rotation rule (fused_rope_block_forward, .h:28-61): only the first ``d2 =
freqs.shape[-1]`` channels rotate; NeoX rotate-half pairing
``out[d] = in[d]·cos(f[d]) + rot_half(in)[d]·sin(f[d])`` with
``rot_half(x)[d] = -x[d+d2/2]`` for d < d2/2 else ``x[d-d2/2]``; trailing
``d-d2`` channels pass through. Backward = rotation by -f (the reference's
separate backward kernel, .h:63-97) — expressed here via custom_vjp so autodiff
never materializes intermediate products.

All math fp32; IO dtype preserved. XLA fuses the elementwise chain; there is
no launch overhead to amortize, so no Pallas kernel is needed for this op.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _rot_half(x):
    d2 = x.shape[-1]
    a, b = x[..., : d2 // 2], x[..., d2 // 2:]
    return jnp.concatenate([-b, a], axis=-1)


def _apply_rope(x, cos, sin):
    """x: (..., d); cos/sin broadcastable (..., d2) with d2 <= d."""
    d = x.shape[-1]
    d2 = cos.shape[-1]
    x32 = x.astype(_f32)
    head, tail = x32[..., :d2], x32[..., d2:]
    out = head * cos + _rot_half(head) * sin
    if d2 < d:
        out = jnp.concatenate([out, tail], axis=-1)
    return out.astype(x.dtype)


@jax.custom_vjp
def _rope_cached(x, cos, sin):
    return _apply_rope(x, cos, sin)


def _rope_cached_fwd(x, cos, sin):
    return _apply_rope(x, cos, sin), (cos, sin)


def _rope_cached_bwd(res, dy):
    cos, sin = res
    # inverse rotation: R(-f) == transpose of R(f)
    dx = _apply_rope(dy, cos, -sin)
    return dx, None, None


_rope_cached.defvjp(_rope_cached_fwd, _rope_cached_bwd)


def _offset_slice(table: jax.Array, position_offset, s: int) -> jax.Array:
    """Rows ``position_offset .. position_offset+s`` of a positional table
    whose axis 0 is the position axis. ``position_offset`` may be a python
    int or a traced int32 scalar (a serving decode step's position); the
    table must cover ``position_offset + s`` rows. The static-zero case
    stays a plain slice so existing jaxprs are unchanged."""
    if isinstance(position_offset, int) and position_offset == 0:
        return table[:s]
    return jax.lax.dynamic_slice_in_dim(table, position_offset, s, 0)


def fused_rope(t: jax.Array, freqs: jax.Array,
               transpose_output_memory: bool = False, *,
               position_offset=0) -> jax.Array:
    """sbhd variant: t (s, b, h, d), freqs (s_max, 1, 1, d2) or (s_max, d2).

    ``transpose_output_memory`` is a CUDA memory-layout knob; XLA owns layout
    on TPU — accepted for parity, ignored.

    ``position_offset`` rotates token row ``j`` of ``t`` by frequency row
    ``position_offset + j`` — a single decode token at absolute position
    ``p`` (``t`` of shape (1, b, h, d), ``position_offset=p``) gets exactly
    the rotation token ``p`` of a full-sequence call gets. Accepts a traced
    scalar, so a serving decode step can pass the slot's current length.
    """
    if freqs.ndim == 2:
        freqs = freqs[:, None, None, :]
    freqs = _offset_slice(freqs, position_offset, t.shape[0])
    cos = jnp.cos(freqs.astype(_f32))
    sin = jnp.sin(freqs.astype(_f32))
    return _rope_cached(t, cos, sin)


def fused_rope_cached(t: jax.Array, cos: jax.Array, sin: jax.Array, *,
                      position_offset=0) -> jax.Array:
    """Cached-freqs variant (``fused_rope_forward_cached``).

    ``position_offset`` indexes the cos/sin tables at the tokens' absolute
    positions (axis 0 = position), same contract as :func:`fused_rope`.
    """
    while cos.ndim < t.ndim:
        cos = jnp.expand_dims(cos, 1)
        sin = jnp.expand_dims(sin, 1)
    cos = _offset_slice(cos, position_offset, t.shape[0])
    sin = _offset_slice(sin, position_offset, t.shape[0])
    return _rope_cached(t, cos.astype(_f32), sin.astype(_f32))


def fused_rope_thd(t: jax.Array, cu_seqlens: jax.Array,
                   freqs: jax.Array) -> jax.Array:
    """Packed thd variant (``fused_rope_forward_thd``): t (total_t, h, d);
    ``cu_seqlens`` (b+1,) cumulative sequence starts; each token rotates by its
    position WITHIN its own sequence.

    TPU note: implemented with a vectorized searchsorted over the static token
    axis (no dynamic shapes), so it stays jittable.
    """
    total = t.shape[0]
    tok = jnp.arange(total, dtype=jnp.int32)
    # sequence id of each token, then its in-sequence position
    seq_id = jnp.searchsorted(cu_seqlens.astype(jnp.int32), tok,
                              side="right") - 1
    seq_id = jnp.clip(seq_id, 0, cu_seqlens.shape[0] - 2)
    pos = tok - cu_seqlens.astype(jnp.int32)[seq_id]
    if freqs.ndim > 2:
        freqs = freqs.reshape(freqs.shape[0], freqs.shape[-1])
    f = freqs.astype(_f32)[pos]            # (total_t, d2)
    cos = jnp.cos(f)[:, None, :]           # broadcast over heads
    sin = jnp.sin(f)[:, None, :]
    return _rope_cached(t, cos, sin)


def fused_rope_2d(t: jax.Array, img_h: int, img_w: int,
                  freqs_h: jax.Array, freqs_w: jax.Array) -> jax.Array:
    """2D (image) variant (``fused_rope_forward_2d``): t (b, img_h*img_w, h, d);
    first half of channels rotates by the row frequency, second half by the
    column frequency."""
    b, s, h, d = t.shape
    assert s == img_h * img_w, "sequence must equal img_h*img_w"
    if freqs_h.ndim > 2:
        freqs_h = freqs_h.reshape(freqs_h.shape[-2], freqs_h.shape[-1])
        freqs_w = freqs_w.reshape(freqs_w.shape[-2], freqs_w.shape[-1])
    d2h = freqs_h.shape[-1]
    d2w = freqs_w.shape[-1]
    fh = jnp.repeat(freqs_h.astype(_f32)[:img_h], img_w, axis=0)  # (s, d2h)
    fw = jnp.tile(freqs_w.astype(_f32)[:img_w], (img_h, 1))       # (s, d2w)
    t_h, t_w = t[..., :d2h], t[..., d2h:d2h + d2w]
    rest = t[..., d2h + d2w:]
    out_h = _rope_cached(t_h, jnp.cos(fh)[None, :, None, :],
                         jnp.sin(fh)[None, :, None, :])
    out_w = _rope_cached(t_w, jnp.cos(fw)[None, :, None, :],
                         jnp.sin(fw)[None, :, None, :])
    return jnp.concatenate([out_h, out_w, rest], axis=-1)
