"""Chunked-vocab fused linear + cross-entropy head (beyond-reference).

The reference fuses softmax+CE once logits exist (``xentropy_cuda``,
apex/contrib/csrc/xentropy/ — see ``contrib/xentropy.py`` for that
surface). At LM scale the dominant cost is upstream of that: the logits
matrix itself. GPT-2-xl at b4·s512 holds (2048, 50257) logits — ~400 MB
of fp32 activations plus the same again for autodiff residuals — whose
only purpose is one lse and one gathered label logit per row.

``linear_cross_entropy(hidden, weight, labels)`` computes the LM-head
matmul and the (label-smoothed) cross entropy TOGETHER, scanning the
vocabulary in chunks with an online logsumexp, so the full logits matrix
NEVER exists in HBM — per-chunk (N, C) tiles live transiently and XLA
fuses each chunk's matmul+softmax pipeline. The custom VJP saves only
``(hidden, weight, lse)`` — one fp32 scalar per row, the same residual
discipline as the reference's xentropy (interface.cpp:42-45) — and the
backward re-scans the chunks, rebuilding each logits tile once
(rematerialization: trade MXU FLOPs for HBM capacity, the right trade on
TPU).

TPU-first notes: chunk width defaults to 8192 lanes (64 MXU tiles); the
vocab tail is padded to the chunk grid with columns masked to -inf so
the lse is exact; everything is ``lax.scan`` — one trace, static shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_f32 = jnp.float32
_NEG = -1e30


def _pad_weight(weight, chunk):
    h, v = weight.shape
    vpad = -(-v // chunk) * chunk
    if vpad != v:
        weight = jnp.pad(weight, ((0, 0), (0, vpad - v)))
    return weight, vpad


def _chunk_logits(hidden, wc, c0, chunk, v, logit_scale):
    """One chunk's logits tile (N, C) in fp32, tail columns masked."""
    x = (hidden @ wc).astype(_f32) * logit_scale
    col = c0 + jax.lax.iota(jnp.int32, chunk)[None, :]
    return jnp.where(col < v, x, _NEG), col


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def linear_cross_entropy(hidden: jax.Array, weight: jax.Array,
                         labels: jax.Array, smoothing: float = 0.0,
                         padding_idx: Optional[int] = None,
                         chunk: int = 8192, logit_scale: float = 1.0):
    """Per-row loss of ``softmax_cross_entropy(hidden @ weight, labels)``
    without materializing the logits. hidden: (N, H); weight: (H, V);
    labels: (N,) int32. Returns (N,) fp32 — semantics identical to
    ``contrib.xentropy.softmax_cross_entropy_loss`` on the dense logits
    (label smoothing ε, ``padding_idx`` rows contribute zero loss/grad).
    """
    loss, _ = _lce_fwd_math(hidden, weight, labels, smoothing, padding_idx,
                            chunk, logit_scale)
    return loss


def _lce_fwd_math(hidden, weight, labels, smoothing, padding_idx, chunk,
                  logit_scale):
    n, h = hidden.shape
    v = weight.shape[1]
    wp, vpad = _pad_weight(weight, chunk)
    nchunks = vpad // chunk

    def body(carry, idx):
        m, s, picked, xsum = carry
        # slice the chunk in place — scanning over a pre-stacked
        # (nc, H, C) moveaxis copy would hold a second full weight in HBM
        wc = jax.lax.dynamic_slice(wp, (0, idx * chunk), (h, chunk))
        logits, col = _chunk_logits(hidden, wc, idx * chunk, chunk, v,
                                    logit_scale)
        cm = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, cm)
        # rescale the running sum-exp to the new max
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        hit = col == labels[:, None]
        picked = picked + jnp.sum(jnp.where(hit, logits, 0.0), axis=-1)
        xsum = xsum + jnp.sum(jnp.where(col < v, logits, 0.0), axis=-1)
        return (m_new, s, picked, xsum), None

    init = (jnp.full((n,), _NEG, _f32), jnp.zeros((n,), _f32),
            jnp.zeros((n,), _f32), jnp.zeros((n,), _f32))
    (m, s, picked, xsum), _ = jax.lax.scan(
        body, init, jnp.arange(nchunks))
    lse = jnp.log(s) + m
    nll = lse - picked
    if smoothing > 0.0:
        loss = (1.0 - smoothing) * nll + smoothing * (lse - xsum / v)
    else:
        loss = nll
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    return loss, lse


def _lce_vjp_fwd(hidden, weight, labels, smoothing, padding_idx, chunk,
                 logit_scale):
    loss, lse = _lce_fwd_math(hidden, weight, labels, smoothing,
                              padding_idx, chunk, logit_scale)
    # residuals: inputs + one fp32 scalar per row — never the logits
    return loss, (hidden, weight, labels, lse)


def _lce_vjp_bwd(smoothing, padding_idx, chunk, logit_scale, res, dloss):
    hidden, weight, labels, lse = res
    n, h = hidden.shape
    v = weight.shape[1]
    wp, vpad = _pad_weight(weight, chunk)
    nchunks = vpad // chunk

    g = dloss.astype(_f32)
    if padding_idx is not None:
        g = jnp.where(labels == padding_idx, 0.0, g)

    def body(dh, idx):
        wc = jax.lax.dynamic_slice(wp, (0, idx * chunk), (h, chunk))
        logits, col = _chunk_logits(hidden, wc, idx * chunk, chunk, v,
                                    logit_scale)
        p = jnp.exp(logits - lse[:, None])           # softmax tile
        onehot = (col == labels[:, None]).astype(_f32)
        target = (1.0 - smoothing) * onehot
        if smoothing > 0.0:
            target = target + jnp.where(col < v, smoothing / v, 0.0)
        dl = (p - target) * g[:, None] * logit_scale  # dlogits tile (N, C)
        # bf16 operands on the MXU, fp32 accumulation (input-dtype matmul
        # rule — see docs/performance.md kernel design notes)
        dl = dl.astype(hidden.dtype)
        dh = dh + jnp.dot(dl, wc.T, preferred_element_type=_f32)
        dwc = jnp.dot(hidden.T, dl, preferred_element_type=_f32)
        return dh, dwc.astype(weight.dtype)

    dh0 = jnp.zeros((n, h), _f32)
    dh, dwcs = jax.lax.scan(body, dh0, jnp.arange(nchunks))
    dw = jnp.moveaxis(dwcs, 0, 1).reshape(h, vpad)[:, :v]
    return dh.astype(hidden.dtype), dw.astype(weight.dtype), None


linear_cross_entropy.defvjp(_lce_vjp_fwd, _lce_vjp_bwd)
