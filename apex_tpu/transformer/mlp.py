"""Whole-MLP fused forward/backward — TPU equivalent of ``mlp_cuda``
(csrc/mlp.cpp:20-33 variadic layer list, csrc/mlp_cuda.cu fused
bias+activation kernels) and the frontend ``apex/mlp/mlp.py:33``.

The reference runs the full MLP in one call: per-layer cuBLAS GEMM + fused
bias/activation, with handwritten semaphore-based bias-grad reductions in
backward (mlp_cuda.cu:553). On TPU the entire stack below lives in ONE jitted
XLA program — every bias/activation fuses into its GEMM's epilogue and the
bias-grad reductions are XLA column reductions; the multi-CTA semaphore
machinery has no analog because XLA's dataflow graph serializes exactly where
needed (SURVEY §5 race detection note).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

_f32 = jnp.float32

_ACTS = {
    "none": lambda h: h,
    "relu": lambda h: jnp.maximum(h, 0.0),
    "sigmoid": jax.nn.sigmoid,
}


def mlp_forward(x: jax.Array, weights: Sequence[jax.Array],
                biases: Sequence[jax.Array] | None,
                activation: str = "relu") -> jax.Array:
    """Run the whole MLP (activation after every layer except the last,
    matching the reference's semantics in mlp.cpp / tests/L0/run_mlp)."""
    act = _ACTS[activation]
    h = x
    n = len(weights)
    for i, w in enumerate(weights):
        h = jax.lax.dot_general(h, w, (((h.ndim - 1,), (1,)), ((), ())),
                                preferred_element_type=_f32)
        if biases is not None:
            h = h + biases[i].astype(_f32)
        if i < n - 1:
            h = act(h)
        h = h.astype(x.dtype)
    return h


class MLP(nn.Module):
    """flax module ≈ ``apex.mlp.MLP(mlp_sizes, bias, activation)``.

    ``mlp_sizes`` = [in, hidden..., out]; weights stored (out, in) like torch.
    """

    mlp_sizes: Sequence[int]
    use_bias: bool = True
    activation: str = "relu"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        ws, bs = [], []
        for i in range(len(self.mlp_sizes) - 1):
            ws.append(self.param(
                f"weight_{i}", nn.initializers.lecun_normal(),
                (self.mlp_sizes[i + 1], self.mlp_sizes[i]),
                self.param_dtype))
            if self.use_bias:
                bs.append(self.param(
                    f"bias_{i}", nn.initializers.zeros,
                    (self.mlp_sizes[i + 1],), self.param_dtype))
        return mlp_forward(x, ws, bs if self.use_bias else None,
                           self.activation)
