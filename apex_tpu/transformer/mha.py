"""Fused multi-head attention modules — the capability of the removed
``apex.contrib.fast_multihead_attn`` (BASELINE.json config 5; absent from the
snapshot per SURVEY §2 — built here against the Pallas flash kernel + megatron
softmax semantics + RoPE, as BASELINE.md directs).

``mha_reference`` is the pure-jnp spec implementation (the reference-module
pattern of apex's tests, e.g. _transducer_ref.py) used by the parity tests.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas.flash_attention import flash_attention
from apex_tpu.transformer.rope import fused_rope_cached
from apex_tpu.transformer.softmax import (scaled_masked_softmax,
                                          scaled_upper_triang_masked_softmax)

_f32 = jnp.float32


def mha_reference(q, k, v, causal=False, mask=None, scale=None):
    """Unfused attention via the megatron softmax ops (parity oracle)."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(_f32), k.astype(_f32))
    if causal:
        probs = scaled_upper_triang_masked_softmax(logits, s)
    else:
        probs = scaled_masked_softmax(logits, mask, s)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(_f32),
                      v.astype(_f32)).astype(q.dtype)


class SelfMultiheadAttn(nn.Module):
    """Self-attention block ≈ fast_multihead_attn's SelfMultiheadAttn.

    Input (b, s, e); fused QKV projection, Pallas flash attention core
    (causal or full; arbitrary masks, ragged lengths and attention dropout
    are handled inside the kernel), output projection. ``use_rope`` threads
    the fused rotary embedding (csrc/megatron RoPE equivalent) into q/k.

    ``dropout_seed`` is the train/eval switch for attention dropout: pass a
    per-step int32 seed during training to enable ``dropout_p``; omit it
    (eval/inference) and dropout is disabled.
    """

    embed_dim: int
    num_heads: int
    causal: bool = False
    use_rope: bool = False
    rope_theta: float = 10000.0
    dropout_p: float = 0.0
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask: Optional[jax.Array] = None,
                 dropout_seed: Optional[jax.Array] = None):
        b, s, e = x.shape
        h = self.num_heads
        d = e // h
        qkv = nn.Dense(3 * e, use_bias=True, param_dtype=self.param_dtype,
                       dtype=x.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if self.use_rope:
            pos = jnp.arange(s, dtype=_f32)
            inv = self.rope_theta ** (-jnp.arange(0, d, 2, dtype=_f32) / d)
            f = pos[:, None] * inv[None, :]
            f = jnp.concatenate([f, f], axis=-1)          # (s, d)
            cos, sin = jnp.cos(f), jnp.sin(f)
            # rope expects (s, ...) leading; move seq axis first
            q = fused_rope_cached(q.transpose(2, 0, 1, 3), cos[:, None, None, :],
                                  sin[:, None, None, :]).transpose(1, 2, 0, 3)
            k = fused_rope_cached(k.transpose(2, 0, 1, 3), cos[:, None, None, :],
                                  sin[:, None, None, :]).transpose(1, 2, 0, 3)
        # always the fused Pallas path: the kernel handles arbitrary masks,
        # ragged lengths (internal padding) and attention dropout directly
        p = self.dropout_p if dropout_seed is not None else 0.0
        o = flash_attention(q, k, v, self.causal, mask=mask,
                            dropout_p=p, dropout_seed=dropout_seed)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        return nn.Dense(e, use_bias=True, param_dtype=self.param_dtype,
                        dtype=x.dtype, name="out")(o)


class EncdecMultiheadAttn(nn.Module):
    """Cross-attention ≈ fast_multihead_attn's EncdecMultiheadAttn."""

    embed_dim: int
    num_heads: int
    dropout_p: float = 0.0
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key_value, mask: Optional[jax.Array] = None,
                 dropout_seed: Optional[jax.Array] = None):
        b, sq, e = query.shape
        sk = key_value.shape[1]
        h = self.num_heads
        d = e // h
        q = nn.Dense(e, param_dtype=self.param_dtype, dtype=query.dtype,
                     name="q")(query)
        kv = nn.Dense(2 * e, param_dtype=self.param_dtype,
                      dtype=key_value.dtype, name="kv")(key_value)
        k, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(b, sq, h, d).transpose(0, 2, 1, 3)
        k = k.reshape(b, sk, h, d).transpose(0, 2, 1, 3)
        v = v.reshape(b, sk, h, d).transpose(0, 2, 1, 3)
        p = self.dropout_p if dropout_seed is not None else 0.0
        o = flash_attention(q, k, v, False, mask=mask,
                            dropout_p=p, dropout_seed=dropout_seed)
        o = o.transpose(0, 2, 1, 3).reshape(b, sq, e)
        return nn.Dense(e, param_dtype=self.param_dtype, dtype=query.dtype,
                        name="out")(o)
