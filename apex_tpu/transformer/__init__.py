"""Transformer kernel pack — TPU equivalents of csrc/megatron + fused_dense +
mlp_cuda (SURVEY §7 step 7)."""

from apex_tpu.transformer.softmax import (  # noqa: F401
    generic_scaled_masked_softmax,
    get_batch_per_block,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.rope import (  # noqa: F401
    fused_rope,
    fused_rope_2d,
    fused_rope_cached,
    fused_rope_thd,
)
from apex_tpu.transformer.fused_dense import (  # noqa: F401
    FusedDense,
    FusedDenseGeluDense,
    dense_gelu_dense,
    linear_bias,
)
from apex_tpu.transformer.linear_cross_entropy import (  # noqa: F401
    linear_cross_entropy,
)
from apex_tpu.transformer.mlp import MLP, mlp_forward  # noqa: F401
from apex_tpu.transformer.wgrad import (  # noqa: F401
    wgrad_gemm_accum_fp16,
    wgrad_gemm_accum_fp32,
)
from apex_tpu.transformer.mha import (  # noqa: F401
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
    mha_reference,
)
