"""Fused attention-score softmax family — TPU equivalent of the four megatron
CUDA modules (``csrc/megatron/scaled_*_softmax*``, setup.py:292-374):

- ``scaled_softmax``                      (unmasked, scale only)
- ``scaled_masked_softmax``               (arbitrary uint8 mask)
- ``scaled_upper_triang_masked_softmax``  (causal)
- ``generic_scaled_masked_softmax``       (unlimited sequence length)

Reference semantics preserved (scaled_masked_softmax.h:211-333):
- inputs scaled then masked positions filled with -10000.0 (not -inf);
- fully-masked rows output ZEROS, not NaN (``scale_value = 0`` when the row max
  is the fill value, :297);
- math in fp32 regardless of IO dtype; backward is the fused
  ``dy→(dy - Σ dy·y)·y·scale`` chain (:106-207 backward kernels).

On TPU one implementation covers all row lengths (no 16k warp limit — the
"generic" variant is the same code), and XLA fuses the whole chain into a
row-tiled loop; a custom VJP keeps the backward as one fused pass saving only
the softmax output, exactly like the reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_f32 = jnp.float32
MASK_FILL = -10000.0


def _softmax_rows(x32: jax.Array) -> jax.Array:
    m = jnp.max(x32, axis=-1, keepdims=True)
    # fully-masked row → every element == MASK_FILL → output zeros (ref :297)
    e = jnp.exp(x32 - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    y = e / s
    return jnp.where(m <= MASK_FILL, jnp.zeros_like(y), y)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scaled_softmax(x, scale):
    return _softmax_rows(x.astype(_f32) * scale).astype(x.dtype)


def _smsm_fwd(x, scale):
    y = _scaled_softmax(x, scale)
    return y, y


def _smsm_bwd(scale, y, dy):
    y32 = y.astype(_f32)
    dy32 = dy.astype(_f32)
    dx = (dy32 - jnp.sum(dy32 * y32, axis=-1, keepdims=True)) * y32 * scale
    return (dx.astype(y.dtype),)


_scaled_softmax.defvjp(_smsm_fwd, _smsm_bwd)


def scaled_softmax(x: jax.Array, scale: float = 1.0) -> jax.Array:
    """≈ ``scaled_softmax_cuda`` (no mask). x: (..., sq, sk)."""
    return _scaled_softmax(x, scale)


def scaled_masked_softmax(x: jax.Array, mask: Optional[jax.Array],
                          scale: float = 1.0) -> jax.Array:
    """≈ ``scaled_masked_softmax_cuda``. ``mask`` is 1/True = masked
    (uint8 semantics of the reference), broadcastable to x; masked positions
    are filled with -10000 AFTER scaling (replace, not add)."""
    if mask is None:
        return scaled_softmax(x, scale)
    keep = 1.0 - mask.astype(_f32)
    return _scaled_masked_softmax_replace(x, keep, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scaled_masked_softmax_replace(x, keep, scale):
    x32 = x.astype(_f32) * scale
    x32 = x32 * keep + (1.0 - keep) * MASK_FILL
    return _softmax_rows(x32).astype(x.dtype)


def _smsr_fwd(x, keep, scale):
    y = _scaled_masked_softmax_replace(x, keep, scale)
    return y, (y, keep)


def _smsr_bwd(scale, res, dy):
    y, keep = res
    y32 = y.astype(_f32)
    dy32 = dy.astype(_f32)
    dx = (dy32 - jnp.sum(dy32 * y32, axis=-1, keepdims=True)) * y32 * scale
    return (dx * keep).astype(y.dtype), None


_scaled_masked_softmax_replace.defvjp(_smsr_fwd, _smsr_bwd)


def scaled_upper_triang_masked_softmax(x: jax.Array,
                                       scale: float = 1.0) -> jax.Array:
    """≈ ``scaled_upper_triang_masked_softmax_cuda`` (causal attention scores).

    x: (..., sq, sk) with sq == sk; position (i, j) masked when j > i
    (scaled_upper_triang_masked_softmax.h:130).
    """
    sq, sk = x.shape[-2], x.shape[-1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    keep = (cols <= rows).astype(_f32)
    return _scaled_masked_softmax_replace(x, keep, scale)


def generic_scaled_masked_softmax(x: jax.Array, mask: Optional[jax.Array],
                                  scale: float = 1.0) -> jax.Array:
    """≈ ``generic_scaled_masked_softmax_cuda`` — the unlimited-seq-len
    variant (generic_scaled_masked_softmax.h). On TPU the row-tiled XLA
    lowering has no 16k row limit, so this is the same implementation."""
    return scaled_masked_softmax(x, mask, scale)


def get_batch_per_block(sq: int, sk: int, b: int, np_: int) -> int:
    """API-parity helper (scaled_masked_softmax.cpp:74). On TPU the compiler
    owns tiling; return a nominal 1."""
    return 1
