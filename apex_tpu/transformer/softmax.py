"""Fused attention-score softmax family — TPU equivalent of the four megatron
CUDA modules (``csrc/megatron/scaled_*_softmax*``, setup.py:292-374):

- ``scaled_softmax``                      (unmasked, scale only)
- ``scaled_masked_softmax``               (arbitrary uint8 mask)
- ``scaled_upper_triang_masked_softmax``  (causal)
- ``generic_scaled_masked_softmax``       (unlimited sequence length)

Reference semantics preserved (scaled_masked_softmax.h:211-333):
- inputs scaled then masked positions filled with -10000.0 (not -inf);
- fully-masked rows output ZEROS, not NaN (``scale_value = 0`` when the row max
  is the fill value, :297);
- math in fp32 regardless of IO dtype; backward is the fused
  ``dy→(dy - Σ dy·y)·y·scale`` chain (:106-207 backward kernels).

On TPU one implementation covers all row lengths (no 16k warp limit — the
"generic" variant is the same code), and XLA fuses the whole chain into a
row-tiled loop; a custom VJP keeps the backward as one fused pass saving only
the softmax output, exactly like the reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas.softmax_kernel import (MASK_FILL,
                                                MAX_PALLAS_COLS,
                                                softmax_bwd_pallas,
                                                softmax_fwd_pallas)
from apex_tpu.utils.env import interpret_default

_f32 = jnp.float32


# ------------------------------------------------- Pallas-routed fast path
#
# On TPU the row-tiled Pallas kernel (ops/pallas/softmax_kernel.py) reads
# and writes each element exactly once; the jnp lowering below re-reads the
# input per reduction pass. CPU/interpret keeps the jnp path (fast under
# XLA:CPU, and the kernel itself is parity-tested in interpret mode).


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _pallas_softmax(x, mask, scale, causal, h):
    y, _ = _psm_fwd(x, mask, scale, causal, h)
    return y


def _psm_fwd(x, mask, scale, causal, h):
    shape = x.shape
    sq, sk = shape[-2], shape[-1]
    x3 = x.reshape(-1, sq, sk)
    m3 = None
    if mask is not None:
        m3 = mask.reshape(-1, mask.shape[-2], mask.shape[-1])
    y3 = softmax_fwd_pallas(x3, m3, scale=scale, causal=causal, h=h)
    y = y3.reshape(shape)
    return y, y


def _psm_bwd(scale, causal, h, y, dy):
    shape = y.shape
    sq, sk = shape[-2], shape[-1]
    dx3 = softmax_bwd_pallas(y.reshape(-1, sq, sk),
                             dy.reshape(-1, sq, sk), scale=scale)
    return dx3.reshape(shape), None


_pallas_softmax.defvjp(_psm_fwd, _psm_bwd)


def _pallas_route(x, mask, scale, causal):
    """Return (ok, h): whether the Pallas kernel can take this call, and the
    head-broadcast factor mapping mask batch rows onto score batch rows."""
    if interpret_default():
        return False, 1
    if x.ndim < 2 or x.shape[-1] > MAX_PALLAS_COLS:
        return False, 1
    if mask is None:
        return True, 1
    if mask.ndim != x.ndim or mask.shape[-1] != x.shape[-1]:
        return False, 1
    if mask.shape[-2] not in (1, x.shape[-2]):
        return False, 1
    # supported leading-dim broadcast: a prefix equal to x's dims followed
    # by all-1s (covers the reference's (b, 1, sq, sk) mask vs (b, h, sq,
    # sk) scores, all-equal, and all-ones). Then flat mask row = flat score
    # row // h with h = prod of the broadcast tail.
    lead_m, lead_x = mask.shape[:-2], x.shape[:-2]
    bm = bx = 1
    in_tail = False
    for a, b in zip(lead_m, lead_x):
        bx *= b
        if a == b and not in_tail:
            bm *= a
        elif a == 1:
            in_tail = True
        else:
            return False, 1
    return True, bx // bm


def _softmax_rows(x32: jax.Array) -> jax.Array:
    m = jnp.max(x32, axis=-1, keepdims=True)
    # fully-masked row → every element == MASK_FILL → output zeros (ref :297)
    e = jnp.exp(x32 - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    y = e / s
    return jnp.where(m <= MASK_FILL, jnp.zeros_like(y), y)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scaled_softmax(x, scale):
    return _softmax_rows(x.astype(_f32) * scale).astype(x.dtype)


def _smsm_fwd(x, scale):
    y = _scaled_softmax(x, scale)
    return y, y


def _smsm_bwd(scale, y, dy):
    y32 = y.astype(_f32)
    dy32 = dy.astype(_f32)
    dx = (dy32 - jnp.sum(dy32 * y32, axis=-1, keepdims=True)) * y32 * scale
    return (dx.astype(y.dtype),)


_scaled_softmax.defvjp(_smsm_fwd, _smsm_bwd)


def scaled_softmax(x: jax.Array, scale: float = 1.0) -> jax.Array:
    """≈ ``scaled_softmax_cuda`` (no mask). x: (..., sq, sk)."""
    ok, h = _pallas_route(x, None, scale, False)
    if ok:
        return _pallas_softmax(x, None, scale, False, h)
    return _scaled_softmax(x, scale)


def scaled_masked_softmax(x: jax.Array, mask: Optional[jax.Array],
                          scale: float = 1.0) -> jax.Array:
    """≈ ``scaled_masked_softmax_cuda``. ``mask`` is 1/True = masked
    (uint8 semantics of the reference), broadcastable to x; masked positions
    are filled with -10000 AFTER scaling (replace, not add)."""
    if mask is None:
        return scaled_softmax(x, scale)
    ok, h = _pallas_route(x, mask, scale, False)
    if ok:
        return _pallas_softmax(x, mask, scale, False, h)
    keep = 1.0 - mask.astype(_f32)
    return _scaled_masked_softmax_replace(x, keep, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scaled_masked_softmax_replace(x, keep, scale):
    x32 = x.astype(_f32) * scale
    x32 = x32 * keep + (1.0 - keep) * MASK_FILL
    return _softmax_rows(x32).astype(x.dtype)


def _smsr_fwd(x, keep, scale):
    y = _scaled_masked_softmax_replace(x, keep, scale)
    return y, (y, keep)


def _smsr_bwd(scale, res, dy):
    y, keep = res
    y32 = y.astype(_f32)
    dy32 = dy.astype(_f32)
    dx = (dy32 - jnp.sum(dy32 * y32, axis=-1, keepdims=True)) * y32 * scale
    return (dx * keep).astype(y.dtype), None


_scaled_masked_softmax_replace.defvjp(_smsr_fwd, _smsr_bwd)


def scaled_upper_triang_masked_softmax(x: jax.Array,
                                       scale: float = 1.0) -> jax.Array:
    """≈ ``scaled_upper_triang_masked_softmax_cuda`` (causal attention scores).

    x: (..., sq, sk) with sq == sk; position (i, j) masked when j > i
    (scaled_upper_triang_masked_softmax.h:130).
    """
    ok, h = _pallas_route(x, None, scale, True)
    if ok:
        return _pallas_softmax(x, None, scale, True, h)
    sq, sk = x.shape[-2], x.shape[-1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    keep = (cols <= rows).astype(_f32)
    return _scaled_masked_softmax_replace(x, keep, scale)


def generic_scaled_masked_softmax(x: jax.Array, mask: Optional[jax.Array],
                                  scale: float = 1.0) -> jax.Array:
    """≈ ``generic_scaled_masked_softmax_cuda`` — the unlimited-seq-len
    variant (generic_scaled_masked_softmax.h). On TPU the row-tiled XLA
    lowering has no 16k row limit, so this is the same implementation."""
    return scaled_masked_softmax(x, mask, scale)


def get_batch_per_block(sq: int, sk: int, b: int, np_: int) -> int:
    """API-parity helper (scaled_masked_softmax.cpp:74). On TPU the compiler
    owns tiling; return a nominal 1."""
    return 1
