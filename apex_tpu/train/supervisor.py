"""``TrainSupervisor`` — the job-level robustness contract.

The design mirror of :class:`apex_tpu.serve.resilience.ServeSupervisor`:
bounded retry + exponential backoff around the run loop, owning the three
failure paths end to end:

- **crash recovery** — a fatal error on any rank (an injected
  ``SimulatedCrash``, a real XLA/runtime fault) ends the attempt (peers
  unblock with ``CollectiveStallError`` instead of hanging — the
  ``ThreadProcessGroup`` contract); the supervisor publishes
  ``train_restart``, backs off, and relaunches the SAME topology. Cached
  :class:`~apex_tpu.train.trainer.Trainer` objects are re-bound to the
  fresh rendezvous, so every compiled executable survives — a
  same-topology restart adds **zero recompiles** (tier-1 reads the trace
  counters). Each attempt restores the last committed checkpoint at
  entry; after ``max_restarts`` failed attempts the root-cause exception
  propagates (the last committed step stays on disk).
- **coordinated preemption** — a stop on any rank (scheduler SIGTERM via
  :meth:`install_signals`, an injected ``preempt_at_step``, or
  :meth:`request_stop`) is agreed collectively at a step boundary; every
  rank drains, ONE final checkpoint commits atomically
  (``train_preempt_drain`` carries the drain seconds), and the attempt
  exits clean. With more entries left in ``world_schedule`` the
  supervisor relaunches at the next world — **elastic resize** — else it
  returns a preempted report.
- **exactly-once accounting** — the supervisor owns the job's ONE
  telemetry sink + goodput ledger and threads its step high-water mark
  through every attempt: each step index lands as productive exactly
  once; replayed executions ride the ``train_replay`` cause. Caveat of
  the fake-multihost harness: its ranks share ONE process event bus, so
  per-rank bus records (``checkpoint_save_stall`` — barrier-overlapped
  spans summed across ranks, ``overflow_step_skipped``,
  ``preemption_requested``) appear world-times in the ledger's event
  counts and the ``checkpoint_save`` cause, each carrying its ``rank``.
  The exactly-once contract is about STEP accounting (``steps`` /
  ``skipped_steps`` / ``train_replay``), which rank 0 alone records —
  on a real pod every process has its own bus and the per-rank records
  separate naturally.

Threading contract: :meth:`run` executes on one control thread; rank
threads touch only their own trainer, the coordinator, and this object's
progress table — every ``_rank_status``/``_trainers`` mutation happens
under ``_lock`` (rank threads report concurrently). ``_stop`` is a plain
one-way rebind (signal handler / control thread writes, rank threads
read) — the snapshot idiom, no lock needed.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from apex_tpu.monitor.telemetry import Telemetry
from apex_tpu.resilience.distributed import (CollectiveStallError,
                                             ThreadProcessGroup)
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.train.config import TrainConfig
from apex_tpu.train.trainer import Trainer
from apex_tpu.utils.logging import publish_event


class TrainSupervisor:
    """Run a data-parallel training job to completion across crashes,
    preemptions, and world-size changes (see module docstring).

    ``world_schedule`` is the elastic plan: the job starts at entry 0 and
    advances one entry per coordinated-preemption drain (the relaunch
    restores the same sharded checkpoint at the new world — bit-exactly,
    by the trainer's canonical shard reduction). Crash restarts stay on
    the current entry: same topology, zero recompiles. Defaults to
    ``[config.world]``. Elastic resizes move the **dp axis only**: the
    tp degree (``config.tp``, plus ``tp_spec`` for a custom workload) is
    fixed for the job's lifetime — changing it is an explicit reshard of
    the checkpoint, refused live (the CLI's exit-2 matrix enforces it at
    parse time).
    """

    def __init__(self, config: TrainConfig, *, injector=None,
                 max_restarts: int = 2, backoff_s: float = 0.05,
                 backoff_factor: float = 2.0, max_backoff_s: float = 2.0,
                 sleep=time.sleep, world_schedule: Optional[List[int]] = None,
                 registry=None, barrier_timeout_s: float = 60.0,
                 loss_fn: Optional[Callable] = None, init_params: Any = None,
                 batch_fn: Optional[Callable[[int], Any]] = None,
                 tp_spec: Any = None):
        self.config = config.validate()
        self.injector = injector
        self.max_restarts = max(0, int(max_restarts))
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.sleep = sleep
        self.barrier_timeout_s = float(barrier_timeout_s)
        self._custom = {"loss_fn": loss_fn, "init_params": init_params,
                        "batch_fn": batch_fn, "tp_spec": tp_spec}
        worlds = list(world_schedule) if world_schedule else [config.world]
        for w in worlds:
            if w < 1 or config.grad_shards % w:
                raise ValueError(
                    f"world_schedule entry {w} must be >= 1 and divide "
                    f"grad_shards {config.grad_shards}")
        if len(worlds) > 1 and not config.checkpoint_dir:
            raise ValueError(
                "an elastic world_schedule needs checkpoint_dir: the "
                "resize crosses a restart, and only a committed sharded "
                "checkpoint carries the state over")
        self._worlds = worlds
        self._world_idx = 0
        self.world_history: List[int] = []

        self.restarts = 0
        self.preempt_drains = 0
        self.hwm = 0
        # ONE job-scope sink: rank-0 trainers of every attempt/world share
        # it, so the ledger's step accounting is exactly-once job-wide
        self.telemetry = Telemetry(
            config.telemetry_jsonl, rank_zero_only=False,
            tokens_per_step=float(config.batch * (config.seq - 1)),
            trace_jsonl=config.trace_jsonl, registry=registry)

        self._lock = threading.Lock()
        self._trainers: Dict[Any, Trainer] = {}
        self._rank_status: Dict[int, Dict[str, Any]] = {}
        # one-way stop flag: written by request_stop()/the signal guard,
        # read by every rank thread (plain rebind — the snapshot idiom)
        self._stop = False
        self._main_guard: Optional[PreemptionGuard] = None
        self._closed = False

    # ---- external control ----------------------------------------------
    def request_stop(self) -> None:
        """Programmatic drain: the next step boundary on every rank joins
        the coordinated preemption agreement."""
        self._stop = True

    def install_signals(self) -> "TrainSupervisor":
        """Arm a main-thread SIGTERM/SIGINT guard (the CLI path): a
        scheduler signal feeds the same coordinated drain an injected
        preemption does. Rank threads cannot install handlers — this is
        the one process-level bridge."""
        self._main_guard = PreemptionGuard().install()
        return self

    def _external_stop(self) -> bool:
        if self._stop:
            return True
        return (self._main_guard is not None
                and self._main_guard.should_stop())

    # ---- live status (rank threads report, control thread reads) -------
    def _progress(self, rank: int, step: int) -> None:
        with self._lock:
            self._rank_status[rank] = {"step": step}

    def status(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {r: dict(v) for r, v in self._rank_status.items()}

    # ---- trainer cache --------------------------------------------------
    def _trainer_for(self, world: int, rank: int, coord) -> Trainer:
        with self._lock:
            trainer = self._trainers.get((world, rank))
            if trainer is None:
                trainer = Trainer(
                    self.config, coordinator=coord,
                    injector=self.injector,
                    telemetry=self.telemetry if rank == 0 else None,
                    hwm=self.hwm, **self._custom)
                self._trainers[(world, rank)] = trainer
            else:
                # same-topology relaunch: every compiled artifact (the
                # cached step fns AND the ResilientStep post-step) is
                # reused — the zero-recompile restart contract
                trainer.rebind(coord)
                trainer.hwm = self.hwm
            return trainer

    def trace_counts(self) -> Dict[str, int]:
        """Aggregate lifetime trace counts over every cached trainer.
        Counter dicts are deduped by identity and then summed: built-in
        workload trainers share ONE lru-cached dict per static_key, and
        custom-``loss_fn`` trainers share one per ``(loss_fn,
        static_key)`` — so the job total is that dict's count, and any
        trainer that somehow compiled its own copy (a changed workload
        mid-job) shows up in the sum instead of hiding behind a max.
        ``post`` is always per-trainer."""
        out = {"shard_grads": 0, "apply": 0, "post": 0}
        with self._lock:
            trainers = list(self._trainers.values())
        distinct = {id(tr._counts): tr._counts for tr in trainers}
        for c in distinct.values():
            out["shard_grads"] += c["shard_grads"]
            out["apply"] += c["apply"]
        out["post"] = sum(tr.trace_counts()["post"] for tr in trainers)
        return out

    # ---- the job loop ---------------------------------------------------
    def _launch(self, world: int):
        group = ThreadProcessGroup(world, injector=self.injector,
                                   barrier_timeout_s=self.barrier_timeout_s)

        def _rank_fn(coord, rank):
            trainer = self._trainer_for(world, rank, coord)
            return trainer.run(external_stop=self._external_stop,
                               progress=self._progress)

        return group.run(_rank_fn)

    def run(self) -> Dict[str, Any]:
        """Drive the job to completion (or a final preempted drain);
        returns the job report. Raises the root-cause exception once the
        restart budget is exhausted — the last committed checkpoint is
        still on disk."""
        try:
            return self._run()
        finally:
            self.close()

    def _run(self) -> Dict[str, Any]:
        last_report: Optional[Dict[str, Any]] = None
        while True:
            world = self._worlds[self._world_idx]
            self.world_history.append(world)
            results = self._launch(world)
            with self._lock:
                rank0 = self._trainers.get((world, 0))
            if rank0 is not None:
                self.hwm = max(self.hwm, rank0.hwm)
            excs = [e for _, e in results if e is not None]
            if not excs:
                last_report = results[0][0]
                if last_report["preempted"]:
                    self.preempt_drains += 1
                    if self._world_idx + 1 < len(self._worlds) \
                            and not self._external_stop():
                        # elastic resize: the drained checkpoint restores
                        # at the next scheduled world, bit-exactly
                        self._world_idx += 1
                        continue
                return self._report(last_report)
            cause = self._root_cause(excs)
            if self.restarts >= self.max_restarts:
                raise cause
            self.restarts += 1
            publish_event("train_restart", attempt=self.restarts,
                          world=world,
                          error=f"{type(cause).__name__}: {cause}")
            self.sleep(min(
                self.backoff_s * self.backoff_factor ** (self.restarts - 1),
                self.max_backoff_s))
            # same topology: the next attempt's trainers restore the last
            # committed step at entry and replay the tail deterministically

    @staticmethod
    def _root_cause(excs: List[BaseException]) -> BaseException:
        """The exception worth propagating: a peer's CollectiveStallError
        is collateral — the rank that actually died is the story."""
        for e in excs:
            if not isinstance(e, CollectiveStallError):
                return e
        return excs[0]

    def _report(self, rank0_report: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            steps_retried = sum(tr.steps_retried
                                for (_, r), tr in self._trainers.items()
                                if r == 0)
            skipped = max((tr._resilient.skipped_steps
                           for tr in self._trainers.values()), default=0)
        return {
            "final_step": rank0_report["final_step"],
            "preempted": rank0_report["preempted"],
            "restarts": self.restarts,
            "preempt_drains": self.preempt_drains,
            "steps_retried": steps_retried,
            "skipped_steps": skipped,
            "hwm": self.hwm,
            "worlds": list(self.world_history),
            "goodput": self.telemetry.summary()["goodput"],
        }

    # ---- teardown -------------------------------------------------------
    def params(self):
        """The final parameter pytree (rank 0's replica of the last world
        that ran) — what the bit-exactness oracles compare."""
        with self._lock:
            return self._trainers[(self.world_history[-1], 0)].params

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._lock:
            trainers = list(self._trainers.values())
        for tr in trainers:
            tr.close()
        self.telemetry.close()
        if self._main_guard is not None:
            self._main_guard.restore()
            self._main_guard = None
