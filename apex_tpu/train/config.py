"""``TrainConfig`` — the one dataclass a production training run reads.

Model shape, data-parallel degree, gradient-shard geometry, AMP policy,
checkpoint/elastic settings, and observability wiring all live here so a
run is reproducible from its config alone (the TorchTitan property:
*one* config drives the trainer, the supervisor, the CLI, and the bench).

The field every correctness claim hangs off is ``grad_shards``: the
global batch is cut into that many **fixed micro-shards**, and the step's
gradient is the shard gradients summed in shard-index order — whatever
world size computed them. Because the shard partitioning (and therefore
every compiled shape and every float-add order) is a property of the
config, not of the world, a run restored at a different data-parallel
degree continues **bit-exactly**, and a same-topology restart reuses
every compiled executable. ``world`` must divide ``grad_shards`` so each
rank owns the same number of shards (the gather seam requires equal
payloads per rank).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from apex_tpu.resilience.step import DEFAULT_SCALE_FLOOR

AMP_MODES = ("off", "dynamic")


@dataclasses.dataclass
class TrainConfig:
    """Everything :class:`~apex_tpu.train.Trainer` and
    :class:`~apex_tpu.train.TrainSupervisor` need, in one place.

    The built-in workload is a tiny seeded LM (embedding → tanh MLP →
    LM head) whose batches are a pure function of ``(seed, step)`` — the
    determinism every chaos/elastic bit-exactness proof rides on. A
    custom model plugs in through ``Trainer(loss_fn=, init_params=,
    batch_fn=)`` and inherits the same loop, checkpointing, preemption,
    and accounting (see ``examples/lm_pretrain``).
    """

    # workload
    steps: int = 8
    batch: int = 8
    seq: int = 16
    vocab: int = 128
    hidden: int = 32
    lr: float = 1e-2
    seed: int = 0

    # parallelism: data-parallel degree (the fake-multihost thread
    # harness on CPU tier-1; real pods rendezvous via JaxCoordinator) and
    # the world-independent micro-shard count (see module docstring)
    world: int = 1
    grad_shards: int = 1
    # tensor-parallel degree: each grad micro-shard's forward/backward
    # runs over the PR-15 head-axis mesh (serve.tp.serving_mesh). The
    # per-shard grad fn gathers the sharded params by pure concatenation,
    # runs the pristine single-chip value_and_grad replicated, and slices
    # the gradients back to the local chunks — no float add ever crosses
    # a rank, so tp=N updates are bit-identical to tp=1 (tier-1 asserts).
    # Elastic resizes stay dp-axis-only: a tp change is an explicit
    # reshard, refused live (the CLI's world_schedule carries no tp).
    tp: int = 1

    # AMP: "dynamic" = fp16-style dynamic loss scaling through
    # DynamicGradScaler + ResilientStep; "off" = unscaled (bf16-first)
    amp: str = "dynamic"
    init_scale: float = 2.0 ** 12
    scale_floor: float = DEFAULT_SCALE_FLOOR
    max_consecutive_overflows: int = 8

    # checkpointing / elasticity
    checkpoint_dir: Optional[str] = None
    save_every: int = 0          # 0 = only the final / preemption commit
    sharded_checkpoint: bool = True
    max_to_keep: int = 3

    # observability
    telemetry_jsonl: Optional[str] = None
    trace_jsonl: Optional[str] = None
    watchdog_timeout_s: Optional[float] = None

    def validate(self) -> "TrainConfig":
        """Refuse contradictory geometry loudly, before anything compiles
        (the CLI turns these into its exit-2 usage errors)."""
        if self.steps < 1:
            raise ValueError(f"steps must be >= 1, got {self.steps}")
        if self.seq < 2:
            raise ValueError(
                f"seq must be >= 2 (next-token pairs), got {self.seq}")
        if self.vocab < 2 or self.hidden < 1:
            raise ValueError(
                f"vocab/hidden must be positive, got "
                f"{self.vocab}/{self.hidden}")
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        if self.grad_shards < 1:
            raise ValueError(
                f"grad_shards must be >= 1, got {self.grad_shards}")
        if self.grad_shards % self.world:
            raise ValueError(
                f"world {self.world} must divide grad_shards "
                f"{self.grad_shards} (equal shards per rank is what makes "
                f"elastic restarts bit-exact)")
        if self.batch % self.grad_shards:
            raise ValueError(
                f"grad_shards {self.grad_shards} must divide batch "
                f"{self.batch}")
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.hidden % self.tp:
            raise ValueError(
                f"tp {self.tp} must divide hidden {self.hidden} (the "
                f"built-in workload shards its hidden/head axis; a custom "
                f"workload's tp_spec axes are checked at placement)")
        if self.amp not in AMP_MODES:
            raise ValueError(f"amp must be one of {AMP_MODES}, "
                             f"got {self.amp!r}")
        if self.save_every < 0:
            raise ValueError(
                f"save_every must be >= 0, got {self.save_every}")
        if self.save_every and not self.checkpoint_dir:
            raise ValueError("save_every needs checkpoint_dir")
        if self.world > 1 and self.checkpoint_dir \
                and not self.sharded_checkpoint:
            raise ValueError(
                "world > 1 needs sharded_checkpoint=True (the dense "
                "manager has no commit protocol across ranks)")
        if self.tp > 1 and self.checkpoint_dir \
                and not self.sharded_checkpoint:
            raise ValueError(
                "tp > 1 needs sharded_checkpoint=True (mesh-sharded "
                "leaves stage per-owner shards; the dense manager would "
                "serialize cross-device gathers on one rank)")
        if self.watchdog_timeout_s is not None \
                and self.watchdog_timeout_s <= 0:
            raise ValueError(
                f"watchdog_timeout_s must be > 0, got "
                f"{self.watchdog_timeout_s}")
        return self

    def static_key(self) -> Tuple:
        """The jit-cache key for the built-in workload's compiled step
        functions: everything that shapes a trace — and nothing that
        doesn't (checkpoint dirs, telemetry paths), so a restarted or
        elastically resized job with the same workload reuses every
        compiled executable. ``world`` is deliberately absent: shard
        shapes are world-independent by construction. ``tp`` is present:
        a tp change reshapes every per-rank trace (the explicit-reshard
        boundary elastic resizes must never cross live)."""
        return (self.batch // self.grad_shards, self.seq, self.vocab,
                self.hidden, self.grad_shards, self.lr, self.amp,
                self.init_scale, self.scale_floor, self.seed, self.tp)
