"""``apex-tpu-train`` — the config-driven production trainer entry point.

Runs the elastic, preemption-tolerant trainer under its supervisor::

    apex-tpu-train --steps 32 --world 2 --grad-shards 2 \\
        --checkpoint-dir /ckpt --save-every 4 --max-restarts 2

    # elastic: drain at world 2, resume at 1, finish back at 2 —
    # bit-exactly (the canonical shard reduction)
    apex-tpu-train --steps 32 --elastic 2:1:2 --grad-shards 2 \\
        --checkpoint-dir /ckpt --chaos preempt:8,preempt:16

    # chaos smoke: crash mid-step AND mid-save, survive both
    apex-tpu-train --steps 24 --checkpoint-dir /ckpt --save-every 4 \\
        --max-restarts 2 --chaos crash-step:9,crash-save:12

``--chaos`` is a seeded deterministic schedule (the same harness tier-1
drives): ``crash-step:N`` (fatal error before step N — warm restart),
``crash-save:N`` (process dies mid-commit of checkpoint N — the previous
step stays restorable), ``preempt:N`` (coordinated drain at step N; with
``--elastic`` each drain advances the world schedule), ``nan-burst:N:L``
(L non-finite steps from N — the overflow-storm guard rail).

``--tp N`` arms the tensor axis: each grad micro-shard's forward/backward
runs over the PR-15 head-axis mesh (gather-compute-slice — bit-identical
to ``--tp 1``). Elastic schedules may spell entries ``W`` or ``WxT``, but
every ``T`` must equal ``--tp``: a live tp resize is refused at parse
time (exit 2) — changing tp is an explicit checkpoint reshard across a
restart, never an in-job transition. The device envelope is checked
up front too: ``max(worlds) × tp`` must fit the host's device count.

Contradictory or inert flag combinations are usage errors (exit 2)
refused BEFORE anything compiles — the serve/fleet CLI precedent. A
SIGTERM mid-run triggers the coordinated drain: one final checkpoint
commits, the summary prints, exit is clean.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

PROG = "apex-tpu-train"


def _usage(msg: str) -> int:
    print(f"{PROG}: {msg}", file=sys.stderr)
    return 2


def parse_chaos(spec: str, injector, steps: int,
                save_every: int = 0) -> Optional[str]:
    """Apply a ``--chaos`` schedule to ``injector``; returns an error
    message (the caller exits 2) or None. Inert entries — a step beyond
    ``--steps``, or a ``crash-save`` at a step the save cadence never
    commits — are refused, not silently ignored."""
    parsed = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, _, arg = entry.partition(":")
        try:
            nums = [int(x) for x in arg.split(":")] if arg else []
        except ValueError:
            return f"--chaos entry {entry!r}: malformed step number"
        if kind in ("crash-step", "crash-save", "preempt") \
                and len(nums) == 1:
            if not 0 <= nums[0] < steps:
                return (f"--chaos {entry!r}: step outside the run "
                        f"[0, {steps}) — the fault would never fire")
        elif kind == "nan-burst" and len(nums) == 2:
            if not 0 <= nums[0] < steps or nums[1] < 1:
                return f"--chaos {entry!r}: burst outside the run"
        else:
            return (f"--chaos entry {entry!r}: expected crash-step:N, "
                    f"crash-save:N, preempt:N, or nan-burst:N:L")
        parsed.append((kind, nums, entry))
    # which steps the run will actually commit: the cadence, the final
    # step, and every scheduled preemption drain — a crash-save anywhere
    # else would silently never fire
    saved = {steps - 1} | {n for k, (n, *_), _ in parsed
                           if k == "preempt"}
    if save_every > 0:
        saved |= set(range(0, steps, save_every))
    for kind, nums, entry in parsed:
        if kind == "crash-step":
            injector.crash_on_train_step(nums[0])
        elif kind == "crash-save":
            if nums[0] not in saved:
                return (f"--chaos {entry!r}: step {nums[0]} is never "
                        f"saved (cadence --save-every "
                        f"{save_every or 'off'}, final step "
                        f"{steps - 1}, preempt drains) — the fault "
                        f"would never fire")
            injector.crash_during_checkpoint_save(nums[0])
        elif kind == "preempt":
            injector.preempt_at_step(nums[0])
        else:
            injector.nan_burst(nums[0], nums[1])
    return None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog=PROG, description="elastic, preemption-tolerant trainer "
                               "(docs/training.md)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--world", type=int, default=1,
                    help="data-parallel degree (thread-faked ranks on "
                         "CPU; must divide --grad-shards)")
    ap.add_argument("--grad-shards", type=int, default=1,
                    help="fixed micro-shard count — the world-"
                         "independent gradient partition that makes "
                         "elastic restarts bit-exact")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: each grad micro-shard "
                         "runs over the head-axis serving mesh, "
                         "bit-identical to --tp 1; fixed for the job "
                         "(a tp change is an explicit reshard)")
    ap.add_argument("--elastic", default=None, metavar="W1:W2:...",
                    help="world schedule: each coordinated preemption "
                         "drain relaunches at the next entry (needs "
                         "--checkpoint-dir; replaces --world). Entries "
                         "may be W or WxT, but T must equal --tp — "
                         "live tp resizes are refused")
    ap.add_argument("--amp", default="dynamic", choices=["off", "dynamic"])
    ap.add_argument("--checkpoint-dir", default=None,
                    help="sharded atomic checkpoints + elastic restore "
                         "land here; resume is automatic")
    ap.add_argument("--save-every", type=int, default=0,
                    help="checkpoint cadence in steps (0 = only the "
                         "final/preemption commit; needs "
                         "--checkpoint-dir)")
    ap.add_argument("--max-restarts", type=int, default=2,
                    help="bounded warm restarts after fatal step errors "
                         "(exponential backoff between attempts)")
    ap.add_argument("--chaos", default=None,
                    help="seeded fault schedule, e.g. "
                         "crash-step:3,crash-save:4,preempt:6 (needs "
                         "--checkpoint-dir and --max-restarts >= 1)")
    ap.add_argument("--telemetry-jsonl", default=None,
                    help="per-step telemetry rows + mirrored events")
    ap.add_argument("--watchdog-timeout", type=float, default=None,
                    help="seconds a gradient exchange / commit barrier "
                         "may block before a collective_stall event")
    args = ap.parse_args(argv)

    # ---- the usage-error matrix: refuse contradictions loudly BEFORE
    # ---- any params are built or anything compiles (fleet precedent).
    # ---- Geometry/range rules live in ONE place — TrainConfig.validate,
    # ---- converted to exit 2 below — only the flag interplay validate
    # ---- cannot see (elastic schedules, chaos) is checked here.
    if args.elastic is not None and args.world != 1:
        return _usage("--elastic is a world schedule; it replaces "
                      "--world — pass exactly one of the two")
    if args.elastic is not None and not args.checkpoint_dir:
        return _usage("--elastic needs --checkpoint-dir: a resize "
                      "crosses a restart, and only a committed sharded "
                      "checkpoint carries the state over")
    worlds = [args.world]
    if args.elastic is not None:
        worlds = []
        for ent in args.elastic.split(":"):
            if not ent:
                continue
            w, _, t = ent.partition("x")
            try:
                world_n = int(w)
                tp_n = int(t) if t else args.tp
            except ValueError:
                return _usage(f"--elastic {args.elastic!r}: expected "
                              f"colon-separated world sizes (W or WxT)")
            if tp_n != args.tp:
                return _usage(
                    f"--elastic entry {ent!r}: live tp resize refused — "
                    f"elastic resizes move the dp axis only (--tp "
                    f"{args.tp} is fixed for the job); a tp change is "
                    f"an explicit checkpoint reshard across a restart")
            worlds.append(world_n)
        if not worlds:
            return _usage("--elastic needs at least one world size")
    for w in worlds:
        # validate() only sees worlds[0] (config.world) — every later
        # schedule entry must hold the same shard-divisibility contract
        if w < 1:
            return _usage(f"world size {w} must be >= 1")
        if args.grad_shards < 1 or args.grad_shards % w:
            return _usage(
                f"world {w} must divide --grad-shards "
                f"{args.grad_shards} (equal shards per rank is what "
                f"makes elastic restarts bit-exact)")
    if args.chaos is not None:
        if args.max_restarts < 1:
            return _usage("--max-restarts 0 with a --chaos schedule: "
                          "an injected crash would simply kill the run "
                          "— give the supervisor a restart budget")
        if not args.checkpoint_dir:
            return _usage("--chaos needs --checkpoint-dir: crash "
                          "recovery restores the last committed step")

    from apex_tpu.train.config import TrainConfig

    try:
        config = TrainConfig(
            steps=args.steps, batch=args.batch, seq=args.seq,
            vocab=args.vocab, hidden=args.hidden, lr=args.lr,
            seed=args.seed, world=worlds[0],
            grad_shards=args.grad_shards, tp=args.tp, amp=args.amp,
            checkpoint_dir=args.checkpoint_dir,
            save_every=args.save_every,
            telemetry_jsonl=args.telemetry_jsonl,
            watchdog_timeout_s=args.watchdog_timeout).validate()
    except ValueError as e:
        return _usage(str(e))

    if args.tp > 1:
        # device-envelope geometry, still before anything compiles: the
        # certified composition is per-rank dp device blocks × the tp
        # mesh, so the PEAK scheduled world must fit alongside the mesh
        import jax

        ndev = len(jax.devices())
        if max(worlds) * args.tp > ndev:
            return _usage(
                f"--tp {args.tp} at world {max(worlds)} needs "
                f"{max(worlds) * args.tp} devices, have {ndev} — the "
                f"dp × tp envelope must fit the host (on CPU force "
                f"more with XLA_FLAGS="
                f"--xla_force_host_platform_device_count=N)")

    injector = None
    if args.chaos is not None:
        from apex_tpu.resilience import FaultInjector

        injector = FaultInjector(seed=args.seed)
        err = parse_chaos(args.chaos, injector, args.steps,
                          save_every=args.save_every)
        if err is not None:
            return _usage(err)

    from apex_tpu.train.supervisor import TrainSupervisor

    supervisor = TrainSupervisor(
        config, injector=injector, max_restarts=args.max_restarts,
        world_schedule=worlds).install_signals()
    report = supervisor.run()
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
