"""``Trainer`` — one elastic, preemption-tolerant data-parallel train loop.

Composes what the repo built but never unified: ``ResilientStep`` +
``DynamicGradScaler`` (AMP with overflow-storm guard rails),
``ShardedCheckpointManager`` (atomic commit, elastic restore),
``PreemptionGuard`` (coordinated save-and-stop), ``CollectiveWatchdog``
(stuck gradient exchanges become events, not hangs), and
``Telemetry(registry=...)`` (training ranks snapshot/merge/SLO-gate
exactly like serving ranks) — behind one :class:`~apex_tpu.train.config.
TrainConfig`.

**The determinism contract** every robustness claim rides on:

- batches are a pure function of ``(config.seed, step)``;
- the global batch is cut into ``grad_shards`` fixed micro-shards, rank
  ``r`` of ``world`` computes shards ``{i : i % world == r}`` with ONE
  compiled per-shard function (shapes are world-independent), and the
  step gradient is the shard gradients summed in **shard-index order** —
  whatever world size computed them. Float addition never reassociates
  across a resize, so a run restored at a different data-parallel degree
  continues **bit-exactly**, and the compiled executables (keyed on the
  workload, not the world) are all reused;
- optimizer moments, scaler state, and the step counter ride the
  checkpoint, so a crash rollback replays the identical tail.

**Threading/collective contract**: with a world > 1 every rank must call
``run()`` with the same config (the ``ThreadProcessGroup`` harness on CPU
tier-1, ``JaxCoordinator`` on a real pod). The per-step gradient exchange
and the every-step ``guard.should_stop()`` poll are collectives — all
ranks reach them at the same cadence by construction of the loop.

**Accounting contract** (rank 0 only — the fake-multihost ranks share one
process bus): each step index lands in the goodput ledger as productive
exactly once per job (the supervisor threads its high-water mark through
restarts); a step re-executed after a crash rollback publishes
``train_step_replayed`` with its wall seconds (ledger cause
``train_replay``) instead. A coordinated preemption finishes the in-flight
step, commits one final checkpoint atomically, publishes
``train_preempt_drain`` with the drain seconds, and returns clean.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.amp.grad_scaler import DynamicGradScaler, ScalerState
from apex_tpu.monitor.metrics import collect_metrics
from apex_tpu.monitor.telemetry import Telemetry
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.resilience.checkpoint_manager import CheckpointManager
from apex_tpu.resilience.distributed import (CollectiveWatchdog,
                                             ShardedCheckpointManager,
                                             SingleProcessCoordinator)
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.resilience.step import ResilientStep
from apex_tpu.resilience.topology import layout_block
from apex_tpu.train.config import TrainConfig
from apex_tpu.utils.logging import is_rank_zero, publish_event


# --------------------------------------------------------------------------
# The built-in tiny-LM workload (pure functions of the config — the
# hand-rolled-loop bit-equality oracle in tests reuses exactly these)
# --------------------------------------------------------------------------

def make_scaler(config: TrainConfig) -> DynamicGradScaler:
    """The config's AMP policy as a scaler (``amp="off"`` disables it —
    unscaled bf16-first semantics; the floor is ResilientStep's job)."""
    return DynamicGradScaler(init_scale=config.init_scale,
                             enabled=config.amp != "off")


def tiny_lm_params(config: TrainConfig) -> Dict[str, jax.Array]:
    """Seeded fp32 init for the built-in LM (embedding → tanh MLP →
    LM head). Pure function of ``config.seed``."""
    k = jax.random.split(jax.random.PRNGKey(config.seed), 3)
    return {
        "emb": jax.random.normal(k[0], (config.vocab, config.hidden),
                                 jnp.float32) * 0.02,
        "w1": jax.random.normal(k[1], (config.hidden, config.hidden),
                                jnp.float32) * 0.1,
        "b1": jnp.zeros((config.hidden,), jnp.float32),
        "head": jax.random.normal(k[2], (config.hidden, config.vocab),
                                  jnp.float32) * 0.02,
    }


def tiny_lm_batch(config: TrainConfig, step: int) -> jax.Array:
    """The global token batch for ``step`` — a pure function of
    ``(config.seed, step)``, so replays and elastic resizes see the
    identical data stream."""
    key = jax.random.fold_in(jax.random.PRNGKey(config.seed + 0x5EED),
                             step)
    return jax.random.randint(key, (config.batch, config.seq), 0,
                              config.vocab, jnp.int32)


def _make_apply(scaler: DynamicGradScaler, counts: Dict[str, int],
                grad_shards: int, lr: float):
    """The jitted post-exchange step: mean the canonical gradient sum,
    fused unscale + grad-norm + overflow probe, fused Adam, in-graph
    metrics. ``counts["apply"]`` bumps only when jax TRACES it — the
    zero-recompile-restart proof reads it."""
    inv = 1.0 / float(grad_shards)

    def apply(state3, sstate, gsum, loss_sum, t):
        counts["apply"] += 1
        params, m, v = state3
        grads = jax.tree_util.tree_map(lambda g: g * inv, gsum)
        grads, grad_norm, found_inf = scaler.unscale_and_norm(grads,
                                                              sstate)
        new_p, m2, v2 = adam_update(params, grads, m, v, step=t + 1,
                                    lr=lr, found_inf=found_inf)
        loss = (loss_sum * inv).astype(jnp.float32)
        # amp off: report loss_scale=1.0 (the sstate scale is inert),
        # keeping the emitted row schema stable across amp on/off
        scale_kw = ({"scaler_state": sstate} if scaler.enabled
                    else {"loss_scale": 1.0})
        tm = collect_metrics(params=new_p, grad_norm=grad_norm,
                             found_inf=found_inf, loss=loss, **scale_kw)
        return (new_p, m2, v2), found_inf, loss, tm

    return jax.jit(apply)


def _make_shard_grads(loss_fn: Callable, scaler: DynamicGradScaler,
                      counts: Dict[str, int]):
    """Jitted per-shard gradient function: scaled-loss grads + the
    unscaled loss as aux. ``loss_fn(params, tokens) -> scalar loss``."""

    def shard_grads(params, sstate, tokens):
        counts["shard_grads"] += 1

        def scaled(p):
            loss = loss_fn(p, tokens)
            return scaler.scale(loss, sstate), loss

        (_, loss), grads = jax.value_and_grad(scaled,
                                              has_aux=True)(params)
        return grads, loss

    return jax.jit(shard_grads)


def _tiny_lm_loss(params, tokens):
    x = params["emb"][tokens[:, :-1]]
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logp = jax.nn.log_softmax((h @ params["head"]).astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)
    return jnp.mean(nll)


# --------------------------------------------------------------------------
# Tensor-parallel gradients (the tp axis of TrainConfig)
#
# The mechanism is gather-compute-slice: params live tp-sharded on the
# PR-15 serving mesh in their RAW axis order (no qkv permutation — the
# logical checkpoint values stay dense-identical), the shard_map body
# all_gathers each sharded leaf by pure concatenation (tiled=True —
# exact reconstruction, no float combine), runs the PRISTINE single-chip
# value_and_grad of the unmodified loss replicated on every rank, and
# slices each sharded leaf's gradient back to its local chunk. No AD
# transpose ever crosses the shard_map boundary and no float add ever
# crosses a rank, so tp=N gradients — and therefore every update — are
# bit-identical to tp=1 (tier-1 asserts through GPT-2 + flash attention).
# --------------------------------------------------------------------------

def builtin_tp_specs() -> Dict[str, P]:
    """PartitionSpecs for the built-in tiny-LM tree: shard the hidden
    axis (requires ``tp | hidden`` — config.validate refuses otherwise);
    a custom workload passes its own spec tree via ``Trainer(tp_spec=)``
    (the GPT-2 one is :func:`apex_tpu.serve.tp.tp_param_specs`)."""
    return {"emb": P(None, "tp"), "w1": P(None, "tp"), "b1": P("tp"),
            "head": P("tp", None)}


def _spec_axis(spec: P) -> Optional[int]:
    for ax, name in enumerate(spec):
        if name == "tp":
            return ax
    return None


def _tp_tree_map(fn, tree, specs):
    return jax.tree_util.tree_map(fn, tree, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def _gather_tree(tree, specs):
    def g(leaf, spec):
        ax = _spec_axis(spec)
        if ax is None:
            return leaf
        return jax.lax.all_gather(leaf, "tp", axis=ax, tiled=True)
    return _tp_tree_map(g, tree, specs)


def _slice_tree(tree, specs, tp: int):
    r = jax.lax.axis_index("tp")

    def s(leaf, spec):
        ax = _spec_axis(spec)
        if ax is None:
            return leaf
        chunk = leaf.shape[ax] // tp
        return jax.lax.dynamic_slice_in_dim(leaf, r * chunk, chunk,
                                            axis=ax)
    return _tp_tree_map(s, tree, specs)


def _make_shard_grads_tp(loss_fn: Callable, scaler: DynamicGradScaler,
                         counts: Dict[str, int], mesh, specs):
    """The tp>1 twin of :func:`_make_shard_grads` — same signature, same
    outputs (sharded grads + replicated unscaled loss), gather-compute-
    slice body under ``shard_map``. The trace counter bumps in the OUTER
    jit wrapper: the shard_map body may legitimately trace more than once
    per executable, so counting there would break the zero-recompile
    proofs."""
    tp = mesh.devices.size
    sstate_spec = jax.tree_util.tree_map(lambda _: P(), scaler.init())

    def body(params_loc, sstate, tokens):
        full = _gather_tree(params_loc, specs)

        def scaled(p):
            loss = loss_fn(p, tokens)
            return scaler.scale(loss, sstate), loss

        (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(full)
        return _slice_tree(grads, specs, tp), loss

    sm = shard_map(body, mesh=mesh,
                   in_specs=(specs, sstate_spec, P()),
                   out_specs=(specs, P()), check_rep=False)

    def shard_grads(params, sstate, tokens):
        counts["shard_grads"] += 1
        return sm(params, sstate, tokens)

    return jax.jit(shard_grads)


def _place_tree(tree, mesh, specs):
    """Commit a tree onto the tp mesh per its specs (replicated leaves
    get P() so every leaf lands device-committed — eager ops and
    zeros_like then preserve the placement)."""
    def p(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return _tp_tree_map(p, tree, specs)


@functools.lru_cache(maxsize=None)
def _builtin_fns(key):
    """Compiled step functions for the built-in workload, cached on the
    config's :meth:`~TrainConfig.static_key` — a restarted (or
    elastically resized) job with the same workload gets the SAME
    callables back, so jax's jit cache serves every dispatch without a
    retrace. The returned ``counts`` dict is the cache entry's lifetime
    trace counter; the mesh/specs pair is ``(None, None)`` at tp=1 and
    the (cached, shared) serving mesh + builtin spec tree at tp>1."""
    (_shard_batch, _seq, _vocab, _hidden, grad_shards, lr, amp,
     init_scale, _floor, _seed, tp) = key
    counts = {"shard_grads": 0, "apply": 0}
    scaler = DynamicGradScaler(init_scale=init_scale,
                               enabled=amp != "off")
    if tp > 1:
        from apex_tpu.serve.tp import serving_mesh
        mesh, specs = serving_mesh(tp), builtin_tp_specs()
        grads_fn = _make_shard_grads_tp(_tiny_lm_loss, scaler, counts,
                                        mesh, specs)
    else:
        mesh = specs = None
        grads_fn = _make_shard_grads(_tiny_lm_loss, scaler, counts)
    return (grads_fn, _make_apply(scaler, counts, grad_shards, lr),
            counts, mesh, specs)


_CUSTOM_FNS: Dict[Any, tuple] = {}


def _custom_fns(loss_fn, key, tp_spec):
    """The custom-workload twin of :func:`_builtin_fns`: compiled step
    functions cached on ``(loss_fn, static_key, tp_spec)``. The
    supervisor rebuilds a Trainer per restart / elastic-resize leg with
    the SAME loss_fn object, and this cache is what keeps those legs on
    one compiled callable (zero recompiles) instead of re-jitting the
    model's grad per leg."""
    if tp_spec is None:
        token = None
    else:
        leaves, treedef = jax.tree_util.tree_flatten(
            tp_spec, is_leaf=lambda x: isinstance(x, P))
        token = (treedef, tuple(leaves))
    cache_key = (loss_fn, key, token)
    hit = _CUSTOM_FNS.get(cache_key)
    if hit is not None:
        return hit
    (_shard_batch, _seq, _vocab, _hidden, grad_shards, lr, amp,
     init_scale, _floor, _seed, tp) = key
    counts = {"shard_grads": 0, "apply": 0}
    scaler = DynamicGradScaler(init_scale=init_scale,
                               enabled=amp != "off")
    if tp > 1:
        from apex_tpu.serve.tp import serving_mesh
        mesh, specs = serving_mesh(tp), tp_spec
        grads_fn = _make_shard_grads_tp(loss_fn, scaler, counts, mesh,
                                        specs)
    else:
        mesh = specs = None
        grads_fn = _make_shard_grads(loss_fn, scaler, counts)
    out = (grads_fn, _make_apply(scaler, counts, grad_shards, lr),
           counts, mesh, specs)
    _CUSTOM_FNS[cache_key] = out
    return out


# --------------------------------------------------------------------------
# Trainer
# --------------------------------------------------------------------------

class Trainer:
    """One rank's view of the elastic production train loop (see module
    docstring for the determinism / collective / accounting contracts).

    Custom models plug in via ``loss_fn(params, tokens) -> scalar``,
    ``init_params`` (a pytree), and ``batch_fn(step) -> tokens`` — the
    checkpointing, preemption, chaos hooks, and accounting are identical
    (``examples/lm_pretrain`` is the worked example). ``registry`` is the
    serving-grade metrics seam: pass a
    :class:`~apex_tpu.monitor.export.MetricsRegistry` and per-step
    counters/histograms land in a mergeable snapshot exactly like a
    serving rank's.
    """

    def __init__(self, config: TrainConfig, *, coordinator=None,
                 injector=None, loss_fn: Optional[Callable] = None,
                 init_params: Any = None,
                 batch_fn: Optional[Callable[[int], Any]] = None,
                 tp_spec: Any = None,
                 registry=None, hwm: int = 0, telemetry=None,
                 install_signal_handlers: bool = False):
        self.config = config.validate()
        self.coord = (coordinator if coordinator is not None
                      else SingleProcessCoordinator())
        self.rank = self.coord.process_index
        self.world = self.coord.process_count
        if config.grad_shards % self.world:
            raise ValueError(
                f"coordinator world {self.world} must divide grad_shards "
                f"{config.grad_shards}")
        self.G = config.grad_shards
        self.injector = injector
        self._install_signals = install_signal_handlers
        # BOTH gates: the coordinator's fake rank (thread harness — the
        # real process is jax rank 0 there) AND the real jax process
        # index, so a multi-host run without a coordinator (N processes
        # each seeing a SingleProcessCoordinator rank 0) still emits one
        # telemetry stream / one banner set, not N
        self._rank0 = self.rank == 0 and is_rank_zero()

        self.scaler = make_scaler(config)
        self.mesh = self.tp_spec = None
        if loss_fn is not None:
            if init_params is None or batch_fn is None:
                raise ValueError(
                    "a custom loss_fn needs init_params and batch_fn")
            if config.tp > 1 and tp_spec is None:
                raise ValueError(
                    "tp > 1 with a custom loss_fn needs tp_spec (a "
                    "PartitionSpec tree matching init_params; GPT-2 "
                    "uses serve.tp.tp_param_specs)")
            (self._shard_grads, self._apply, self._counts, self.mesh,
             self.tp_spec) = _custom_fns(
                 loss_fn, config.static_key(),
                 tp_spec if config.tp > 1 else None)
            self.params = jax.tree_util.tree_map(jnp.asarray, init_params)
            self._batch_fn = batch_fn
        else:
            (self._shard_grads, self._apply, self._counts, self.mesh,
             self.tp_spec) = _builtin_fns(config.static_key())
            self.params = tiny_lm_params(config)
            self._batch_fn = lambda t: tiny_lm_batch(config, t)
        if self.mesh is not None:
            # commit params onto the tp mesh; moments inherit via
            # zeros_like, grads come back sharded from the shard_map, and
            # _apply's elementwise Adam preserves the placement — so the
            # whole state stays resident in the tp layout step over step
            self.params = _place_tree(self.params, self.mesh,
                                      self.tp_spec)
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)  # noqa: E731
        self.m = jax.tree_util.tree_map(zeros, self.params)
        self.v = jax.tree_util.tree_map(zeros, self.params)
        self.sstate: ScalerState = self.scaler.init()

        self._next_step = 0           # the step not yet run
        self.hwm = int(hwm)           # job-scope exactly-once watermark
        self.steps_retried = 0        # replayed executions (rank 0)

        self.watchdog: Optional[CollectiveWatchdog] = None
        if config.watchdog_timeout_s:
            self.watchdog = CollectiveWatchdog(
                timeout_s=config.watchdog_timeout_s,
                coordinator=self.coord)
        self.manager = None
        if config.checkpoint_dir:
            kw: Dict[str, Any] = {"max_to_keep": config.max_to_keep}
            if injector is not None:
                kw["fs"] = injector.filesystem()
            if config.sharded_checkpoint:
                self.manager = ShardedCheckpointManager(
                    config.checkpoint_dir, coordinator=self.coord,
                    watchdog=self.watchdog, **kw)
            else:
                self.manager = CheckpointManager(config.checkpoint_dir,
                                                 **kw)
        # rank 0 owns telemetry + the goodput ledger (the fake-multihost
        # ranks share ONE process bus — a per-rank sink would multiply
        # every record); other ranks compute, rank 0 accounts. A
        # supervisor passes ONE shared sink so the job's accounting spans
        # restarts and elastic resizes (exactly-once needs one ledger).
        self.telemetry: Optional[Telemetry] = None
        self._owns_telemetry = False
        if self._rank0:
            if telemetry is not None:
                self.telemetry = telemetry
            else:
                self.telemetry = Telemetry(
                    config.telemetry_jsonl, rank_zero_only=False,
                    tokens_per_step=float(config.batch
                                          * (config.seq - 1)),
                    trace_jsonl=config.trace_jsonl, registry=registry)
                self._owns_telemetry = True
        # telemetry=None on purpose: the trainer does its own exactly-once
        # logging (ResilientStep would log every execution, replays
        # included); the in-graph metrics ride _apply's collect_metrics.
        # The tracer rides through: with config.trace_jsonl, rank 0's
        # steps emit the train_step/forward_backward/unscale span tree
        # (the hand-rolled lm_pretrain loop's tracing, preserved)
        self._tracer = (self.telemetry.tracer
                        if self.telemetry is not None else None)
        self._resilient = ResilientStep(
            self._apply, self.scaler,
            max_consecutive_overflows=config.max_consecutive_overflows,
            scale_floor=config.scale_floor, tracer=self._tracer)
        self.guard: Optional[PreemptionGuard] = None
        self._last_saved_step: Optional[int] = None

    # ---- lifecycle ------------------------------------------------------
    def rebind(self, coordinator) -> "Trainer":
        """A relaunched attempt re-rendezvouses: same trainer object (every
        compiled executable and the ResilientStep post-step survive — the
        zero-recompile same-topology-restart contract), fresh coordinator;
        the preemption guard is rebuilt per :meth:`run`."""
        if self.config.grad_shards % coordinator.process_count:
            raise ValueError(
                f"coordinator world {coordinator.process_count} must "
                f"divide grad_shards {self.config.grad_shards}")
        self.coord = coordinator
        self.rank = coordinator.process_index
        self.world = coordinator.process_count
        self._rank0 = self.rank == 0 and is_rank_zero()
        if self.manager is not None and hasattr(self.manager,
                                                "coordinator"):
            self.manager.coordinator = coordinator
        if self.watchdog is not None:
            self.watchdog.coordinator = coordinator
        return self

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._owns_telemetry and self.telemetry is not None:
            self.telemetry.close()

    def calibrate(self) -> "Trainer":
        """MFU calibration for rank 0's telemetry: the XLA cost model of
        one gradient shard, scaled by ``grad_shards`` (the step runs one
        such call per shard). Optional — it pays one analysis
        lower+compile of the shard function, which also bumps the trace
        counter once."""
        if self.telemetry is None:
            return self
        tokens = self._batch_fn(0)
        shard = tokens.reshape((self.G, tokens.shape[0] // self.G)
                               + tokens.shape[1:])[0]
        self.telemetry.calibrate(self._shard_grads, self.params,
                                 self.sstate, shard)
        if self.telemetry.flops_per_step:
            self.telemetry.flops_per_step *= self.G
        return self

    def trace_counts(self) -> Dict[str, int]:
        """Lifetime jax trace counts of the three step-path functions —
        flat across warm restarts and elastic resizes of the same
        workload (the tier-1 zero-recompile proofs read this)."""
        return {"shard_grads": self._counts["shard_grads"],
                "apply": self._counts["apply"],
                "post": self._resilient.post_traces}

    # ---- checkpoint tree ------------------------------------------------
    def _tree(self, step: int) -> Dict[str, Any]:
        r = self._resilient
        return {
            "params": self.params, "m": self.m, "v": self.v,
            "scaler": {"scale": self.sstate.scale,
                       "growth": self.sstate.growth_tracker,
                       "hyst": self.sstate.hysteresis_tracker},
            "meta": {"step": np.int64(step),
                     "world": np.int64(self.world),
                     "consec": np.int64(r.consecutive_overflows),
                     "skipped": np.int64(r.skipped_steps),
                     "degraded": np.int64(bool(r.degraded))},
        }

    def _save(self, step: int) -> Optional[str]:
        """Commit ``step`` (idempotent per step: the final/drain save
        after a cadence save of the same step — or a resumed
        already-complete run — must not re-stage or double-publish the
        commit; every rank derives the same decision, so the sharded
        barriers stay aligned)."""
        if step == self._last_saved_step:
            return None
        span = (self._tracer.span("checkpoint", step=step)
                if self._tracer is not None and self._tracer.enabled
                else contextlib.nullcontext())
        with span:
            path = self.manager.save(step, self._tree(step),
                                     layout=self._layout_block())
        self._last_saved_step = step
        if self._rank0:
            publish_event("train_checkpoint_commit", step=int(step),
                          path=path, world=self.world)
        return path

    def _layout_block(self) -> Dict[str, Any]:
        """The manifest ``layout`` block this topology stamps on every
        commit: which (dp world, grad_shards, tp) wrote the step. Values
        are stored in the raw dense format whatever the tp degree — tp
        shards are raw-axis chunks, so the logical tree is
        topology-portable by construction."""
        return layout_block(world=self.world, grad_shards=self.G,
                            tp=self.config.tp)

    def _restore(self) -> Optional[int]:
        out = self.manager.restore_latest(self._tree(0))
        if self._rank0:
            for q in getattr(self.manager, "last_quarantined", ()):
                publish_event("train_ckpt_quarantined", **q)
        if out is None:
            return None
        step, tree = out
        self.params, self.m, self.v = (tree["params"], tree["m"],
                                       tree["v"])
        sc = tree["scaler"]
        if self.mesh is not None:
            # restored leaves come back committed to the restore
            # target's devices; params/m/v restore onto the tp mesh (the
            # _tree(0) template is mesh-placed) but the scaler scalars'
            # template is the plain single-device init — re-place them
            # replicated on the mesh or the jitted step would see two
            # committed device sets and refuse
            rep = NamedSharding(self.mesh, P())
            sc = {k: jax.device_put(v, rep) for k, v in sc.items()}
        self.sstate = ScalerState(sc["scale"], sc["growth"], sc["hyst"])
        meta = tree["meta"]
        r = self._resilient
        r.consecutive_overflows = int(meta["consec"])
        r.skipped_steps = int(meta["skipped"])
        r.degraded = bool(int(meta["degraded"]))
        self._next_step = int(meta["step"]) + 1
        self._last_saved_step = int(meta["step"])  # it IS committed
        saved_world = int(meta["world"])
        if saved_world != self.world and self._rank0:
            publish_event("train_elastic_resized",
                          from_world=saved_world, to_world=self.world,
                          step=int(meta["step"]))
        # topology observability: the manifest's layout block names the
        # topology that WROTE the step. Restoring reassembles leaves
        # topology-independently and re-places them onto THIS config's
        # mesh (the automatic reshard) — when the written tp differs,
        # that crossing is counted, never silently absorbed.
        saved_layout = getattr(self.manager, "last_restored_layout",
                               None)
        if saved_layout and self._rank0:
            saved_tp = int(saved_layout.get("tp", 1))
            if saved_tp != self.config.tp:
                publish_event(
                    "train_topology_restored", step=int(meta["step"]),
                    from_tp=saved_tp, to_tp=self.config.tp,
                    from_world=int(saved_layout.get("world",
                                                    saved_world)),
                    to_world=self.world)
        return step

    # ---- the step -------------------------------------------------------
    def _step(self, t: int):
        tokens = self._batch_fn(t)
        n = tokens.shape[0]
        if n % self.G:
            raise ValueError(
                f"batch_fn returned leading dim {n}, not divisible by "
                f"grad_shards {self.G}")
        shards = tokens.reshape((self.G, n // self.G) + tokens.shape[1:])
        parts = [(i, *self._shard_grads(self.params, self.sstate,
                                        shards[i]))
                 for i in range(self.rank, self.G, self.world)]
        if self.world > 1:
            watch = (self.watchdog.watch(f"train_allgather:{t}")
                     if self.watchdog is not None
                     else contextlib.nullcontext())
            with watch:
                gathered = self.coord.all_gather_object(parts)
            parts = [p for rank_parts in gathered for p in rank_parts]
        # canonical reduction: shard-index order, whatever rank computed
        # each shard — the float-add order (and therefore the update) is
        # identical at every world size
        parts.sort(key=lambda p: p[0])
        gsum = functools.reduce(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b),
            (g for _, g, _ in parts))
        loss_sum = functools.reduce(jnp.add, (l for _, _, l in parts))
        if self.injector is not None and self.injector.grads_faulty(t):
            # deterministic fill (not the seeded poison_grads draw): every
            # rank and every replay of this step must agree
            gsum = jax.tree_util.tree_map(
                lambda g: jnp.full_like(g, jnp.nan), gsum)
        state3, self.sstate, found_inf, loss, tm = self._resilient(
            (self.params, self.m, self.v), self.sstate, gsum, loss_sum,
            jnp.int32(t))
        self.params, self.m, self.v = state3
        # the loop's ONE host sync — the skip flag it needs anyway
        return loss, tm, bool(found_inf)

    def _account(self, t: int, tm, skipped: bool, seconds: float) -> None:
        if not self._rank0:
            return
        if t >= self.hwm:
            self.telemetry.log_step(t, metrics=tm, skipped=skipped,
                                    step_ms=seconds * 1e3)
            self.hwm = t + 1
        else:
            # a crash rollback re-executed this step: real wall time spent
            # redoing discarded work — charged to train_replay, never
            # double-counted as a productive step
            self.steps_retried += 1
            publish_event("train_step_replayed", step=int(t),
                          seconds=round(seconds, 6))

    # ---- the run loop ---------------------------------------------------
    def run(self, *, on_step=None, on_resume=None, on_preempt=None,
            external_stop: Optional[Callable[[], bool]] = None,
            progress: Optional[Callable[[int, int], None]] = None
            ) -> Dict[str, Any]:
        """Run (or resume) to ``config.steps``; returns a report dict.

        ``on_step(step, loss)`` / ``on_resume(step)`` / ``on_preempt(step)``
        fire on rank 0 (``on_step`` costs one extra scalar fetch).
        ``external_stop()`` polled each step feeds the coordinated
        preemption agreement (the supervisor's signal bridge — thread
        ranks cannot install handlers). ``progress(rank, step)`` fires on
        every rank (the supervisor's live status feed).
        """
        cfg = self.config
        self.guard = PreemptionGuard(coordinator=self.coord)
        if self._install_signals:
            self.guard.install()
        restored = self._restore() if self.manager is not None else None
        if restored is not None and on_resume is not None and self._rank0:
            on_resume(restored)
        preempted = False
        try:
            while self._next_step < cfg.steps:
                t = self._next_step
                if self.injector is not None:
                    delay = self.injector.train_straggle_due(self.rank, t)
                    if delay:
                        time.sleep(delay)
                    if self.injector.train_preempt_due(self.rank, t):
                        self.guard.request_stop()
                if external_stop is not None and external_stop():
                    self.guard.request_stop()
                if self.injector is not None:
                    self.injector.maybe_crash_train(t, self.rank)
                t0 = time.perf_counter()
                loss, tm, skipped = self._step(t)
                self._account(t, tm, skipped,
                              time.perf_counter() - t0)
                if progress is not None:
                    progress(self.rank, t)
                if on_step is not None and self._rank0:
                    on_step(t, float(loss))
                self._next_step = t + 1
                if self.manager is not None and cfg.save_every \
                        and t % cfg.save_every == 0:
                    self._save(t)
                # the every-step preemption poll IS a collective in
                # coordinated mode: every rank flips at the same boundary
                if self.guard.should_stop():
                    preempted = True
                    break
            if preempted:
                # coordinated drain: the in-flight step finished above and
                # the sharded save's barriers drain the collectives; ONE
                # final checkpoint commits atomically, then a clean exit
                t0 = time.perf_counter()
                if self.manager is not None and self._next_step > 0:
                    self._save(self._next_step - 1)
                if self._rank0:
                    publish_event(
                        "train_preempt_drain",
                        seconds=round(time.perf_counter() - t0, 6),
                        step=self._next_step - 1, world=self.world,
                        signal=self.guard.received_signal)
                    if on_preempt is not None:
                        on_preempt(self._next_step - 1)
            elif self.manager is not None:
                self._save(cfg.steps - 1)  # the final commit
        finally:
            self.guard.restore()
        return {"rank": self.rank, "world": self.world,
                "final_step": self._next_step - 1,
                "preempted": preempted, "restored_from": restored,
                "hwm": self.hwm, "steps_retried": self.steps_retried,
                "skipped_steps": self._resilient.skipped_steps}
