"""apex_tpu.train — the elastic, preemption-tolerant production trainer.

The TorchTitan-class composition layer (ROADMAP item 4): one
config-driven system over what the repo built but never unified —

- :mod:`~apex_tpu.train.config` — :class:`TrainConfig`: model shape,
  data-parallel degree + gradient-shard geometry, AMP policy,
  checkpoint/elastic settings, observability wiring, in one dataclass.
- :mod:`~apex_tpu.train.trainer` — :class:`Trainer`: one rank's loop,
  composing ``ResilientStep`` + ``DynamicGradScaler``,
  ``ShardedCheckpointManager``, ``PreemptionGuard``,
  ``CollectiveWatchdog``, and ``Telemetry(registry=...)``. The canonical
  shard-indexed gradient reduction makes every update bit-identical at
  any world size — the property elastic restarts ride.
- :mod:`~apex_tpu.train.supervisor` — :class:`TrainSupervisor`: the job
  loop owning the robustness contract — bounded warm restarts with
  exponential backoff (zero recompiles on same-topology restart),
  coordinated preemption drain with one final atomic commit, elastic
  world-schedule relaunches, and job-scope exactly-once step accounting
  in the goodput ledger.
- :mod:`~apex_tpu.train.cli` — the ``apex-tpu-train`` entry point with
  its seeded ``--chaos`` schedule surface.

See docs/training.md for the contracts and the chaos-harness catalog.
"""

from apex_tpu.train.config import AMP_MODES, TrainConfig  # noqa: F401
from apex_tpu.train.supervisor import TrainSupervisor  # noqa: F401
from apex_tpu.train.trainer import (  # noqa: F401
    Trainer, make_scaler, tiny_lm_batch, tiny_lm_params)

__all__ = [
    "AMP_MODES", "TrainConfig", "Trainer", "TrainSupervisor",
    "make_scaler", "tiny_lm_batch", "tiny_lm_params",
]
