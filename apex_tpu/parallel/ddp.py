"""Data-parallel gradient synchronization — TPU equivalent of the removed
``apex.parallel.DistributedDataParallel``.

Spec (tests/distributed/DDP/ddp_race_condition_test.py:41 + csrc/flatten_unflatten.cpp):
flat-bucket all-reduce of gradients overlapped with backward, with
``message_size`` bucketing, ``gradient_predivide_factor``, and
``delay_allreduce``. The kernels it rode on (``apex_C.flatten/unflatten``) are
apex_tpu.utils.flatten here.

TPU design: gradient sync is ``jax.lax.psum`` on a named mesh axis inside the
jitted (shard_map / pjit) train step. Bucketing by ``message_size`` maps small
grads into large contiguous collectives (fewer, bigger ICI transfers) and XLA's
latency-hiding scheduler overlaps them with remaining backward compute — the
role the reference's multiple NCCL streams played (``num_allreduce_streams``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.flatten import flat_spec, flatten, unflatten


def _bucket_leaves(leaves, message_size: int):
    """Greedy assignment of leaves into buckets of ≥ message_size elements,
    segregated by dtype (reference DDP buckets per dtype so fp32 grads are
    never degraded through a lower-precision flat buffer), preserving order
    within each dtype (buckets fill as backward produces grads). Plans via
    the native helper (apex_tpu/_csrc plan_buckets) when compiled."""
    from apex_tpu._native.api import plan_buckets as _plan_buckets

    sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
    dtype_ids, dmap = [], {}
    for leaf in leaves:
        dt = jnp.dtype(leaf.dtype)
        dtype_ids.append(dmap.setdefault(dt, len(dmap)))
    bucket_ids, n_buckets = _plan_buckets(sizes, dtype_ids, message_size)
    buckets = [[] for _ in range(n_buckets)]
    for i, b in enumerate(bucket_ids):
        buckets[int(b)].append(i)
    return [b for b in buckets if b]


def bucketed_allreduce(grads: Any, axis_name: str = "data",
                       message_size: int = 1 << 22,
                       gradient_predivide_factor: float = 1.0,
                       gradient_average: bool = True) -> Any:
    """Flat-bucket mean-all-reduce of a gradient pytree over ``axis_name``.

    Must be called inside shard_map/pmap where ``axis_name`` is bound.
    Predivide-then-postdivide mirrors the reference's
    ``gradient_predivide_factor`` overflow guard.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    world = jax.lax.psum(1, axis_name)
    pre = gradient_predivide_factor
    post = (world / pre) if gradient_average else (1.0 / pre)

    out = [None] * len(leaves)
    for idxs in _bucket_leaves(leaves, message_size):
        group = [leaves[i] for i in idxs]
        spec = flat_spec(group)
        flat = flatten(group, spec, dtype=group[0].dtype)
        if pre != 1.0:
            flat = flat / pre
        flat = jax.lax.psum(flat, axis_name)
        if post != 1.0:
            flat = flat / jnp.asarray(post, flat.dtype)
        for i, g in zip(idxs, unflatten(flat, spec)):
            out[i] = g
    return jax.tree_util.tree_unflatten(treedef, out)


def allreduce_grads(grads: Any, axis_name: str = "data",
                    gradient_average: bool = True) -> Any:
    """Simple per-leaf psum-mean (the un-bucketed path; XLA may still combine)."""
    world = jax.lax.psum(1, axis_name)

    def _ar(g):
        s = jax.lax.psum(g, axis_name)
        return s / world if gradient_average else s

    return jax.tree_util.tree_map(_ar, grads)


class DistributedDataParallel:
    """Callable wrapper ≈ ``apex.parallel.DistributedDataParallel``.

    Wraps a ``grad_fn(params, batch) -> (loss, grads)``; calling
    ``ddp.sync(grads)`` inside the shard-mapped step returns synchronized
    grads. ``delay_allreduce=True`` reproduces the reference's
    whole-backward-then-one-flat-allreduce mode (single bucket).
    """

    def __init__(self, axis_name: str = "data", message_size: int = 1 << 22,
                 delay_allreduce: bool = False,
                 gradient_predivide_factor: float = 1.0,
                 gradient_average: bool = True,
                 allreduce_trigger_params: Optional[Sequence] = None,
                 num_allreduce_streams: int = 1):
        # num_allreduce_streams / trigger params are scheduling hints the XLA
        # compiler owns on TPU; accepted for API parity.
        self.axis_name = axis_name
        self.message_size = (1 << 62) if delay_allreduce else message_size
        self.gradient_predivide_factor = gradient_predivide_factor
        self.gradient_average = gradient_average

    def sync(self, grads: Any) -> Any:
        return bucketed_allreduce(
            grads, self.axis_name, self.message_size,
            self.gradient_predivide_factor, self.gradient_average)

    def value_and_grad(self, loss_fn: Callable) -> Callable:
        """Returns f(params, *args) -> (loss, synced_grads) for use inside
        shard_map over the data axis."""
        vg = jax.value_and_grad(loss_fn)

        @functools.wraps(loss_fn)
        def wrapped(params, *args, **kw):
            loss, grads = vg(params, *args, **kw)
            return loss, self.sync(grads)

        return wrapped
