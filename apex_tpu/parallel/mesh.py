"""Mesh helpers — the rendezvous layer.

Reference analog: NCCL bootstrap (``apex/contrib/csrc/nccl_p2p/nccl_p2p.cpp:20-22``
broadcasting ``ncclUniqueId``) and c10d process groups. On TPU the fabric is the
device mesh: ``jax.sharding.Mesh`` over ICI (+DCN for multislice), with
``jax.distributed.initialize`` as the multi-host rendezvous.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def get_mesh(data_axis: str = "data", devices=None) -> Mesh:
    """1-D data-parallel mesh over all local devices (DDP default)."""
    devices = devices if devices is not None else jax.devices()
    return make_mesh([len(devices)], [data_axis], devices)
