"""Mesh helpers — the rendezvous + fabric layer.

Reference analog: NCCL bootstrap (``apex/contrib/csrc/nccl_p2p/nccl_p2p.cpp:20-22``
broadcasting ``ncclUniqueId``), the c10d process groups every distributed
component rides on, and the env-var rendezvous of ``torch.distributed``
(MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE — the launch contract of the
reference's DDP tests, tests/distributed/DDP/ddp_race_condition_test.py).

On TPU the comm fabric is the device mesh: ``jax.sharding.Mesh`` over ICI
within a slice, with a DCN axis across slices/hosts for multislice jobs, and
``jax.distributed.initialize`` as the multi-host rendezvous (replacing the
ncclUniqueId broadcast). Collectives are then XLA ``psum``/``all_gather``/
``ppermute`` under pjit/shard_map — no communicator objects to manage.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None):
    """Multi-host rendezvous ≈ the reference's NCCL bootstrap.

    Resolution order for each field: explicit argument → JAX's own env/TPU
    autodetection → the torch.distributed env contract the reference's
    launch scripts use (``MASTER_ADDR``/``MASTER_PORT``, ``WORLD_SIZE``,
    ``RANK``). A single-process run (world size 1 and no coordinator)
    is a no-op, so the same training script works from a laptop to a pod —
    the ``torchrun``-compatibility the reference's examples assume.

    Returns ``(process_index, process_count)`` after initialization.
    """
    world = num_processes
    if world is None and os.environ.get("WORLD_SIZE"):
        world = int(os.environ["WORLD_SIZE"])
    rank = process_id
    if rank is None and os.environ.get("RANK"):
        rank = int(os.environ["RANK"])
    coord = coordinator_address
    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = (os.environ["MASTER_ADDR"] + ":"
                 + os.environ.get("MASTER_PORT", "29500"))

    # world size 1 short-circuits even with a coordinator set — torchrun
    # exports MASTER_ADDR for --nproc_per_node=1 too. NOTE: nothing before
    # this point may touch the backend (jax.devices()/process_count()):
    # jax.distributed.initialize refuses to run once XLA is initialized.
    single = world == 1 or (world is None and coord is None)
    if not single:
        already = getattr(jax.distributed, "is_initialized", lambda: False)()
        if not already:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=world,
                                       process_id=rank)
    return jax.process_index(), jax.process_count()


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Mesh over an explicit device list (row-major assignment).

    For full-machine meshes on real hardware prefer
    :func:`make_topology_mesh`, which lets jax's mesh utilities pick an
    ICI-contiguous device order. The serving engine's tensor-parallel
    mesh (:func:`apex_tpu.serve.tp.serving_mesh` — the 1-D ``"tp"``
    axis its head-sharded decode lowers under) builds here with an
    explicit device prefix, so tests pin which virtual CPU devices back
    the mesh and a deployment passes its ICI slice."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def make_topology_mesh(axis_sizes: Sequence[int],
                       axis_names: Sequence[str]) -> Mesh:
    """Topology-aware mesh over ALL devices: axis order maps onto the
    physical ICI torus so the innermost (most-communicating) axes ride the
    fastest links — the design rule of the scaling playbook. Falls back to
    row-major assignment when the backend exposes no topology (CPU mesh in
    tests)."""
    from jax.experimental import mesh_utils

    # size errors must propagate (a wrong mesh shape is a user bug, and
    # create_device_mesh handles topology-less backends itself)
    arr = mesh_utils.create_device_mesh(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def make_hybrid_mesh(dcn_axis_sizes: Sequence[int],
                     ici_axis_sizes: Sequence[int],
                     axis_names: Sequence[str]) -> Mesh:
    """Multislice mesh: outer axes over DCN (across slices/hosts), inner
    axes over ICI (within a slice) — e.g. ``make_hybrid_mesh([4], [2, 4],
    ["dp", "fsdp", "tp"])`` for 4 slices × 8 chips. The DCN axes MUST be
    the lowest-bandwidth-demand ones (plain data parallel); everything
    chatty (tp/sp/ep) stays on ICI. ≈ the reference's hierarchy of
    intra-node NVLink vs inter-node IB process groups.

    Falls back to a flat row-major mesh when no multislice topology is
    available (single host, CPU tests)."""
    from jax.experimental import mesh_utils

    names = tuple(axis_names)
    sizes = tuple(dcn_axis_sizes) + tuple(ici_axis_sizes)
    assert len(names) == len(sizes), (names, sizes)
    # fall back to a flat mesh ONLY when the backend exposes no multislice
    # topology (CPU tests, single slice) — on real multislice hardware a
    # sizing error must propagate, not silently put tp/sp across DCN
    devices = jax.devices()
    if not hasattr(devices[0], "slice_index"):
        return make_mesh(sizes, names)
    # create_hybrid_device_mesh multiplies same-rank shapes elementwise, so
    # pad each side with ones to place DCN axes outermost, ICI innermost
    ici_p = (1,) * len(dcn_axis_sizes) + tuple(ici_axis_sizes)
    dcn_p = tuple(dcn_axis_sizes) + (1,) * len(ici_axis_sizes)
    arr = mesh_utils.create_hybrid_device_mesh(ici_p, dcn_p)
    return Mesh(arr, names)


def device_process_map(devices, num_processes: int):
    """Deterministic contiguous-block device→process assignment.

    Real multi-host jax exposes ownership as ``device.process_index``; when
    a single host *fakes* N processes (the resilience test harness's
    ``ThreadProcessGroup`` over ``xla_force_host_platform_device_count``
    CPU devices), this provides the same contract: devices sorted by id are
    split into ``num_processes`` equal contiguous blocks — the layout
    TPU slices actually have (each host owns a contiguous chip block), so
    shard-ownership dedup exercises the production code path. Returns
    ``{device: process_rank}``.
    """
    devs = sorted(devices, key=lambda d: d.id)
    n = len(devs)
    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    if n % num_processes:
        raise ValueError(
            f"{n} devices do not split evenly over {num_processes} "
            f"processes (fake-process blocks must be equal-sized)")
    per = n // num_processes
    return {d: i // per for i, d in enumerate(devs)}


def get_mesh(data_axis: str = "data", devices=None) -> Mesh:
    """1-D data-parallel mesh over all local devices (DDP default)."""
    devices = devices if devices is not None else jax.devices()
    return make_mesh([len(devices)], [data_axis], devices)
