"""Ulysses-style sequence parallelism — all-to-all head↔sequence re-sharding.

The reference has no sequence parallelism (SURVEY §2.5: PP/EP/Ulysses/ring
absent from apex); this module and :mod:`apex_tpu.parallel.ring_attention`
are the framework's two first-class long-context strategies:

- **Ring** (ring_attention.py): K/V rotate over the ICI ring; O(s_local·d)
  memory; comm scales with the shard size × (n−1) steps. Best when s is
  huge and heads are few.
- **Ulysses** (this module, after DeepSpeed-Ulysses): inputs arrive
  sequence-sharded ``(b, h, s/n, d)``; ONE ``all_to_all`` re-shards to
  head-sharded ``(b, h/n, s, d)``, each device runs ordinary full-sequence
  flash attention over its head group, and a second ``all_to_all`` restores
  sequence sharding. Comm is two all-to-alls of the activation (independent
  of n on a ring/torus), and the attention itself needs NO cross-device
  softmax merging — the numerics are exactly single-device flash. Requires
  ``h % n == 0``; best when h ≥ n (the usual transformer regime).

Composition rule of thumb (scaling playbook): Ulysses inside a slice where
all_to_all rides ICI; ring across the slower axis when h < n forces it.

Layout convention matches the rest of the package: q/k/v ``(b, h, s_local,
d)`` per device under ``shard_map`` with the sequence axis sharded on
``axis_name``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.compat import axis_size

from apex_tpu.ops.pallas.flash_attention import flash_attention


def _seq_to_heads(x, axis_name: str, n: int):
    """(b, h, s/n, d) seq-sharded → (b, h/n, s, d) head-sharded.

    ``all_to_all`` splits the head axis n-ways and concatenates the
    gathered pieces along the sequence axis."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _heads_to_seq(x, axis_name: str, n: int):
    """Inverse of :func:`_seq_to_heads`."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, causal: bool = False,
                           scale: Optional[float] = None,
                           dropout_p: float = 0.0, dropout_seed=None):
    """Full-sequence self-attention over sequence-sharded q/k/v.

    Inside ``shard_map``: q/k/v are the local ``(b, h, s_local, d)`` shards
    of a globally ``(b, h, s, d)`` array sharded on ``axis_name``. Returns
    the local shard of the attention output with the same sharding.
    Differentiable (all_to_all is its own transpose, so the backward is two
    all-to-alls around the flash backward — no custom VJP needed).
    """
    n = axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % n != 0:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the sequence-parallel "
            f"axis size ({n}); use ring_attention when h < n")
    qh = _seq_to_heads(q, axis_name, n)
    kh = _seq_to_heads(k, axis_name, n)
    vh = _seq_to_heads(v, axis_name, n)
    oh = flash_attention(qh, kh, vh, causal, scale,
                         dropout_p=dropout_p, dropout_seed=dropout_seed)
    return _heads_to_seq(oh.astype(q.dtype), axis_name, n)
