"""1-D halo exchange over ICI — TPU equivalent of the reference's halo stack:

- ``nccl_p2p_cuda.left_right_halo_exchange`` (apex/contrib/csrc/nccl_p2p/nccl_p2p.cpp:24-26)
- ``peer_memory_cuda.push_pull_halos_1d`` (apex/contrib/csrc/peer_memory/peer_memory.cpp:34)
- the pluggable exchangers of apex/contrib/bottleneck/halo_exchangers.py:28-201
  (``HaloExchangerNoComm`` :28, ``HaloExchangerAllGather`` :46,
  ``HaloExchangerSendRecv`` :95, ``HaloExchangerPeer`` :146)

TPU design: neighbor transfer is ``jax.lax.ppermute`` on a named mesh axis —
the compiler lowers it to direct ICI neighbor DMA, which *is* the peer-memory
push of the reference (SURVEY §2.5). All four reference exchanger flavors
collapse onto two implementations (ppermute, all_gather); the class zoo is kept
for API parity and for tests that exercise each. This module is also the
building block ring attention generalizes (SURVEY §5 long-context).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.compat import axis_size


def left_right_halo_exchange(left_output_halo: jax.Array,
                             right_output_halo: jax.Array,
                             axis_name: str) -> Tuple[jax.Array, jax.Array]:
    """Send my left/right edge strips to my left/right neighbors; receive
    theirs. Returns ``(left_input_halo, right_input_halo)`` — what arrives
    from the left / right neighbor respectively (nccl_p2p.cpp:24 semantics,
    non-periodic: edge devices receive zeros).
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    # right-going: my right halo → right neighbor's left input
    right_perm = [(i, i + 1) for i in range(n - 1)]
    left_in = jax.lax.ppermute(right_output_halo, axis_name, right_perm)
    # left-going: my left halo → left neighbor's right input
    left_perm = [(i + 1, i) for i in range(n - 1)]
    right_in = jax.lax.ppermute(left_output_halo, axis_name, left_perm)
    # non-edge devices got data; edges receive zeros (ppermute default)
    del idx
    return left_in, right_in


def halo_exchange_1d(x: jax.Array, halo: int, axis_name: str,
                     spatial_axis: int = 0) -> jax.Array:
    """Pad the sharded spatial axis with ``halo`` rows from each neighbor
    (the SpatialBottleneck pre-conv exchange, bottleneck.py:304+).

    Returns x extended to ``shape[spatial_axis] + 2*halo``; edge devices get
    zero padding on their outer side.
    """
    top = jax.lax.slice_in_dim(x, 0, halo, axis=spatial_axis)
    bottom_start = x.shape[spatial_axis] - halo
    bottom = jax.lax.slice_in_dim(x, bottom_start,
                                  x.shape[spatial_axis], axis=spatial_axis)
    left_in, right_in = left_right_halo_exchange(top, bottom, axis_name)
    return jnp.concatenate([left_in, x, right_in], axis=spatial_axis)


class HaloExchanger:
    """Base for the exchanger zoo (halo_exchangers.py:28-201 parity)."""

    def __init__(self, axis_name: str = "spatial"):
        self.axis_name = axis_name

    def left_right_halo_exchange(self, left_output_halo, right_output_halo):
        return left_right_halo_exchange(left_output_halo, right_output_halo,
                                        self.axis_name)

    def __call__(self, x, halo: int, spatial_axis: int = 0):
        return halo_exchange_1d(x, halo, self.axis_name, spatial_axis)


class HaloExchangerNoComm(HaloExchanger):
    """Correctness-ablation exchanger (halo_exchangers.py:28): returns zero
    halos without touching the fabric."""

    def left_right_halo_exchange(self, left_output_halo, right_output_halo):
        return (jnp.zeros_like(right_output_halo),
                jnp.zeros_like(left_output_halo))

    def __call__(self, x, halo: int, spatial_axis: int = 0):
        z_top = jnp.zeros_like(
            jax.lax.slice_in_dim(x, 0, halo, axis=spatial_axis))
        return jnp.concatenate([z_top, x, z_top], axis=spatial_axis)


class HaloExchangerAllGather(HaloExchanger):
    """all_gather-based exchange (halo_exchangers.py:46): gather every
    device's strips, pick the neighbors'. Costs world× bandwidth — kept for
    parity/testing like the reference."""

    def left_right_halo_exchange(self, left_output_halo, right_output_halo):
        n = axis_size(self.axis_name)
        idx = jax.lax.axis_index(self.axis_name)
        lefts = jax.lax.all_gather(left_output_halo, self.axis_name)
        rights = jax.lax.all_gather(right_output_halo, self.axis_name)
        left_in = jnp.where(idx > 0, rights[jnp.maximum(idx - 1, 0)],
                            jnp.zeros_like(right_output_halo))
        right_in = jnp.where(idx < n - 1,
                             lefts[jnp.minimum(idx + 1, n - 1)],
                             jnp.zeros_like(left_output_halo))
        return left_in, right_in


class HaloExchangerSendRecv(HaloExchanger):
    """p2p send/recv flavor (halo_exchangers.py:95) — on TPU identical to the
    ppermute base (ppermute IS the p2p primitive)."""


class HaloExchangerPeer(HaloExchanger):
    """CUDA-IPC peer-memory flavor (halo_exchangers.py:146). On TPU direct
    neighbor DMA over ICI is what ppermute compiles to, so this is the base
    implementation; the ``peer_pool`` argument of the reference has no analog
    (XLA owns buffers)."""
