"""Pipeline parallelism (GPipe) over a mesh axis.

The reference has no pipeline parallelism (SURVEY §2.5: PP absent from apex);
the TPU framework provides it as a first-class axis alongside dp/tp/sp.

Design: the homogeneous stage stack is sharded over the ``pp`` axis (each
device holds one stage's params, passed as stacked leaves with a leading
stage dim). The GPipe schedule is a ``lax.scan`` over M + P - 1 ticks: stage
0 ingests a fresh microbatch each tick, every stage applies its layer to
whatever sits in its input buffer, and activations hop to the next stage with
``ppermute`` (one ICI neighbor transfer per tick). The backward pass needs no
hand-written schedule: autodiff transposes the scan and the ppermute, yielding
the reverse pipeline automatically.

Bubble fraction = (P-1)/(M+P-1), the standard GPipe tradeoff — pick
num_microbatches ≥ 4·P. Interleaved (1F1B) scheduling is a planned refinement.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   axis_name: str = "pp",
                   num_microbatches: int = 4) -> jax.Array:
    """Run a P-stage pipeline over the ``axis_name`` mesh axis.

    Call INSIDE shard_map. ``stage_params``: this device's stage params (pass
    stacked params with in_specs=P('pp', ...) and squeeze the leading dim, or
    any per-device tree). ``stage_fn(params, x_micro) -> y_micro`` must
    preserve the microbatch shape (homogeneous stages). ``x``: the full local
    batch (B, ...), B divisible by num_microbatches; every device receives
    the same x (replicated in-specs) and stage 0 feeds it in.

    Returns the pipeline output (B, ...) — valid on every device (the last
    stage's results are broadcast back over the axis).
    """
    p = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    m = num_microbatches
    b = x.shape[0]
    assert b % m == 0, "num_microbatches must divide the batch size"
    mb = b // m
    micro = x.reshape(m, mb, *x.shape[1:])
    ticks = m + p - 1

    fwd_perm = [(i, i + 1) for i in range(p - 1)]

    def tick(buf, t):
        # stage 0 ingests microbatch t (clamped; garbage ticks are discarded)
        idx = jnp.clip(t, 0, m - 1)
        fresh = jax.lax.dynamic_index_in_dim(micro, idx, 0, keepdims=False)
        inp = jnp.where(my == 0, fresh, buf)
        out = stage_fn(stage_params, inp)
        nxt = jax.lax.ppermute(out, axis_name, fwd_perm)
        return nxt, out

    # initial carry = a real microbatch, NOT zeros: bubble ticks run stage_fn
    # on this buffer and discard the result, but a zeros input could produce
    # NaN primals (e.g. eps-free norms) that poison the scan VJP via
    # 0-cotangent × NaN. stage_fn must be finite on finite inputs.
    _, outs = jax.lax.scan(tick, micro[0], jnp.arange(ticks))
    # last stage's valid outputs are at ticks [p-1, p-1+m)
    valid = jax.lax.dynamic_slice_in_dim(outs, p - 1, m, axis=0)
    y = valid.reshape(b, *x.shape[1:])
    # broadcast the last stage's result to every device: zero elsewhere + psum
    y = jnp.where(my == p - 1, y, jnp.zeros_like(y))
    return jax.lax.psum(y, axis_name)


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage param trees along a new leading axis, for
    sharding with in_specs=P('pp', ...)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def unstack_local(params: Any) -> Any:
    """Inside shard_map: squeeze the leading (local stage) dim of 1.

    Raises if more than one stage landed on this device (stage count must
    equal the pp axis size — silently using stage 0 of several would compute
    a wrong, shorter pipeline).
    """

    def squeeze(l):
        assert l.shape[0] == 1, (
            f"{l.shape[0]} stages per device: stack exactly axis_size stages "
            f"(stage count must equal the pp mesh axis size)")
        return l[0]

    return jax.tree_util.tree_map(squeeze, params)
