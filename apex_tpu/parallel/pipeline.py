"""Pipeline parallelism (GPipe) over a mesh axis.

The reference has no pipeline parallelism (SURVEY §2.5: PP absent from apex);
the TPU framework provides it as a first-class axis alongside dp/tp/sp.

Design: the homogeneous stage stack is sharded over the ``pp`` axis (each
device holds one stage's params, passed as stacked leaves with a leading
stage dim). The GPipe schedule is a ``lax.scan`` over M + P - 1 ticks: stage
0 ingests a fresh microbatch each tick, every stage applies its layer to
whatever sits in its input buffer, and activations hop to the next stage with
``ppermute`` (one ICI neighbor transfer per tick). The backward pass needs no
hand-written schedule: autodiff transposes the scan and the ppermute, yielding
the reverse pipeline automatically.

Bubble fraction = (P-1)/(M+P-1), the standard GPipe tradeoff — pick
num_microbatches ≥ 4·P.

Round 2 adds **1F1B** (``pipeline_train_1f1b``): a manually-scheduled
one-forward-one-backward pipeline that bounds stashed activations at
O(P · microbatch) instead of GPipe's O(M · microbatch). The schedule is the
standard non-interleaved 1F1B in SPMD lockstep form: at tick t, stage s
forwards microbatch ``t - s`` and backwards microbatch ``t - 2(P-1) + s``
(the last stage backwards a microbatch the same tick it forwards it, earlier
stages progressively later), so the steady state alternates F and B with at
most 2(P-1) microbatches in flight. Backward recomputes the stage forward
from the stashed INPUT (remat — the memory/compute tradeoff every 1F1B
implementation makes) and uses ``jax.vjp`` for the stage pullback; activation
hops ride ``ppermute`` in both directions each tick.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from apex_tpu.utils.compat import axis_size


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   axis_name: str = "pp",
                   num_microbatches: int = 4) -> jax.Array:
    """Run a P-stage pipeline over the ``axis_name`` mesh axis.

    Call INSIDE shard_map. ``stage_params``: this device's stage params (pass
    stacked params with in_specs=P('pp', ...) and squeeze the leading dim, or
    any per-device tree). ``stage_fn(params, x_micro) -> y_micro`` must
    preserve the microbatch shape (homogeneous stages). ``x``: the full local
    batch (B, ...), B divisible by num_microbatches; every device receives
    the same x (replicated in-specs) and stage 0 feeds it in.

    Returns the pipeline output (B, ...) — valid on every device (the last
    stage's results are broadcast back over the axis).
    """
    p = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    m = num_microbatches
    b = x.shape[0]
    assert b % m == 0, "num_microbatches must divide the batch size"
    mb = b // m
    micro = x.reshape(m, mb, *x.shape[1:])
    ticks = m + p - 1

    fwd_perm = [(i, i + 1) for i in range(p - 1)]

    def tick(buf, t):
        # stage 0 ingests microbatch t (clamped; garbage ticks are discarded)
        idx = jnp.clip(t, 0, m - 1)
        fresh = jax.lax.dynamic_index_in_dim(micro, idx, 0, keepdims=False)
        inp = jnp.where(my == 0, fresh, buf)
        out = stage_fn(stage_params, inp)
        nxt = jax.lax.ppermute(out, axis_name, fwd_perm)
        return nxt, out

    # initial carry = a real microbatch, NOT zeros: bubble ticks run stage_fn
    # on this buffer and discard the result, but a zeros input could produce
    # NaN primals (e.g. eps-free norms) that poison the scan VJP via
    # 0-cotangent × NaN. stage_fn must be finite on finite inputs.
    _, outs = jax.lax.scan(tick, micro[0], jnp.arange(ticks))
    # last stage's valid outputs are at ticks [p-1, p-1+m)
    valid = jax.lax.dynamic_slice_in_dim(outs, p - 1, m, axis=0)
    y = valid.reshape(b, *x.shape[1:])
    # broadcast the last stage's result to every device: zero elsewhere + psum
    y = jnp.where(my == p - 1, y, jnp.zeros_like(y))
    return jax.lax.psum(y, axis_name)


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage param trees along a new leading axis, for
    sharding with in_specs=P('pp', ...)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def unstack_local(params: Any) -> Any:
    """Inside shard_map: squeeze the leading (local stage) dim of 1.

    Raises if more than one stage landed on this device (stage count must
    equal the pp axis size — silently using stage 0 of several would compute
    a wrong, shorter pipeline).
    """

    def squeeze(l):
        assert l.shape[0] == 1, (
            f"{l.shape[0]} stages per device: stack exactly axis_size stages "
            f"(stage count must equal the pp mesh axis size)")
        return l[0]

    return jax.tree_util.tree_map(squeeze, params)


def pipeline_train_1f1b(stage_fn: Callable, stage_params: Any,
                        shared_params: Any, x_template: jax.Array,
                        micro_args: tuple, num_microbatches: int,
                        axis_name: str = "pp"):
    """One fused forward+backward pipeline pass with the 1F1B schedule.

    Call INSIDE shard_map.

    ``stage_fn(stage_params, shared_params, x_act, *args_i) -> (y, loss_i)``
    is this device's stage: ``x_act`` is the incoming activation microbatch
    (same shape as the returned ``y``; the first stage ignores it and builds
    its input from ``args_i``, e.g. an embedding lookup), ``args_i`` are this
    microbatch's slices of ``micro_args`` (arrays with leading dim M — e.g.
    tokens/targets/mask). ``loss_i`` must be the microbatch loss on the LAST
    stage and any finite scalar elsewhere (it is discarded). stage_fn must be
    finite on finite inputs (bubble ticks run it on stale buffers).

    Returns ``(loss_sum, stage_grads, shared_grads, )`` where ``loss_sum`` is
    the sum of per-microbatch losses (valid on every device after a psum over
    the axis), ``stage_grads`` are THIS stage's param grads (local, not
    psum'd over pp), and ``shared_grads`` are psum'd over the pipeline axis.
    """
    p = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    m = num_microbatches
    depth = 2 * p  # stash ring: ≥ max microbatches in flight + 1
    ticks = m + 2 * (p - 1)
    fwd_perm = [(i, i + 1) for i in range(p - 1)]
    bwd_perm = [(i + 1, i) for i in range(p - 1)]
    is_last = my == p - 1

    zero_stage = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32), stage_params)
    zero_shared = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32), shared_params)

    def micro_at(t):
        return tuple(jax.lax.dynamic_index_in_dim(
            a, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            for a in micro_args)

    def tick(carry, t):
        in_buf, dy_buf, stash, g_stage, g_shared, loss_acc = carry

        # ---- forward sub-tick: microbatch fi = t - my
        fi = t - my
        valid_f = (fi >= 0) & (fi < m)
        args_f = micro_at(fi)
        y, loss_i = stage_fn(stage_params, shared_params, in_buf, *args_f)
        slot_f = jnp.clip(fi, 0, m - 1) % depth
        stash = jnp.where(
            valid_f,
            jax.lax.dynamic_update_index_in_dim(stash, in_buf, slot_f, 0),
            stash)
        loss_acc = loss_acc + jnp.where(valid_f & is_last, loss_i, 0.0)

        # ---- backward sub-tick: microbatch bi = t - 2(p-1) + my
        bi = t - 2 * (p - 1) + my
        valid_b = (bi >= 0) & (bi < m)
        args_b = micro_at(bi)
        x_b = jax.lax.dynamic_index_in_dim(
            stash, jnp.clip(bi, 0, m - 1) % depth, 0, keepdims=False)

        def f(sp, sh, xa):
            return stage_fn(sp, sh, xa, *args_b)

        _, pull = jax.vjp(f, stage_params, shared_params, x_b)
        # the last stage's cotangent enters through the loss output; earlier
        # stages take the ppermuted activation cotangent. Gate on valid_b so
        # bubble ticks contribute exact zeros.
        dy = jnp.where(valid_b & jnp.logical_not(is_last), dy_buf, 0.0)
        wl = jnp.where(valid_b & is_last, 1.0, 0.0)
        d_sp, d_sh, dx = pull((dy.astype(x_b.dtype), wl))
        # select (not multiply): bubble-tick pullbacks can contain non-finite
        # garbage; where() discards it exactly
        gate = lambda g: jnp.where(valid_b, g, 0.0)  # noqa: E731
        g_stage = jax.tree_util.tree_map(
            lambda a, g: a + gate(g), g_stage, d_sp)
        g_shared = jax.tree_util.tree_map(
            lambda a, g: a + gate(g), g_shared, d_sh)
        dx = jnp.where(valid_b, dx, 0.0)

        # ---- neighbor hops (one fwd + one bwd ppermute per tick)
        in_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        dy_next = jax.lax.ppermute(dx, axis_name, bwd_perm)
        return (in_next, dy_next, stash, g_stage, g_shared, loss_acc), None

    stash0 = jnp.stack([x_template] * depth)
    carry0 = (x_template, jnp.zeros_like(x_template), stash0,
              zero_stage, zero_shared, jnp.float32(0.0))
    (_, _, _, g_stage, g_shared, loss_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(ticks))

    loss_sum = jax.lax.psum(loss_acc, axis_name)
    g_shared = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), g_shared)
    return loss_sum, g_stage, g_shared
