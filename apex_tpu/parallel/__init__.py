"""Distributed utilities — TPU equivalent of the removed ``apex.parallel``
(DDP + SyncBatchNorm) and the contrib comm machinery, over XLA collectives."""

from apex_tpu.parallel.mesh import (get_mesh, init_distributed,  # noqa: F401
                                    make_hybrid_mesh, make_mesh,
                                    make_topology_mesh)
from apex_tpu.parallel.ddp import (  # noqa: F401
    DistributedDataParallel,
    bucketed_allreduce,
    allreduce_grads,
)
from apex_tpu.parallel.sync_batch_norm import (  # noqa: F401
    SyncBatchNorm,
    sync_batch_norm_stats,
)
from apex_tpu.parallel.halo import (  # noqa: F401
    HaloExchanger,
    HaloExchangerAllGather,
    HaloExchangerNoComm,
    HaloExchangerPeer,
    HaloExchangerSendRecv,
    halo_exchange_1d,
    left_right_halo_exchange,
)
from apex_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_self_attention,
    zigzag_ring_self_attention,
    zigzag_shard,
    zigzag_unshard,
)
from apex_tpu.parallel.ulysses import ulysses_self_attention  # noqa: F401
from apex_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    stack_stage_params,
    unstack_local,
)
from apex_tpu.parallel.moe import moe_ffn_ep, top1_dispatch  # noqa: F401
