"""Expert parallelism (MoE) over a mesh axis.

The reference has no expert parallelism (SURVEY §2.5); provided here as the
``ep`` axis counterpart to dp/tp/sp/pp. GShard-style top-1 routing with fixed
expert capacity: dispatch/combine are einsums (MXU-friendly one-hots, no
dynamic shapes) and the cross-device token exchange is ONE ``all_to_all``
each way over ICI — the collective the reference's NCCL backend never had a
use for (SURVEY §5 comm backend mapping).

Capacity overflow drops tokens (standard GShard behavior); the combine path
returns zeros for dropped tokens so the residual connection carries them.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.compat import axis_size

_f32 = jnp.float32


def top1_dispatch(gate_logits: jax.Array, num_experts: int,
                  capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Build (dispatch, combine) tensors from router logits.

    gate_logits: (T, E). Returns dispatch (T, E, C) one-hot and combine
    (T, E, C) = dispatch · router_prob.
    """
    t = gate_logits.shape[0]
    probs = jax.nn.softmax(gate_logits.astype(_f32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                     # (T,)
    onehot = jax.nn.one_hot(expert, num_experts, dtype=_f32)  # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0          # (T, E)
    keep = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1).astype(jnp.int32),
                            capacity, dtype=_f32)            # (T, E, C)
    dispatch = pos_oh * keep.astype(_f32)[..., None]
    gate = jnp.sum(probs * onehot, axis=-1, keepdims=True)   # (T, 1)
    combine = dispatch * gate[..., None]
    return dispatch, combine


def moe_ffn_ep(x: jax.Array, gate_w: jax.Array, w1: jax.Array,
               w2: jax.Array, axis_name: str = "ep",
               capacity_factor: float = 1.25) -> jax.Array:
    """Expert-parallel MoE FFN. Call inside shard_map.

    x: (T, D) local tokens; gate_w: (D, E) replicated router;
    w1: (E_local, D, H), w2: (E_local, H, D) — this device's expert shard
    (pass stacked experts with in_specs=P('ep', ...)).
    Returns (T, D): combined expert outputs (dropped tokens → zeros).
    """
    ep = axis_size(axis_name)
    t, d = x.shape
    e_local = w1.shape[0]
    e = e_local * ep
    cap = max(int(t / e * capacity_factor), 1)

    logits = jnp.dot(x.astype(_f32), gate_w.astype(_f32),
                     preferred_element_type=_f32)
    dispatch, combine = top1_dispatch(logits, e, cap)

    # gather expert inputs: (E, C, D)
    exp_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(_f32))
    # all_to_all: split the expert dim across devices, concat the token side
    # → each device gets its experts' slices from every peer: (E_l, ep*C, D)
    exp_in = exp_in.reshape(ep, e_local, cap, d)
    exp_in = jax.lax.all_to_all(exp_in, axis_name, split_axis=0,
                                concat_axis=0, tiled=False)
    exp_in = exp_in.transpose(1, 0, 2, 3).reshape(e_local, ep * cap, d)

    # local expert FFN (vmapped over this device's experts)
    def ffn(wi, wo, h):
        z = jax.nn.gelu(jnp.dot(h, wi.astype(_f32),
                                preferred_element_type=_f32))
        return jnp.dot(z, wo.astype(_f32), preferred_element_type=_f32)

    exp_out = jax.vmap(ffn)(w1, w2, exp_in)                 # (E_l, ep*C, D)

    # reverse exchange: back to (E, C, D) on every source device
    exp_out = exp_out.reshape(e_local, ep, cap, d).transpose(1, 0, 2, 3)
    exp_out = jax.lax.all_to_all(exp_out, axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
    exp_out = exp_out.reshape(e, cap, d)

    y = jnp.einsum("tec,ecd->td", combine, exp_out)
    return y.astype(x.dtype)
