"""Ring attention — sequence/context parallelism for long sequences.

The reference has no ring attention (SURVEY §5: apex's closest artifacts are
the spatial halo exchangers and the 'generic' softmax that lifts the row-length
limit). The TPU framework builds the long-context story from the same two
primitives idiomatically: the Pallas flash kernel for the local block and
``ppermute`` neighbor exchange (the halo machinery generalized to a ring) for
the cross-device pass — K/V shards rotate around the ICI ring while each
device's Q stays resident, with online log-sum-exp merging of partial results.

Memory: O(local_seq · d) per device; comm: n-1 K/V hops (+ n dK/dV hops in the backward) of the local
shard per layer, riding ICI neighbor links (never DCN within a slice).

Two sharding layouts:

- **Contiguous** (``ring_self_attention``): device i holds global chunk i.
  Fine for non-causal. For causal it wastes ~2× FLOPs: ring steps whose
  source shard is entirely in the future must still be materialized in the
  scan (uniform step shape), and causal work is imbalanced across devices.
- **Zigzag** (``zigzag_ring_self_attention``, round-2, VERDICT item 6): the
  global sequence is split into 2n chunks; device i holds chunk i (the "low"
  half) and chunk 2n-1-i (the "high" half). Under causal masking every ring
  step then does exactly the same 2·c² work (c = chunk length): for a source
  shard earlier in the ring, all local queries attend its low chunk only;
  for a later source, only the local high queries attend its full shard. The
  step dispatches between those two equal-cost branches with ``lax.cond`` —
  no masked-and-discarded kernel invocations, total causal FLOPs ≈ S²/(2n)
  per device (the optimum), ~2× better than the contiguous layout.

Causal gating uses ``lax.cond``/``jnp.where`` selection — never multiplying
a possibly-non-finite partial by a 0/1 gate (a 0·inf there poisons dq/dk/dv
with NaN; advisor finding round-1).

Backward: a custom VJP runs the ring in the same direction once more — dK/dV
accumulators travel WITH the rotating K/V shards, each device adding its
block's contribution as the shard passes through, so after a full loop the
gradients arrive back at their owner. dQ accumulates locally. Each block's
contribution uses the Pallas flash backward kernels with the FINAL merged
logsumexp (P = exp(S - lse_final) is the exact global softmax probability).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.compat import axis_size

from apex_tpu.ops.pallas.flash_attention import (flash_attention_bwd,
                                                 flash_attention_fwd)

_f32 = jnp.float32
_NEG = -1e30  # python scalar: no device-array creation at import time


def _merge(o1, lse1, o2, lse2):
    """Log-sum-exp merge of two partial attention results (o, lse)."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    tot = w1 + w2
    safe = jnp.where(tot > 0, tot, 1.0)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / safe[..., None]
    lse = m + jnp.log(safe)
    lse = jnp.where(tot > 0, lse, _NEG)
    return o, lse



def _rotate(x, axis_name, perm, transport):
    """One +1 ring hop of ``x``. ``transport="rdma"`` issues the Pallas
    one-sided remote-DMA put (ops/pallas/remote_copy.peer_shift — an
    explicit peer copy over ICI); the default stays the compiler-scheduled
    ``ppermute``. Numerics are identical (parity-tested)."""
    if transport == "rdma":
        from apex_tpu.ops.pallas.remote_copy import peer_shift

        return peer_shift(x, axis_name, 1)
    return jax.lax.ppermute(x, axis_name, perm)


# ------------------------------------------------------- contiguous layout


def _ring_fwd_impl(q, k, v, axis_name, causal, s, block_q, block_k,
                   transport="collective"):
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    # step 0: diagonal block — causal within the local shard
    o, lse = flash_attention_fwd(q, k, v, scale=s, causal=causal,
                                 block_q=block_q, block_k=block_k)
    o = o.astype(_f32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def compute_step(o_acc, lse_acc, k_cur, v_cur, step):
        # at scan index `step` the carry holds the shard of device
        # (my - step - 1) mod n (it has made step+1 hops)
        src = (my - step - 1) % n
        o_i, lse_i = flash_attention_fwd(q, k_cur, v_cur, scale=s,
                                         causal=False, block_q=block_q,
                                         block_k=block_k)
        if causal:
            # mask whole contribution when the source shard is in my future
            lse_i = jnp.where(src < my, lse_i, _NEG)
        return _merge(o_acc, lse_acc, o_i.astype(_f32), lse_i)

    def body(carry, step):
        o_acc, lse_acc, k_cur, v_cur = carry
        # the hop for the NEXT step is dataflow-independent of this step's
        # flash compute, so XLA's latency-hiding scheduler overlaps the
        # collective with the matmuls (a head-of-body rotate would
        # serialize comm then compute)
        k_nxt = _rotate(k_cur, axis_name, perm, transport)
        v_nxt = _rotate(v_cur, axis_name, perm, transport)
        o_acc, lse_acc = compute_step(o_acc, lse_acc, k_cur, v_cur, step)
        return (o_acc, lse_acc, k_nxt, v_nxt), None

    if n > 1:
        # first hop issued here, overlapping the diagonal block's compute;
        # the LAST step is peeled out of the scan so no wasted (n-th) hop
        # is ever issued — exactly n-1 K/V rotations total
        k1 = _rotate(k, axis_name, perm, transport)
        v1 = _rotate(v, axis_name, perm, transport)
        if n > 2:
            (o, lse, k1, v1), _ = jax.lax.scan(
                body, (o, lse, k1, v1), jnp.arange(n - 2))
        o, lse = compute_step(o, lse, k1, v1, n - 2)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str, causal: bool = False,
                        scale: Optional[float] = None,
                        block_q: int = 128, block_k: int = 128,
                        transport: str = "collective") -> jax.Array:
    """Ring attention over the ``axis_name`` mesh axis.

    q/k/v: LOCAL shards (b, h, s_local, d) of a sequence sharded contiguously
    along the axis. Returns the local output shard (b, h, s_local, d).
    Call inside shard_map/pjit with the sequence axis bound to ``axis_name``.
    For causal long-context training prefer ``zigzag_ring_self_attention``.
    """
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, s, block_q, block_k,
                          transport)
    return o


def _ring_vjp_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                  transport):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, s, block_q, block_k,
                            transport)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis_name, causal, scale, block_q, block_k, transport,
                  res, do):
    q, k, v, o, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    lse = lse.astype(_f32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # diagonal contribution (own shard, still home)
    dq_acc, dk_cur, dv_cur, _ = flash_attention_bwd(
        q, k, v, o, lse, do, scale=s, causal=causal,
        block_q=block_q, block_k=block_k)
    dq_acc = dq_acc.astype(_f32)
    dk_cur = dk_cur.astype(_f32)
    dv_cur = dv_cur.astype(_f32)

    def compute_step(k_cur, v_cur, step):
        src = (my - step - 1) % n
        dq_j, dk_j, dv_j, _ = flash_attention_bwd(
            q, k_cur, v_cur, o, lse, do, scale=s, causal=False,
            block_q=block_q, block_k=block_k)
        if causal:
            # select, don't multiply: dq_j may contain inf/nan for masked
            # future shards (exp(s - lse) overflow) and 0 * inf = nan
            allowed = src < my
            dq_j = jnp.where(allowed, dq_j.astype(_f32), 0.0)
            dk_j = jnp.where(allowed, dk_j.astype(_f32), 0.0)
            dv_j = jnp.where(allowed, dv_j.astype(_f32), 0.0)
        return dq_j.astype(_f32), dk_j.astype(_f32), dv_j.astype(_f32)

    def body(carry, step):
        # carry holds the shard PRESENT on this device and its aligned
        # gradient accumulator; rotations sit at the TAIL of the body so
        # the k/v hop (independent of this step's compute) overlaps the
        # backward matmuls. The dk/dv hop necessarily follows the add —
        # that half of the comm is the ring-backward dependency chain.
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        dq_j, dk_j, dv_j = compute_step(k_cur, v_cur, step)
        dq_acc = dq_acc + dq_j
        dk_cur = dk_cur + dk_j
        dv_cur = dv_cur + dv_j
        k_nxt = _rotate(k_cur, axis_name, perm, transport)
        v_nxt = _rotate(v_cur, axis_name, perm, transport)
        dk_nxt = _rotate(dk_cur, axis_name, perm, transport)
        dv_nxt = _rotate(dv_cur, axis_name, perm, transport)
        return (dq_acc, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    if n > 1:
        # pre-rotate once (overlapping the diagonal backward above); the
        # last step is peeled: its k/v need no further hop (n-1 K/V hops
        # total) while dk/dv take their final homecoming hop (n total)
        k1 = _rotate(k, axis_name, perm, transport)
        v1 = _rotate(v, axis_name, perm, transport)
        dk1 = _rotate(dk_cur, axis_name, perm, transport)
        dv1 = _rotate(dv_cur, axis_name, perm, transport)
        if n > 2:
            (dq_acc, k1, v1, dk1, dv1), _ = jax.lax.scan(
                body, (dq_acc, k1, v1, dk1, dv1), jnp.arange(n - 2))
        dq_j, dk_j, dv_j = compute_step(k1, v1, n - 2)
        dq_acc = dq_acc + dq_j
        dk_cur = _rotate(dk1 + dk_j, axis_name, perm, transport)
        dv_cur = _rotate(dv1 + dv_j, axis_name, perm, transport)
    return (dq_acc.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


ring_self_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Alias with the conventional name."""
    return ring_self_attention(q, k, v, axis_name, causal, scale)


# ---------------------------------------------------------- zigzag layout


def zigzag_shard(x, n: int, axis: int = 2):
    """Reorder a GLOBAL sequence axis into zigzag layout.

    Splits the axis into 2n chunks and orders them so that a contiguous
    n-way shard gives device i chunks (i, 2n-1-i). Apply before sharding;
    ``zigzag_unshard`` inverts.
    """
    s = x.shape[axis]
    assert s % (2 * n) == 0, f"seq {s} must divide 2n={2 * n}"
    chunks = jnp.split(x, 2 * n, axis=axis)
    order = []
    for i in range(n):
        order += [chunks[i], chunks[2 * n - 1 - i]]
    return jnp.concatenate(order, axis=axis)


def zigzag_unshard(x, n: int, axis: int = 2):
    """Invert ``zigzag_shard``."""
    chunks = jnp.split(x, 2 * n, axis=axis)
    inv = [None] * (2 * n)
    for i in range(n):
        inv[i] = chunks[2 * i]
        inv[2 * n - 1 - i] = chunks[2 * i + 1]
    return jnp.concatenate(inv, axis=axis)


def _zz_fwd_impl(q, k, v, axis_name, s, block_q, block_k,
                 transport="collective"):
    """Causal zigzag ring forward. Local layout: [low chunk, high chunk]."""
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    c = q.shape[2] // 2

    # diagonal: local [lo, hi] preserves global order (all lo positions
    # precede all hi positions), so plain top-left causal flash is exact
    o, lse = flash_attention_fwd(q, k, v, scale=s, causal=True,
                                 block_q=block_q, block_k=block_k)
    o = o.astype(_f32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step_earlier(k_cur, v_cur):
        # src earlier in the ring: every local query attends src's LOW chunk
        # fully; src's high chunk is in everyone's future. cost: 2c × c
        o_i, lse_i = flash_attention_fwd(
            q, k_cur[:, :, :c], v_cur[:, :, :c], scale=s, causal=False,
            block_q=block_q, block_k=block_k)
        return o_i.astype(_f32), lse_i

    def step_later(k_cur, v_cur):
        # src later in the ring: only local HIGH queries attend, but they
        # attend src's full shard (both its chunks precede my high chunk).
        # cost: c × 2c — identical to the other branch: balanced ring.
        o_hi, lse_hi = flash_attention_fwd(
            q[:, :, c:], k_cur, v_cur, scale=s, causal=False,
            block_q=block_q, block_k=block_k)
        o_i = jnp.concatenate([jnp.zeros_like(o_hi), o_hi.astype(_f32)],
                              axis=2)
        lse_i = jnp.concatenate([jnp.full_like(lse_hi, _NEG), lse_hi],
                                axis=2)
        return o_i, lse_i

    def compute_step(o_acc, lse_acc, k_cur, v_cur, step):
        src = (my - step - 1) % n
        o_i, lse_i = jax.lax.cond(src < my, step_earlier, step_later,
                                  k_cur, v_cur)
        return _merge(o_acc, lse_acc, o_i, lse_i)

    def body(carry, step):
        o_acc, lse_acc, k_cur, v_cur = carry
        # tail rotation: the next hop is independent of this step's flash
        # compute, so the scheduler overlaps comm with the matmuls
        k_nxt = _rotate(k_cur, axis_name, perm, transport)
        v_nxt = _rotate(v_cur, axis_name, perm, transport)
        o_acc, lse_acc = compute_step(o_acc, lse_acc, k_cur, v_cur, step)
        return (o_acc, lse_acc, k_nxt, v_nxt), None

    if n > 1:
        # last step peeled: exactly n-1 hops, none wasted
        k1 = _rotate(k, axis_name, perm, transport)
        v1 = _rotate(v, axis_name, perm, transport)
        if n > 2:
            (o, lse, k1, v1), _ = jax.lax.scan(
                body, (o, lse, k1, v1), jnp.arange(n - 2))
        o, lse = compute_step(o, lse, k1, v1, n - 2)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def zigzag_ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                               axis_name: str,
                               scale: Optional[float] = None,
                               block_q: int = 128,
                               block_k: int = 128,
                               transport: str = "collective") -> jax.Array:
    """Causal ring attention in the balanced zigzag layout.

    q/k/v: LOCAL shards (b, h, s_local, d) where the GLOBAL sequence was
    reordered with ``zigzag_shard(x, n)`` before sharding, so this device
    holds [chunk i, chunk 2n-1-i]. Output is the local shard in the same
    layout (``zigzag_unshard`` recovers natural order). Always causal —
    for non-causal use ``ring_self_attention`` (already balanced).
    """
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, _ = _zz_fwd_impl(q, k, v, axis_name, s, block_q, block_k, transport)
    return o


def _zz_vjp_fwd(q, k, v, axis_name, scale, block_q, block_k, transport):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, lse = _zz_fwd_impl(q, k, v, axis_name, s, block_q, block_k,
                          transport)
    return o, (q, k, v, o, lse)


def _zz_vjp_bwd(axis_name, scale, block_q, block_k, transport, res, do):
    q, k, v, o, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    c = q.shape[2] // 2
    lse = lse.astype(_f32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq_acc, dk_cur, dv_cur, _ = flash_attention_bwd(
        q, k, v, o, lse, do, scale=s, causal=True,
        block_q=block_q, block_k=block_k)
    dq_acc = dq_acc.astype(_f32)
    dk_cur = dk_cur.astype(_f32)
    dv_cur = dv_cur.astype(_f32)

    def bwd_earlier(k_cur, v_cur):
        dq_j, dk_lo, dv_lo, _ = flash_attention_bwd(
            q, k_cur[:, :, :c], v_cur[:, :, :c], o, lse, do, scale=s,
            causal=False, block_q=block_q, block_k=block_k)
        zeros_k = jnp.zeros((dk_lo.shape[0], dk_lo.shape[1], c,
                             dk_lo.shape[3]), _f32)
        dk_j = jnp.concatenate([dk_lo.astype(_f32), zeros_k], axis=2)
        dv_j = jnp.concatenate([dv_lo.astype(_f32), zeros_k], axis=2)
        return dq_j.astype(_f32), dk_j, dv_j

    def bwd_later(k_cur, v_cur):
        dq_hi, dk_j, dv_j, _ = flash_attention_bwd(
            q[:, :, c:], k_cur, v_cur, o[:, :, c:], lse[:, :, c:],
            do[:, :, c:], scale=s, causal=False,
            block_q=block_q, block_k=block_k)
        dq_j = jnp.concatenate([jnp.zeros_like(dq_hi, _f32),
                                dq_hi.astype(_f32)], axis=2)
        return dq_j, dk_j.astype(_f32), dv_j.astype(_f32)

    def compute_step(k_cur, v_cur, step):
        src = (my - step - 1) % n
        return jax.lax.cond(src < my, bwd_earlier, bwd_later, k_cur, v_cur)

    def body(carry, step):
        # tail rotations (see _ring_vjp_bwd): the k/v hop overlaps this
        # step's backward matmuls; the dk/dv hop follows the add
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        dq_j, dk_j, dv_j = compute_step(k_cur, v_cur, step)
        dk_cur = dk_cur + dk_j
        dv_cur = dv_cur + dv_j
        k_nxt = _rotate(k_cur, axis_name, perm, transport)
        v_nxt = _rotate(v_cur, axis_name, perm, transport)
        dk_nxt = _rotate(dk_cur, axis_name, perm, transport)
        dv_nxt = _rotate(dv_cur, axis_name, perm, transport)
        return (dq_acc + dq_j, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    if n > 1:
        # last step peeled: k/v make n-1 hops, dk/dv their homecoming n-th
        k1 = _rotate(k, axis_name, perm, transport)
        v1 = _rotate(v, axis_name, perm, transport)
        dk1 = _rotate(dk_cur, axis_name, perm, transport)
        dv1 = _rotate(dv_cur, axis_name, perm, transport)
        if n > 2:
            (dq_acc, k1, v1, dk1, dv1), _ = jax.lax.scan(
                body, (dq_acc, k1, v1, dk1, dv1), jnp.arange(n - 2))
        dq_j, dk_j, dv_j = compute_step(k1, v1, n - 2)
        dq_acc = dq_acc + dq_j
        dk_cur = _rotate(dk1 + dk_j, axis_name, perm, transport)
        dv_cur = _rotate(dv1 + dv_j, axis_name, perm, transport)
    return (dq_acc.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


zigzag_ring_self_attention.defvjp(_zz_vjp_fwd, _zz_vjp_bwd)
