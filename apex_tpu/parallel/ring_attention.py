"""Ring attention — sequence/context parallelism for long sequences.

The reference has no ring attention (SURVEY §5: apex's closest artifacts are
the spatial halo exchangers and the 'generic' softmax that lifts the row-length
limit). The TPU framework builds the long-context story from the same two
primitives idiomatically: the Pallas flash kernel for the local block and
``ppermute`` neighbor exchange (the halo machinery generalized to a ring) for
the cross-device pass — K/V shards rotate around the ICI ring while each
device's Q stays resident, with online log-sum-exp merging of partial results.

Memory: O(local_seq · d) per device; comm: (n-1) ppermutes of the local K/V
shard per layer, riding ICI neighbor links (never DCN within a slice).

Known optimization not yet taken (round-1): with causal=True and contiguous
sharding, ring steps whose source shard is entirely in the future still run
the flash kernel and are masked after the fact — ~2× the necessary attention
FLOPs. Zigzag/striped sequence sharding (each device holds a low AND a high
block) balances the causal work and removes the waste; planned follow-up.

Causal handling: sequence is sharded contiguously, so block (i attends j) is
fully allowed for j < i, fully masked for j > i, and causal within the
diagonal block — the diagonal runs as a causal flash call, off-diagonal
contributions are merged with -inf lse where masked.

Backward: a custom VJP runs the ring in the same direction once more — dK/dV
accumulators travel WITH the rotating K/V shards, each device adding its
block's contribution as the shard passes through, so after a full loop the
gradients arrive back at their owner. dQ accumulates locally. Each block's
contribution uses the Pallas flash backward kernels with the FINAL merged
logsumexp (P = exp(S - lse_final) is the exact global softmax probability).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas.flash_attention import (flash_attention_bwd,
                                                 flash_attention_fwd)

_f32 = jnp.float32
_NEG = -1e30  # python scalar: no device-array creation at import time


def _merge(o1, lse1, o2, lse2):
    """Log-sum-exp merge of two partial attention results (o, lse)."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    tot = w1 + w2
    safe = jnp.where(tot > 0, tot, 1.0)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / safe[..., None]
    lse = m + jnp.log(safe)
    lse = jnp.where(tot > 0, lse, _NEG)
    return o, lse


def _ring_fwd_impl(q, k, v, axis_name, causal, s, block_q, block_k):
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    # step 0: diagonal block — causal within the local shard
    o, lse = flash_attention_fwd(q, k, v, scale=s, causal=causal,
                                 block_q=block_q, block_k=block_k)
    o = o.astype(_f32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        o_acc, lse_acc, k_cur, v_cur = carry
        # rotate K/V one hop around the ring
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        # after `step+1` hops I hold the shard of device (my - step - 1) mod n
        src = (my - step - 1) % n
        o_i, lse_i = flash_attention_fwd(q, k_cur, v_cur, scale=s,
                                         causal=False, block_q=block_q,
                                         block_k=block_k)
        if causal:
            # mask whole contribution when the source shard is in my future
            allowed = src < my
            lse_i = jnp.where(allowed, lse_i, _NEG)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_i.astype(_f32), lse_i)
        return (o_acc, lse_acc, k_cur, v_cur), None

    if n > 1:
        (o, lse, _, _), _ = jax.lax.scan(
            body, (o, lse, k, v), jnp.arange(n - 1))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str, causal: bool = False,
                        scale: Optional[float] = None,
                        block_q: int = 128, block_k: int = 128) -> jax.Array:
    """Ring attention over the ``axis_name`` mesh axis.

    q/k/v: LOCAL shards (b, h, s_local, d) of a sequence sharded contiguously
    along the axis. Returns the local output shard (b, h, s_local, d).
    Call inside shard_map/pjit with the sequence axis bound to ``axis_name``.
    """
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, s, block_q, block_k)
    return o


def _ring_vjp_fwd(q, k, v, axis_name, causal, scale, block_q, block_k):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, s, block_q, block_k)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis_name, causal, scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    lse = lse.astype(_f32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # diagonal contribution (own shard, still home)
    dq_acc, dk_cur, dv_cur = flash_attention_bwd(
        q, k, v, o, lse, do, scale=s, causal=causal,
        block_q=block_q, block_k=block_k)
    dq_acc = dq_acc.astype(_f32)
    dk_cur = dk_cur.astype(_f32)
    dv_cur = dv_cur.astype(_f32)

    def body(carry, step):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        # rotate the shard AND its gradient accumulators together
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        src = (my - step - 1) % n
        dq_j, dk_j, dv_j = flash_attention_bwd(
            q, k_cur, v_cur, o, lse, do, scale=s, causal=False,
            block_q=block_q, block_k=block_k)
        if causal:
            gate = (src < my).astype(_f32)
        else:
            gate = jnp.float32(1.0)
        dq_acc = dq_acc + gate * dq_j.astype(_f32)
        dk_cur = dk_cur + gate * dk_j.astype(_f32)
        dv_cur = dv_cur + gate * dv_j.astype(_f32)
        return (dq_acc, k_cur, v_cur, dk_cur, dv_cur), None

    if n > 1:
        (dq_acc, _, _, dk_cur, dv_cur), _ = jax.lax.scan(
            body, (dq_acc, k, v, dk_cur, dv_cur), jnp.arange(n - 1))
        # one final hop brings dK/dV home (n rotations total = identity)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
    return (dq_acc.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


ring_self_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Alias with the conventional name."""
    return ring_self_attention(q, k, v, axis_name, causal, scale)
