"""SyncBatchNorm — TPU equivalent of the ``syncbn`` kernels + removed
``apex.parallel.SyncBatchNorm`` frontend.

Reference: ``csrc/welford.cu`` — per-GPU Welford stats (``welford_kernel``
:218), cross-process parallel merge after all-gather
(``welford_kernel_parallel`` :502), BN fwd/bwd (:277,:334) with NCHW and
channels-last paths and fused ReLU backward (:565). Python spec:
``tests/distributed/synced_batchnorm/*``.

TPU design: local reduction + ``all_gather`` of per-device (mean, m2, count)
merged with the numerically-stable Chan/Welford pairwise formula — the exact
analog of ``welford_kernel_parallel``. Differentiation through the collectives
gives the cross-replica backward for free (psum transpose = psum), replacing
the handwritten ``batchnorm_backward_kernel``. Layout (NCHW vs NHWC) is an
``axis`` argument — XLA handles both without separate kernels.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _welford_merge(mean_a, m2_a, n_a, mean_b, m2_b, n_b):
    """Chan et al. pairwise merge (welford.cu:502 ``welford_kernel_parallel``)."""
    n = n_a + n_b
    delta = mean_b - mean_a
    safe_n = jnp.where(n > 0, n, 1.0)
    mean = mean_a + delta * n_b / safe_n
    m2 = m2_a + m2_b + delta * delta * n_a * n_b / safe_n
    return mean, m2, n


def sync_batch_norm_stats(x: jax.Array, reduce_axes: Sequence[int],
                          axis_name: Optional[str] = None,
                          axis_index_groups=None, shift=None):
    """Cross-replica Welford mean/var over ``reduce_axes`` (+ the device axis).

    ``axis_index_groups`` restricts the reduction to device subgroups — the
    ``bn_group`` semantics of the contrib group BN (groupbn/batch_norm.py) and
    the process-group subsets of tests/distributed/synced_batchnorm/test_groups.py.

    Returns ``(mean, var_biased, count_total)`` in fp32, shaped like the
    non-reduced (channel) dims.
    """
    x32 = x.astype(_f32)
    reduce_axes = tuple(a % x.ndim for a in reduce_axes)
    n_local = 1
    for a in reduce_axes:
        n_local *= x.shape[a]
    n_local = jnp.asarray(n_local, _f32)
    # SHIFTED one-pass local stats: E[d] and E[d²] for d = x - shift reduce
    # over a SINGLE read of x (XLA fuses both reductions and the subtract
    # into one loop), vs the centered two-pass form whose var reduction
    # re-reads x after mean is known. Plain E[x²]−E[x]² cancels
    # catastrophically when |mean| ≫ std; shifting by ANY within-a-few-std
    # estimate of the mean makes the cancellation relative to (mean−shift)²
    # ≈ std² instead of mean², restoring the robustness of the centered
    # form at one-pass cost. Default shift: the first element along the
    # reduced axes per channel (an O(C) read, not a pass) — a sample drawn
    # from the distribution is within ~std of the mean with overwhelming
    # probability, so every caller gets the robust path without opting in.
    # The cross-device merge below stays Welford/Chan (welford.cu:502).
    if shift is None:
        idx = tuple(0 if a in reduce_axes else slice(None)
                    for a in range(x.ndim))
        shift_c = jax.lax.stop_gradient(x32[idx])
        bc = tuple(1 if a in reduce_axes else x.shape[a]
                   for a in range(x.ndim))
    else:
        # shift has the channel (non-reduced) shape, e.g. (C,)
        shift_c = jax.lax.stop_gradient(jnp.asarray(shift, _f32))
        bc = tuple(1 if a in reduce_axes else x.shape[a]
                   for a in range(x.ndim))
    d = x32 - shift_c.reshape(bc)
    mean_d = jnp.mean(d, axis=reduce_axes)
    mean2_d = jnp.mean(d * d, axis=reduce_axes)
    var_l = jnp.maximum(mean2_d - mean_d * mean_d, 0.0)
    mean_l = shift_c.reshape(mean_d.shape) + mean_d
    m2_l = var_l * n_local

    if axis_name is None:
        return mean_l, var_l, n_local

    # gather per-device stats and merge pairwise (stable, order-independent
    # up to fp error — same structure as the reference's parallel merge)
    means = jax.lax.all_gather(mean_l, axis_name,
                               axis_index_groups=axis_index_groups)
    m2s = jax.lax.all_gather(m2_l, axis_name,
                             axis_index_groups=axis_index_groups)
    world = means.shape[0]
    counts = jnp.full((world,), n_local, _f32)

    def body(carry, xs):
        mean_a, m2_a, n_a = carry
        mean_b, m2_b, n_b = xs
        return _welford_merge(mean_a, m2_a, n_a, mean_b, m2_b, n_b), None

    (mean, m2, n), _ = jax.lax.scan(
        body, (means[0], m2s[0], counts[0]),
        (means[1:], m2s[1:], counts[1:]))
    return mean, m2 / n, n


class SyncBatchNorm(nn.Module):
    """flax module ≈ ``apex.parallel.SyncBatchNorm`` (README.md:76-81 surface).

    ``axis_name=None`` degrades to plain BatchNorm (single-device).
    ``channel_axis`` selects NCHW (1) or NHWC (-1) — both welford.cu layout
    variants. ``fuse_relu`` mirrors the fused ReLU path (:565); on TPU XLA
    fuses the activation into the normalization loop automatically.
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = "data"
    channel_axis: int = -1
    fuse_relu: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        ca = self.channel_axis % x.ndim
        reduce_axes = tuple(a for a in range(x.ndim) if a != ca)
        shape_bc = tuple(self.num_features if a == ca else 1
                         for a in range(x.ndim))

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((self.num_features,), _f32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((self.num_features,), _f32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # during init the mesh axis may not be bound yet → local stats
            axis = None if self.is_initializing() else self.axis_name
            mean, var, count = sync_batch_norm_stats(x, reduce_axes, axis)
            if self.track_running_stats and not self.is_initializing():
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_mean.value = ((1 - self.momentum) * ra_mean.value
                                 + self.momentum * mean)
                ra_var.value = ((1 - self.momentum) * ra_var.value
                                + self.momentum * unbiased)

        y = (x.astype(_f32) - mean.reshape(shape_bc)) * jax.lax.rsqrt(
            var.reshape(shape_bc) + self.eps)
        if self.affine:
            weight = self.param("weight", nn.initializers.ones,
                                (self.num_features,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros,
                              (self.num_features,), self.param_dtype)
            y = y * weight.reshape(shape_bc).astype(_f32) \
                + bias.reshape(shape_bc).astype(_f32)
        if self.fuse_relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype)
