// apex_tpu native runtime helpers — the C++ layer of the framework.
//
// Reference analog: the host-side C++ of apex — `apex_C`
// (csrc/flatten_unflatten.cpp: flatten/unflatten under flat-bucket DDP) and
// the chunk/bucket planning embedded in csrc/multi_tensor_apply.cuh:13-23
// (packing tensor fragments into launch-sized groups) plus the
// ParameterFragment range bookkeeping of
// apex/contrib/optimizers/distributed_fused_adam.py:389-414.
//
// On TPU the device-side work is XLA/Pallas; what stays host-side and
// latency-sensitive is the PLANNING over very large parameter lists
// (hundreds of thousands of leaves for big models — quadratic/slow in
// Python) and bulk host-memory packing for checkpoint/data staging. Exposed
// via a plain C ABI consumed with ctypes (no pybind11 in this image).
//
// Build: apex_tpu/_native/build.py (gcc -O3 -shared -fPIC). Every entry point
// has a pure-Python fallback in apex_tpu/utils/flatten.py — the native path
// is an accelerator, not a requirement.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Compute 128-lane-aligned offsets for packing `n` leaves of `sizes[i]`
// elements into one flat buffer. Writes offsets[n], padded[n]; returns the
// total padded size. (= FlatSpec planning, utils/flatten.py:flat_spec)
int64_t plan_flat(const int64_t* sizes, int64_t n, int64_t align,
                  int64_t* offsets, int64_t* padded) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t sz = sizes[i] > 0 ? sizes[i] : 1;
    int64_t pad = (sz + align - 1) / align * align;
    offsets[i] = off;
    padded[i] = pad;
    off += pad;
  }
  return off;
}

// Greedy per-dtype bucket assignment for flat-bucket gradient all-reduce
// (= apex.parallel DDP message_size bucketing; parallel/ddp.py
// _bucket_leaves). dtype_ids[i] groups leaves; buckets fill in order to
// >= message_size elements. Writes bucket_ids[n]; returns bucket count.
int64_t plan_buckets(const int64_t* sizes, const int32_t* dtype_ids,
                     int64_t n, int64_t message_size, int32_t* bucket_ids) {
  // stable per-dtype accumulation, preserving leaf order within a dtype
  std::vector<int32_t> seen_dtypes;
  int64_t next_bucket = 0;
  for (size_t pass = 0; pass < (size_t)n; ++pass) {
    // find dtypes in first-appearance order
    int32_t dt = dtype_ids[pass];
    bool found = false;
    for (int32_t s : seen_dtypes)
      if (s == dt) { found = true; break; }
    if (!found) seen_dtypes.push_back(dt);
  }
  for (int32_t dt : seen_dtypes) {
    int64_t cur = -1, cur_n = 0;
    for (int64_t i = 0; i < n; ++i) {
      if (dtype_ids[i] != dt) continue;
      if (cur < 0) cur = next_bucket++;
      bucket_ids[i] = (int32_t)cur;
      cur_n += sizes[i] > 0 ? sizes[i] : 1;
      if (cur_n >= message_size) { cur = -1; cur_n = 0; }
    }
  }
  return next_bucket;
}

// Multithreaded gather of `n` host arrays into one contiguous buffer at the
// planned offsets (bytes). The host-side "flatten" for checkpoint assembly /
// input staging (apex_C.flatten's role for host tensors).
void pack_bytes(const uint8_t** srcs, const int64_t* nbytes,
                const int64_t* dst_offsets, int64_t n, uint8_t* dst,
                int32_t num_threads) {
  if (num_threads < 1) num_threads = 1;
  auto worker = [&](int64_t t0, int64_t t1) {
    for (int64_t i = t0; i < t1; ++i)
      std::memcpy(dst + dst_offsets[i], srcs[i], (size_t)nbytes[i]);
  };
  if (num_threads == 1 || n < 4) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int64_t a = t * chunk, b = a + chunk > n ? n : a + chunk;
    if (a >= b) break;
    threads.emplace_back(worker, a, b);
  }
  for (auto& th : threads) th.join();
}

// Scatter back (host-side unflatten).
void unpack_bytes(const uint8_t* src, const int64_t* src_offsets,
                  const int64_t* nbytes, int64_t n, uint8_t** dsts,
                  int32_t num_threads) {
  if (num_threads < 1) num_threads = 1;
  auto worker = [&](int64_t t0, int64_t t1) {
    for (int64_t i = t0; i < t1; ++i)
      std::memcpy(dsts[i], src + src_offsets[i], (size_t)nbytes[i]);
  };
  if (num_threads == 1 || n < 4) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int64_t a = t * chunk, b = a + chunk > n ? n : a + chunk;
    if (a >= b) break;
    threads.emplace_back(worker, a, b);
  }
  for (auto& th : threads) th.join();
}

// ZeRO fragment bookkeeping (ParameterFragment math,
// distributed_fused_adam.py:389-414): for each leaf [offset, offset+size)
// in the flat buffer and a world of `world` equal shards of `shard_size`,
// emit per-leaf per-shard overlap ranges:
//   frag_shard[i], frag_leaf_begin[i], frag_leaf_end[i] (leaf-local),
//   frag_shard_begin[i] (shard-local). Returns fragment count (call once
//   with out=nullptr to size the buffers).
int64_t plan_fragments(const int64_t* offsets, const int64_t* sizes,
                       int64_t n, int64_t shard_size, int32_t* frag_leaf,
                       int32_t* frag_shard, int64_t* leaf_begin,
                       int64_t* leaf_end, int64_t* shard_begin) {
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t beg = offsets[i], end = offsets[i] + sizes[i];
    for (int64_t s = beg / shard_size; s * shard_size < end; ++s) {
      int64_t sb = s * shard_size, se = sb + shard_size;
      int64_t ob = beg > sb ? beg : sb;
      int64_t oe = end < se ? end : se;
      if (oe <= ob) continue;
      if (frag_leaf) {
        frag_leaf[count] = (int32_t)i;
        frag_shard[count] = (int32_t)s;
        leaf_begin[count] = ob - beg;
        leaf_end[count] = oe - beg;
        shard_begin[count] = ob - sb;
      }
      ++count;
    }
  }
  return count;
}

}  // extern "C"
