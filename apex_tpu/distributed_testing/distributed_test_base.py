"""Distributed test harness — TPU equivalent of
``apex/distributed_testing/distributed_test_base.py:24-131``.

The reference spawns one process per GPU (``MultiProcessTestCase``, world =
min(gpus, 4), file:// rendezvous, NCCL/UCC backends). On TPU a single process
drives all local devices, so the harness provides a mesh + shard_map context
instead of process spawning — and a CPU fallback mesh via
``xla_force_host_platform_device_count`` gives multi-"device" tests without
hardware, the fixture apex lacks (SURVEY §4).
"""

from __future__ import annotations

import functools
import unittest
from typing import Optional, Sequence

import jax
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.mesh import make_mesh


class DistributedTestBase(unittest.TestCase):
    """Subclass and use ``self.mesh`` / ``self.run_on_mesh``.

    ``world_size`` defaults to min(device_count, 8) — the analog of the
    reference's ``min(cuda.device_count(), 4)`` (:38-39).
    """

    axis_name = "data"
    max_world = 8

    @property
    def world_size(self) -> int:
        return min(jax.device_count(), self.max_world)

    @functools.cached_property
    def mesh(self) -> Mesh:
        return make_mesh([self.world_size], [self.axis_name])

    def run_on_mesh(self, fn, args, in_specs, out_specs):
        """shard_map + jit the per-device fn over the harness mesh."""
        f = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        return jax.jit(f)(*args)

    def skip_if_fewer_than(self, n: int):
        if jax.device_count() < n:
            self.skipTest(f"needs {n} devices, have {jax.device_count()}")


class NcclDistributedTestBase(DistributedTestBase):
    """Name-parity alias (:86): the TPU 'backend' is XLA-over-ICI."""


class UccDistributedTestBase(DistributedTestBase):
    """Name-parity alias (:99-131): no separate transport exists on TPU."""
