from apex_tpu.distributed_testing.distributed_test_base import (  # noqa: F401
    DistributedTestBase,
    NcclDistributedTestBase,
    UccDistributedTestBase,
)
