"""Live SLO tracking — declarative objectives, rolling windows,
multi-window burn rates, breach/recovery bus events.

An SLO here is an **error-budget objective**: "at most ``bad_frac_budget``
of events may be bad". The three serving objectives ship as constructors
(docs/observability.md "Live metrics, SLOs, and fleet aggregation"):

- :meth:`SLObjective.ttft_p99_ms` — "99% of completed requests reach
  their first token within N ms": a completion is *bad* when its TTFT
  exceeds the threshold; the budget is ``1 - 0.99``.
- :meth:`SLObjective.deadline_miss_frac` — at most this fraction of
  terminal requests expire on their deadline.
- :meth:`SLObjective.shed_frac` — at most this fraction of submitted
  requests are shed/rejected by admission control.

**Multi-window burn rate.** Each objective keeps two rolling windows of
(good, bad) events. The *burn rate* of a window is
``bad_frac / bad_frac_budget`` — 1.0 means the error budget is being
consumed exactly as fast as it accrues; 10 means ten times too fast. A
**breach** fires when the short AND long windows both burn at or above
``burn_factor`` (and the short window holds at least ``min_events``
events): the short window proves the damage is happening *now*, the long
window that it is not a blip — the standard SRE double condition that
keeps one bad tick from paging. **Recovery** fires when the short-window
burn drops back below the factor: the condition creating new damage has
stopped (the long window still remembers it, by design — re-breach is
cheap if it resumes).

Transitions publish ``serve_slo_breach`` / ``serve_slo_recovered`` on
the process event bus (registered in the goodput ``EVENT_SCHEMA``), so
the goodput ledger counts them, the Telemetry JSONL mirrors them, and
the flight recorder's ring holds them at crash time — zero wiring, the
PR-2 contract. The tracker is pure host-side bookkeeping on monotonic
clocks (``time.perf_counter``; APX005), driven by the serving scheduler
through :class:`~apex_tpu.serve.metrics.ServeMetrics`.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from apex_tpu.utils.logging import publish_event

# event sources an objective can observe (ServeMetrics feeds these)
SOURCES = ("ttft", "deadline", "shed")


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declarative objective. ``source`` names the event stream it
    consumes; ``threshold_s`` (latency objectives) classifies a sample
    as bad; ``bad_frac_budget`` is the error budget."""

    name: str
    source: str
    bad_frac_budget: float
    threshold_s: Optional[float] = None
    short_window_s: float = 60.0
    long_window_s: float = 300.0
    burn_factor: float = 1.0
    min_events: int = 8

    def __post_init__(self):
        if self.source not in SOURCES:
            raise ValueError(
                f"SLO source {self.source!r} not in {SOURCES}")
        if not 0.0 < self.bad_frac_budget <= 1.0:
            raise ValueError(
                f"bad_frac_budget must be in (0, 1]: "
                f"{self.bad_frac_budget}")
        if self.short_window_s <= 0:
            # a zero/negative span would prune every event at each
            # evaluate(): min_events never reached, a breach can never
            # fire — the tracker would be armed but structurally inert
            raise ValueError(
                f"window spans must be positive: "
                f"short={self.short_window_s}s long={self.long_window_s}s")
        if self.short_window_s >= self.long_window_s:
            raise ValueError(
                f"short window ({self.short_window_s}s) must be shorter "
                f"than the long window ({self.long_window_s}s)")

    # ---- the serving objectives ----------------------------------------
    @staticmethod
    def ttft_p99_ms(threshold_ms: float, **kw) -> "SLObjective":
        """99% of completions reach first token within ``threshold_ms``."""
        return SLObjective(name="ttft_p99_ms", source="ttft",
                           bad_frac_budget=0.01,
                           threshold_s=float(threshold_ms) / 1e3, **kw)

    @staticmethod
    def deadline_miss_frac(budget: float, **kw) -> "SLObjective":
        """At most ``budget`` of terminal requests miss their deadline."""
        return SLObjective(name="deadline_miss_frac", source="deadline",
                           bad_frac_budget=float(budget), **kw)

    @staticmethod
    def shed_frac(budget: float, **kw) -> "SLObjective":
        """At most ``budget`` of submissions are shed by admission."""
        return SLObjective(name="shed_frac", source="shed",
                           bad_frac_budget=float(budget), **kw)


class _Window:
    """Rolling (good, bad) event window: O(1) amortized add/prune with
    running totals — evaluation never rescans the event list."""

    def __init__(self, span_s: float):
        self.span_s = float(span_s)
        self._events: Deque[Tuple[float, bool]] = collections.deque()
        self.total = 0
        self.bad = 0

    def add(self, t: float, bad: bool) -> None:
        self._events.append((t, bad))
        self.total += 1
        self.bad += int(bad)

    def prune(self, now: float) -> None:
        horizon = now - self.span_s
        while self._events and self._events[0][0] < horizon:
            _, bad = self._events.popleft()
            self.total -= 1
            self.bad -= int(bad)

    @property
    def bad_frac(self) -> float:
        return self.bad / self.total if self.total else 0.0


class _ObjectiveState:
    def __init__(self, obj: SLObjective):
        self.obj = obj
        self.short = _Window(obj.short_window_s)
        self.long = _Window(obj.long_window_s)
        self.breached = False
        self.breaches = 0      # lifetime transition count

    def burn(self, window: _Window) -> float:
        return window.bad_frac / self.obj.bad_frac_budget


class SLOTracker:
    """Evaluate a set of :class:`SLObjective` over live event streams.

    ``observe(source, value=..., bad=...)`` feeds every objective bound
    to ``source``; ``evaluate()`` (the scheduler calls it once per tick)
    prunes windows, recomputes burn rates, and publishes exactly one
    ``serve_slo_breach`` / ``serve_slo_recovered`` event per state
    transition — a sustained storm raises ONE breach, its end ONE
    recovery, never a flap per tick (tier-1 asserts the exact pair).

    Single-threaded by contract: driven from the scheduler tick under the
    scheduler's lock (the same discipline as the admission controller).
    ``clock`` is injectable for deterministic tests; it must be a
    monotonic source (the default is ``time.perf_counter``)."""

    def __init__(self, objectives: Sequence[SLObjective], *,
                 clock=time.perf_counter):
        if not objectives:
            raise ValueError("SLOTracker needs at least one objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.clock = clock
        self._states = {o.name: _ObjectiveState(o) for o in objectives}

    @property
    def objectives(self) -> List[SLObjective]:
        return [s.obj for s in self._states.values()]

    def observe(self, source: str, *, value: Optional[float] = None,
                bad: Optional[bool] = None,
                t: Optional[float] = None) -> None:
        """One event on ``source``: either a measured ``value`` (latency
        objectives classify it against their threshold) or an explicit
        ``bad`` verdict (fraction objectives)."""
        now = self.clock() if t is None else t
        for state in self._states.values():
            obj = state.obj
            if obj.source != source:
                continue
            if obj.threshold_s is not None:
                if value is None:
                    continue    # a verdict-only event carries no latency
                is_bad = float(value) > obj.threshold_s
            elif bad is not None:
                is_bad = bool(bad)
            else:
                continue
            state.short.add(now, is_bad)
            state.long.add(now, is_bad)

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Prune windows, recompute burns, publish transitions. Returns
        the transition records (empty on the steady state)."""
        now = self.clock() if now is None else now
        transitions: List[Dict[str, Any]] = []
        for state in self._states.values():
            obj = state.obj
            state.short.prune(now)
            state.long.prune(now)
            burn_short = state.burn(state.short)
            burn_long = state.burn(state.long)
            hot = (burn_short >= obj.burn_factor
                   and burn_long >= obj.burn_factor
                   and state.short.total >= obj.min_events)
            fields = {
                "objective": obj.name, "source": obj.source,
                "burn_short": round(burn_short, 4),
                "burn_long": round(burn_long, 4),
                "bad_frac_short": round(state.short.bad_frac, 6),
                "bad_frac_long": round(state.long.bad_frac, 6),
                "budget": obj.bad_frac_budget,
            }
            if obj.threshold_s is not None:
                fields["threshold_ms"] = obj.threshold_s * 1e3
            if not state.breached and hot:
                state.breached = True
                state.breaches += 1
                transitions.append(publish_event(
                    "serve_slo_breach", level="warning", **fields))
            elif state.breached and burn_short < obj.burn_factor:
                state.breached = False
                transitions.append(publish_event(
                    "serve_slo_recovered", **fields))
        return transitions

    def summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-objective live state (the CLI prints it; ServeMetrics
        mirrors the burns into registry gauges per tick)."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, state in self._states.items():
            obj = state.obj
            out[name] = {
                "breached": state.breached,
                "breaches": state.breaches,
                "burn_short": round(state.burn(state.short), 4),
                "burn_long": round(state.burn(state.long), 4),
                "short_events": state.short.total,
                "long_events": state.long.total,
                "budget": obj.bad_frac_budget,
            }
            if obj.threshold_s is not None:
                out[name]["threshold_ms"] = obj.threshold_s * 1e3
        return out


def parse_slo_specs(specs: Sequence[str], *,
                    short_window_s: Optional[float] = None,
                    long_window_s: Optional[float] = None,
                    min_events: Optional[int] = None
                    ) -> List[SLObjective]:
    """CLI surface: ``NAME=VALUE`` specs (``ttft_p99_ms=50`` —
    threshold in ms; ``deadline_miss_frac=0.05`` / ``shed_frac=0.1`` —
    the error budget). Raises ``ValueError`` with the fix spelled out."""
    kw: Dict[str, Any] = {}
    if short_window_s is not None:
        kw["short_window_s"] = float(short_window_s)
    if long_window_s is not None:
        kw["long_window_s"] = float(long_window_s)
    if min_events is not None:
        kw["min_events"] = int(min_events)
    ctors = {"ttft_p99_ms": SLObjective.ttft_p99_ms,
             "deadline_miss_frac": SLObjective.deadline_miss_frac,
             "shed_frac": SLObjective.shed_frac}
    out: List[SLObjective] = []
    for spec in specs:
        name, sep, val = spec.partition("=")
        ctor = ctors.get(name.strip())
        if ctor is None or not sep:
            raise ValueError(
                f"--slo {spec!r}: want NAME=VALUE with NAME one of "
                f"{sorted(ctors)} (ttft_p99_ms takes a threshold in ms, "
                f"the _frac objectives take the error budget)")
        try:
            v = float(val)
            if not math.isfinite(v) or v <= 0:
                raise ValueError(v)
        except ValueError:
            raise ValueError(
                f"--slo {spec!r}: VALUE must be a positive number")
        out.append(ctor(v, **kw))
    return out
