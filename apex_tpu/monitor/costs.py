"""Compiled-step cost ledgers — phase-attributed, device-independent.

Every committed bench capture is a CPU-smoke record (ROADMAP standing
caveat): the regression gate has never gated a number that survives a
host swap. This module extracts what IS device-independent from the
engine's saved AOT artifacts: a deterministic per-executable **cost
ledger** — FLOPs, HBM bytes (operand-byte model), arithmetic intensity,
an op-family histogram, and a per-phase attribution keyed on the
``jax.named_scope`` markers the GPT-2 serving forwards carry
(``ln_qkv`` / ``attention`` / ``mlp`` / ``sampling`` / ``collective``).
Phase sums reconcile **exactly** with the executable totals by
construction (one walk accumulates both) — and the reconciliation is
re-derived independently in tier-1, the PR-13 precedent.

The walk generalizes ``serve/tp.py:count_collectives``: instead of
substring-counting collectives it parses every op line of the lowered
StableHLO (with MLIR debug info, so scope paths ride the ``loc(...)``
metadata), prices it with an analytic per-op model, and multiplies
``stablehlo.while`` region bodies by their parsed trip counts. On top
rides a roofline layer (:data:`CHIP_SPECS`): per-phase predicted step
time, a predicted-MFU bound, and — for tensor-parallel engines —
collective bytes per sync mode priced from the PR-15 contract.

**Import-time stdlib only.** Like ``monitor/export.py``, this module
never imports jax (or any ``apex_tpu`` sibling) at import time: the
jax-free consumers — ``tools/cost_diff.py`` and
``tools/check_regression.py`` — load it by file path, so the ONE
spelling of the ledger/gate-metric rules lives here and can never
diverge (the histogram_quantile delegation precedent). Functions that
touch jax objects (``lowered``/``compiled``) only call methods on them.

Entry points: ``Engine.cost_ledger()`` (serve/engine.py — rides the
saved ``_decode_lowered``/``_prefill_lowered``, never re-tracing),
``apex-tpu-bench --serve --cost-ledger PATH``, and the jax-free
``tools/cost_diff.py``. See docs/performance.md "Cost ledgers and
roofline gating".
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

LEDGER_SCHEMA = "apex_tpu.cost_ledger/v1"

# the phase vocabulary of the annotated GPT-2 serving forwards; "verify"
# is the speculative verify step's own work (the final LN + logits
# projection per verify position plus the in-graph acceptance test —
# PR 18; absent from one-token executables); "other" is the explicit
# remainder bucket (embedding lookup, cache advance, PRNG plumbing) so
# phase sums always equal the executable total
PHASES = ("ln_qkv", "attention", "mlp", "sampling", "verify",
          "collective", "other")

SYNC_MODES = ("exact", "overlap", "relaxed")

# chip-spec table for the roofline layer (bf16 peak TFLOPs, HBM GB/s —
# the same peaks utils/prof.py reports). "cpu" is the off-silicon
# fallback: its roofline projections are shape-checking only, so it is
# marked non-gating and `ledger_gate_metrics` withholds the
# roofline-derived families (the device-independent FLOP/byte/op
# families gate regardless — that is the point of the ledger).
CHIP_SPECS = {
    "v5e": {"tflops": 197.0, "hbm_gbps": 819.0, "gating": True},
    "v6e": {"tflops": 918.0, "hbm_gbps": 1640.0, "gating": True},
    "v5p": {"tflops": 459.0, "hbm_gbps": 2765.0, "gating": True},
    "cpu": {"tflops": 0.5, "hbm_gbps": 40.0, "gating": False},
}

# the device-side fields of CompiledMemoryStats (host_* mirrors skipped:
# they are zero everywhere we run and double the record size) — moved
# here from monitor/memory.py so the ledger and the hbm_snapshot events
# extract through one spelling
MEMORY_STATIC_KEYS = ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3B11FNUZ": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i4": 1, "ui4": 1, "i1": 1,
    "c64": 8, "c128": 16,
}

_FLOAT_PREFIXES = ("f", "bf")

# one scalar-output flop per element; the transcendental subset is also
# tallied separately (mirrors XLA cost_analysis' "transcendentals")
_TRANSCENDENTAL = frozenset({
    "exponential", "exponential_minus_one", "log", "log_plus_one",
    "logistic", "tanh", "sqrt", "rsqrt", "cbrt", "sine", "cosine",
    "tangent", "atan2", "power",
})
_ELEMENTWISE = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "negate", "abs", "sign", "floor", "ceil", "round_nearest_afz",
    "round_nearest_even", "remainder", "select", "clamp", "compare",
}) | _TRANSCENDENTAL
_REDUCES = frozenset({"reduce", "reduce_window"})
# control/region scaffolding: never recorded as ops (their region
# bodies are walked line by line instead)
_SKIP_OPS = frozenset({"while", "return", "func", "call", "if", "case"})

_COLLECTIVES = ("all_gather", "all_reduce", "all_to_all",
                "collective_permute")

_OP_RE = re.compile(r'(?:^|\s|=\s|")(?:stablehlo|mhlo|chlo|func)\.'
                    r'([A-Za-z_][A-Za-z0-9_]*)')
_LOC_TAIL_RE = re.compile(r'\s*loc\((?:#(loc[0-9]*))?\)\s*$')
_LOC_DEF_RE = re.compile(r'^#(loc[0-9]*) = loc\((.*)\)\s*$')
_LOC_REF_RE = re.compile(r'#(loc[0-9]*)')
_QUOTED_RE = re.compile(r'"([^"]*)"')
_TENSOR_RE = re.compile(r'tensor<([^>]*)>')
_CONTRACT_RE = re.compile(r'contracting_dims\s*=\s*\[([^\]]*)\]'
                          r'\s*x\s*\[([^\]]*)\]')
_SCALAR_CONST_RE = re.compile(
    r'%(\S+)\s*=\s*stablehlo\.constant\s+dense<(\d+)>\s*:\s*tensor<[su]?i')
_FUNC_RE = re.compile(r'^\s*func\.func\s+(?:[a-z]+\s+)?@([\w$.-]+)\s*\(')
_CALL_RE = re.compile(r'(?<![\w.])(?:func\.)?call\s+@([\w$.-]+)')


def _sig6(x: float) -> float:
    """6 significant digits — stable, readable floats in the ledger."""
    return float(f"{float(x):.6g}")


# --------------------------------------------------------------- parsing

def _tensor_info(spec: str) -> Tuple[int, str, int]:
    """``(elements, dtype, bytes)`` for a ``tensor<...>`` body like
    ``2x256xf32`` (scalar tensors have no dims; dynamic dims count 1)."""
    parts = spec.split("x")
    dtype = parts[-1].strip()
    elems = 1
    for p in parts[:-1]:
        p = p.strip()
        if p.isdigit():
            elems *= int(p)
    return elems, dtype, elems * _DTYPE_BYTES.get(dtype, 4)


def _is_float(dtype: str) -> bool:
    return dtype.startswith(_FLOAT_PREFIXES)


def _signature(body: str) -> Optional[Tuple[Optional[List[str]], List[str]]]:
    """``(operand_tensor_specs | None, result_tensor_specs)`` from the
    trailing type signature of an op line (loc already stripped).
    ``None`` operands means the uniform form (``%r = op %a, %b : T``):
    the caller counts ``%``-refs instead."""
    idx = body.rfind(" : ")
    if idx < 0:
        return None
    sig = body[idx + 3:].strip()
    if "->" in sig:
        lhs, rhs = sig.split("->", 1)
        return _TENSOR_RE.findall(lhs), _TENSOR_RE.findall(rhs)
    return None, _TENSOR_RE.findall(sig)


def _uniform_operand_count(body: str) -> int:
    """Operand count for the uniform type form: ``%``-refs on the RHS of
    the assignment (attributes never contain ``%``)."""
    rhs = body.split(" = ", 1)[-1]
    idx = rhs.rfind(" : ")
    if idx >= 0:
        rhs = rhs[:idx]
    return rhs.count("%")


def _phase_resolver(text: str) -> Callable[[Optional[str]], str]:
    """Map a ``#locN`` id to its phase by walking the MLIR location
    footer: scope paths live in quoted strings
    (``"jit(f)/jit(main)/attention/dot_general"``), possibly behind
    callsite/fused chains of further ``#loc`` refs. Innermost scope
    wins, so a ``collective`` scope nested inside ``mlp`` attributes to
    ``collective``."""
    defs: Dict[str, str] = {}
    for line in text.splitlines():
        if not line.startswith("#loc"):
            continue
        m = _LOC_DEF_RE.match(line)
        if m:
            defs[m.group(1)] = m.group(2)
    memo: Dict[str, str] = {}

    def from_path(name: str) -> Optional[str]:
        # loc bodies quote SOURCE FILE paths too ("/a/verify/drive.py");
        # a directory that happens to be named after a phase must not
        # claim the op — only named_scope paths (last segment is the op
        # primitive, never a filename) are phase evidence
        if name.rsplit("/", 1)[-1].endswith((".py", ".pyi")):
            return None
        for seg in reversed(name.split("/")):
            for ph in PHASES[:-1]:
                if seg == ph or (seg.startswith(ph + "_")
                                 and seg[len(ph) + 1:].isdigit()):
                    return ph
        return None

    def resolve(loc: Optional[str], depth: int = 0) -> str:
        if loc is None or loc not in defs or depth > 25:
            return "other"
        if loc in memo:
            return memo[loc]
        memo[loc] = "other"          # cycle guard
        body = defs[loc]
        for q in _QUOTED_RE.findall(body):
            ph = from_path(q)
            if ph:
                memo[loc] = ph
                return ph
        for ref in _LOC_REF_RE.findall(body):
            if ref != loc:
                ph = resolve(ref, depth + 1)
                if ph != "other":
                    memo[loc] = ph
                    return ph
        return memo[loc]

    return resolve


def _while_spans(lines: List[str], i: int, end: int
                 ) -> Optional[Tuple[int, int, int, int, int]]:
    """Region spans of the ``stablehlo.while`` at line ``i``:
    ``(cond_start, cond_end, body_start, body_end, next_line)`` —
    half-open line ranges found by brace matching from the ``cond {``
    opener (attribute-dict braces are balanced per line at depth >= 1,
    so only region braces cross zero)."""
    j = i
    while j < min(i + 3, end) and "cond" not in lines[j]:
        j += 1
    if j >= min(i + 3, end) or "{" not in lines[j]:
        return None
    depth = 0
    opens: List[int] = []
    closes: List[int] = []
    k = j
    while k < end:
        for ch in lines[k]:
            if ch == "{":
                depth += 1
                if depth == 1:
                    opens.append(k)
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    closes.append(k)
        if len(closes) == 2:
            return (opens[0] + 1, closes[0], opens[1] + 1, closes[1],
                    closes[1] + 1)
        k += 1
    return None


def _trip_count(lines: List[str], start: int, end: int,
                consts: Dict[str, int]) -> Optional[int]:
    """Trip count of a while loop from its cond region: the jax
    counted-loop pattern ``compare LT, %iterArg, %bound`` where
    ``%bound`` is a scalar integer constant (in the region or collected
    earlier at module scope). ``None`` when the loop is not provably
    counted (walked with multiplier 1 + a ledger note)."""
    local = dict(consts)
    cmp_line = None
    for k in range(start, end):
        m = _SCALAR_CONST_RE.search(lines[k])
        if m:
            local[m.group(1)] = int(m.group(2))
        if "stablehlo.compare" in lines[k] and "%iterArg" in lines[k]:
            cmp_line = lines[k]
    if cmp_line is None:
        return None
    for name in re.findall(r'%(\S+?)[,\s:]', cmp_line):
        if name in local and not name.startswith("iterArg"):
            return local[name]
    return None


def _flops_for(op: str, operands: List[Tuple[int, str, int]],
               results: List[Tuple[int, str, int]], body: str) -> int:
    if op == "dot_general":
        if not results:
            return 0
        out_elems = results[0][0]
        contract = 1
        m = _CONTRACT_RE.search(body)
        if m:
            # lhs shape from the signature's first operand spec
            sig = _signature(body)
            lhs_shape: List[int] = []
            if sig and sig[0]:
                parts = sig[0][0].split("x")[:-1]
                lhs_shape = [int(p) for p in parts if p.strip().isdigit()]
            for idx in m.group(1).split(","):
                idx = idx.strip()
                if idx.isdigit() and int(idx) < len(lhs_shape):
                    contract *= lhs_shape[int(idx)]
        return 2 * out_elems * contract
    if op in _REDUCES:
        if operands and _is_float(operands[0][1]):
            return operands[0][0]
        return 0
    if op in _ELEMENTWISE:
        if results and _is_float(results[0][1]):
            return results[0][0]
        # compare on floats produces i1 — charge the operand elements
        if op == "compare" and operands and _is_float(operands[0][1]):
            return operands[0][0]
        return 0
    return 0


def walk_module(text: str) -> Dict[str, Any]:
    """Deterministic analytic walk of a lowered StableHLO module (debug-
    info form from :func:`stablehlo_debug_text`). Returns totals, the
    per-phase attribution, the op-family histogram, and collective
    counts/bytes. Phase sums equal totals by construction — one
    accumulation pass feeds both.

    The byte model is XLA's operand-byte convention (every op charges
    operand + result bytes — an HBM upper bound that ignores fusion /
    VMEM reuse; see the ``roofline()`` caveat in utils/prof.py). FLOPs:
    ``dot_general`` = 2·|out|·|contraction|, elementwise float = |out|,
    reduce = |in|; data movement (reshape/convert/slice/...) = 0.
    ``stablehlo.while`` bodies multiply by the parsed trip count, so a
    prefill scan prices every scanned token. ``func.call`` sites walk
    the callee's body at the caller's multiplicity (jax outlines scan
    bodies into ``func.func private`` functions), so outlined loop
    bodies price once per trip, not once per module."""
    lines = text.splitlines()
    resolve = _phase_resolver(text)
    phases = {ph: {"ops": 0, "flops": 0, "hbm_bytes": 0,
                   "transcendentals": 0} for ph in PHASES}
    families: Dict[str, int] = {}
    collectives = {k: 0 for k in ("all_gather", "all_reduce",
                                  "all_to_all", "permute")}
    collective_bytes = 0
    consts: Dict[str, int] = {}
    notes: List[str] = []

    def record(line: str, op: str, mult: int) -> None:
        nonlocal collective_bytes
        locm = _LOC_TAIL_RE.search(line)
        body = line[:locm.start()] if locm else line
        phase = resolve(locm.group(1) if locm else None)
        m = _SCALAR_CONST_RE.search(body)
        if m:
            consts[m.group(1)] = int(m.group(2))
        sig = _signature(body)
        operands: List[Tuple[int, str, int]] = []
        results: List[Tuple[int, str, int]] = []
        if sig is not None:
            op_specs, res_specs = sig
            results = [_tensor_info(s) for s in res_specs]
            if op_specs is None:
                n = 0 if op == "constant" else _uniform_operand_count(body)
                operands = results[:1] * n
            else:
                operands = [_tensor_info(s) for s in op_specs]
        nbytes = sum(o[2] for o in operands) + sum(r[2] for r in results)
        flops = _flops_for(op, operands, results, body)
        bucket = phases[phase]
        bucket["ops"] += mult
        bucket["flops"] += mult * flops
        bucket["hbm_bytes"] += mult * nbytes
        if op in _TRANSCENDENTAL and flops:
            bucket["transcendentals"] += mult * flops
        families[op] = families.get(op, 0) + mult
        if op in _COLLECTIVES:
            key = "permute" if op == "collective_permute" else op
            collectives[key] += mult
            collective_bytes += mult * sum(r[2] for r in results)

    # function bodies by name: jax outlines scan/cond bodies into
    # private funcs reached via func.call — walked at the call site's
    # multiplicity, never at module scope
    funcs: Dict[str, Tuple[int, int]] = {}
    n = len(lines)
    i = 0
    while i < n:
        fm = _FUNC_RE.match(lines[i])
        if fm is None:
            i += 1
            continue
        # the signature line nets +1 (attribute dicts balance within
        # it; the body brace stays open) — accumulate it whole, then
        # close where cumulative depth first returns to zero
        depth = lines[i].count("{") - lines[i].count("}")
        close = None
        k = i + 1
        while k < n and close is None:
            for ch in lines[k]:
                if ch == "{":
                    depth += 1
                elif ch == "}":
                    depth -= 1
                    if depth == 0:
                        close = k
                        break
            k += 1
        if close is None:
            close = n - 1
        funcs[fm.group(1)] = (i + 1, close)
        i = close + 1

    def walk(start: int, end: int, mult: int,
             stack: Tuple[str, ...]) -> None:
        i = start
        while i < end:
            line = lines[i]
            stripped = line.lstrip()
            if stripped.startswith(("#loc", "module")):
                i += 1          # loc metadata / module-attribute lines
                continue
            cm = _CALL_RE.search(line)
            if cm is not None:
                callee = funcs.get(cm.group(1))
                if (callee is not None and cm.group(1) not in stack
                        and len(stack) < 25):
                    walk(callee[0], callee[1], mult,
                         stack + (cm.group(1),))
                i += 1
                continue
            m = _OP_RE.search(line)
            op = m.group(1) if m else None
            if op == "while":
                spans = _while_spans(lines, i, end)
                if spans is None:
                    i += 1
                    continue
            else:
                spans = None
            if spans is not None:
                c0, c1, b0, b1, nxt = spans
                trip = _trip_count(lines, c0, c1, consts)
                if trip is None:
                    trip = 1
                    notes.append(f"while@line{i}: trip count not "
                                 f"statically resolvable; counted once")
                walk(c0, c1, mult, stack)   # cond: ~trip cheap compares
                walk(b0, b1, mult * trip, stack)
                i = nxt
                continue
            if op is not None and op not in _SKIP_OPS:
                record(line, op, mult)
            i += 1

    entry = "main" if "main" in funcs else (next(iter(funcs), None))
    if entry is not None:
        walk(funcs[entry][0], funcs[entry][1], 1, (entry,))
    else:
        walk(0, n, 1, ())
    total = {"ops": sum(p["ops"] for p in phases.values()),
             "flops": sum(p["flops"] for p in phases.values()),
             "hbm_bytes": sum(p["hbm_bytes"] for p in phases.values()),
             "transcendentals": sum(p["transcendentals"]
                                    for p in phases.values())}
    total["arithmetic_intensity"] = _sig6(
        total["flops"] / total["hbm_bytes"]) if total["hbm_bytes"] else 0.0
    out = {"total": total, "phases": phases,
           "op_families": dict(sorted(families.items())),
           "collectives": collectives,
           "collective_bytes": collective_bytes}
    if notes:
        out["notes"] = sorted(set(notes))
    return out


# ------------------------------------------------ jax-object extractors

def stablehlo_debug_text(lowered, large_elements_limit: int = 8) -> str:
    """The lowered module's StableHLO text WITH MLIR debug info — scope
    paths appear only in ``loc(...)`` metadata, which the default
    ``as_text()`` strips. ``large_elements_limit`` elides baked-in param
    constants (a decode lowering with closed-over weights is ~15 MB of
    hex without it)."""
    try:
        ir = lowered.compiler_ir()
        return ir.operation.get_asm(
            enable_debug_info=True,
            large_elements_limit=large_elements_limit)
    except Exception:
        # no debug info available: the walk still totals correctly,
        # every op just lands in the "other" phase
        return lowered.as_text()


def collective_counts(stablehlo_text: str) -> Dict[str, int]:
    """Collective-op counts by substring — THE spelling behind
    ``serve/tp.py:count_collectives`` (which delegates here). Pre-XLA-
    pass text, so only shard_map-explicit collectives count, never a
    compiler resharding."""
    return {
        "all_gather": stablehlo_text.count("stablehlo.all_gather"),
        "all_reduce": stablehlo_text.count("stablehlo.all_reduce"),
        "all_to_all": stablehlo_text.count("stablehlo.all_to_all"),
        "permute": stablehlo_text.count("collective_permute"),
    }


def expected_collective_ops(n_layer: int, sync: str) -> Dict[str, int]:
    """The per-decode-step collective CONTRACT per sync mode (the PR-15
    contract; ``serve/tp.py:expected_collectives`` delegates here):
    exact = 2 all-gathers/layer, overlap = 4 half-psum all-reduces/layer
    (TokenWeave), relaxed = 2 (one deferred logical all-reduce split in
    slot halves)."""
    if sync == "exact":
        return {"all_gather": 2 * n_layer, "all_reduce": 0}
    if sync == "overlap":
        return {"all_gather": 0, "all_reduce": 4 * n_layer}
    if sync == "relaxed":
        return {"all_gather": 0, "all_reduce": 2 * n_layer}
    raise ValueError(f"unknown tp_sync mode {sync!r}; "
                     f"pick one of {SYNC_MODES}")


def xla_cost_record(compiled) -> Optional[Dict[str, float]]:
    """``compiled.cost_analysis()`` flattened to the stable keys — THE
    spelling the three pre-existing call sites (monitor/metrics.py,
    utils/prof.py, Telemetry.calibrate) now share. ``None`` when the
    backend reports no analysis."""
    if compiled is None:
        return None
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    if not isinstance(ca, dict) or not ca:
        return None
    out = {"flops": float(ca.get("flops", 0.0)),
           "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    if "transcendentals" in ca:
        out["transcendentals"] = float(ca["transcendentals"])
    return out


def xla_flops(compiled) -> float:
    rec = xla_cost_record(compiled)
    return rec["flops"] if rec else 0.0


def memory_analysis_record(compiled) -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` as a plain int dict (plus the
    derived ``reserved_bytes`` total), or ``None`` when the executable
    doesn't expose one. Moved from monitor/memory.py (which delegates
    here) so the ledger and the hbm_snapshot events can never diverge."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for k in MEMORY_STATIC_KEYS:
        v = getattr(ma, k, None)
        if isinstance(v, (int, float)):
            out[k] = int(v)
    if not out:
        return None
    out["reserved_bytes"] = (out.get("argument_size_in_bytes", 0)
                             + out.get("output_size_in_bytes", 0)
                             + out.get("temp_size_in_bytes", 0))
    return out


def executable_record(lowered, compiled=None) -> Dict[str, Any]:
    """One executable's ledger entry: the deterministic analytic walk
    plus XLA's own cost/memory analyses (kept separately under ``xla`` —
    the analytic model is the gateable one; XLA's numbers are the
    cross-check)."""
    rec = walk_module(stablehlo_debug_text(lowered))
    xla: Dict[str, Any] = {}
    cost = xla_cost_record(compiled)
    if cost is not None:
        xla["cost_analysis"] = cost
    mem = memory_analysis_record(compiled)
    if mem is not None:
        xla["memory_analysis"] = mem
    if xla:
        rec["xla"] = xla
    return rec


# ----------------------------------------------------- roofline pricing

def roofline_record(walk: Dict[str, Any], chip: str) -> Dict[str, Any]:
    """Roofline projection of one walked executable on ``chip``: per-
    phase MXU/HBM times, the binding resource, a predicted step time
    (sum of per-phase maxima — phases serialize; within a phase compute
    and memory overlap), and the predicted-MFU bound."""
    spec = CHIP_SPECS.get(chip)
    if spec is None:
        raise ValueError(f"unknown chip spec {chip!r}; "
                         f"pick one of {sorted(CHIP_SPECS)}")
    peak_flops = spec["tflops"] * 1e12
    peak_bw = spec["hbm_gbps"] * 1e9
    per_phase: Dict[str, Any] = {}
    step_s = 0.0
    for ph, p in walk["phases"].items():
        t_mxu = p["flops"] / peak_flops
        t_hbm = p["hbm_bytes"] / peak_bw
        t = max(t_mxu, t_hbm)
        step_s += t
        if p["ops"]:
            per_phase[ph] = {"t_mxu_us": _sig6(t_mxu * 1e6),
                             "t_hbm_us": _sig6(t_hbm * 1e6),
                             "bound": "mxu" if t_mxu > t_hbm else "hbm",
                             "t_us": _sig6(t * 1e6)}
    flops = walk["total"]["flops"]
    return {"chip": chip, "gating": bool(spec["gating"]),
            "per_phase": per_phase,
            "predicted_step_time_us": _sig6(step_s * 1e6),
            "predicted_mfu": _sig6(flops / (peak_flops * step_s))
            if step_s > 0 else 0.0}


def price_collectives(n_layer: int, n_embd: int, num_slots: int,
                      tp: int, dtype_bytes: int = 4) -> Dict[str, Any]:
    """Predicted per-decode-step collective bytes-on-wire per sync mode,
    priced from the PR-15 contract and the model dims (ring cost:
    all-gather moves (tp-1)/tp of the full payload per device,
    all-reduce 2·(tp-1)/tp of the partial). Payloads per layer: exact
    gathers the attention heads [B, e] and the MLP hidden [B, 4e];
    overlap all-reduces two [B, e] partials split in slot halves;
    relaxed lands ONE combined [B, e] partial in halves."""
    ring_ag = (tp - 1) / tp
    ring_ar = 2 * (tp - 1) / tp
    b, e = num_slots, n_embd
    per_layer = {
        "exact": ring_ag * b * (e + 4 * e) * dtype_bytes,
        "overlap": ring_ar * 2 * b * e * dtype_bytes,
        "relaxed": ring_ar * b * e * dtype_bytes,
    }
    return {mode: {"ops": expected_collective_ops(n_layer, mode),
                   "bytes_on_wire_per_step": int(n_layer
                                                 * per_layer[mode])}
            for mode in SYNC_MODES}


# --------------------------------------------------------- ledger build

def build_ledger(executables: Dict[str, Dict[str, Any]],
                 workload: Dict[str, Any],
                 chip: str = "cpu") -> Dict[str, Any]:
    """Assemble the provenance-stamped ledger document. Deterministic:
    no wall clocks, no environment reads — two builds from the same AOT
    artifacts are byte-identical under ``json.dumps(sort_keys=True)``
    (tier-1 asserts exactly that). Writers that want capture provenance
    (git, device_kind, timestamps) stamp it under ``meta`` at write time
    (``apex-tpu-bench --cost-ledger``) so it never breaks determinism
    of the ledger body."""
    spec = CHIP_SPECS.get(chip)
    if spec is None:
        raise ValueError(f"unknown chip spec {chip!r}; "
                         f"pick one of {sorted(CHIP_SPECS)}")
    executables = {name: dict(rec) for name, rec in executables.items()}
    for rec in executables.values():
        rec["roofline"] = roofline_record(rec, chip)
    ledger: Dict[str, Any] = {
        "schema": LEDGER_SCHEMA,
        "chip_spec": chip,
        "gating": bool(spec["gating"]),
        "workload": dict(workload),
        "executables": executables,
    }
    dec = executables.get("decode")
    if dec is not None:
        slots = max(int(workload.get("num_slots", 1)), 1)
        ledger["derived"] = {
            "decode_flops_per_token": _sig6(dec["total"]["flops"] / slots),
            "decode_hbm_bytes_per_token": _sig6(
                dec["total"]["hbm_bytes"] / slots),
            "decode_ops_total": dec["total"]["ops"],
            "predicted_mfu": dec["roofline"]["predicted_mfu"],
        }
    tp = int(workload.get("tp", 1) or 1)
    if tp > 1 and dec is not None:
        n_layer = int(workload.get("n_layer", 0))
        ledger["collective_pricing"] = price_collectives(
            n_layer, int(workload.get("n_embd", 0)),
            int(workload.get("num_slots", 1)), tp,
            int(workload.get("dtype_bytes", 4)))
        sync = workload.get("tp_sync") or "exact"
        ledger["contract"] = {
            "tp_sync": sync,
            "expected": expected_collective_ops(n_layer, sync),
            "counted": dec["collectives"],
        }
    return ledger


# workload/provenance axes on which two ledgers are INCOMPARABLE (the
# check_regression INCOMPARABLE_WORKLOAD_KEYS discipline, extended with
# the ledger-specific axes: a different dtype/page_size/slot count/chip
# spec prices a different step). Dict value = the default for a missing
# key, mirroring tools/check_regression.py.
LEDGER_INCOMPARABLE_KEYS = {
    "tp": 1, "tp_sync": None, "page_size": 0, "dtype": None,
    "num_slots": None, "max_len": None, "chip_spec": None,
    # speculative decoding (PR 18): a verify-step ledger prices
    # draft_len + 1 positions per step — never gate it against a
    # one-token ledger. Missing keys = speculation off (pre-PR-18
    # ledgers are one-token by construction).
    "spec_draft_len": 0, "decode_policy": None,
    # block-scale KV quantization (apex_tpu.quant): a quantized
    # decode step's HBM bytes are the codec bytes + scale planes — a
    # real win that must never gate against an fp32 ledger as if it
    # were an optimization of the same workload. Missing keys =
    # unquantized (pre-quant ledgers stored full-width K/V).
    "kv_quant": None, "quant_block": 0,
}


def is_ledger(doc: Any) -> bool:
    return isinstance(doc, dict) and doc.get("schema") == LEDGER_SCHEMA


def ledger_workload_axes(ledger: Dict[str, Any]) -> Dict[str, Any]:
    w = ledger.get("workload") or {}
    axes = {k: w.get(k, d) for k, d in LEDGER_INCOMPARABLE_KEYS.items()
            if k != "chip_spec"}
    axes["chip_spec"] = ledger.get("chip_spec")
    return axes


def provenance_mismatch(cur: Dict[str, Any],
                        base: Dict[str, Any]) -> List[str]:
    """Human-readable reasons two ledgers must NOT be compared (empty
    list = comparable). ``tools/cost_diff.py`` exits 2 on any."""
    reasons: List[str] = []
    for doc, tag in ((cur, "current"), (base, "baseline")):
        if not is_ledger(doc):
            reasons.append(f"{tag} is not a {LEDGER_SCHEMA} document")
    if reasons:
        return reasons
    ca, ba = ledger_workload_axes(cur), ledger_workload_axes(base)
    for k in LEDGER_INCOMPARABLE_KEYS:
        if ca.get(k) != ba.get(k):
            reasons.append(f"workload.{k}={ca.get(k)!r} vs baseline "
                           f"workload.{k}={ba.get(k)!r}")
    return reasons


def ledger_gate_metrics(ledger: Dict[str, Any]) -> Dict[str, float]:
    """The flat, gateable metric view of a ledger — THE spelling
    check_regression loads by path. The device-independent families
    (``*_flops_per_token`` / ``*_hbm_bytes_per_token`` / ``*_ops_total``,
    lower-is-better) always gate; the roofline-derived families
    (``predicted_mfu`` higher-is-better, ``predicted_step_time_us``)
    only when the chip spec is a gating one (never the cpu fallback)."""
    out: Dict[str, float] = {}
    gating = bool(ledger.get("gating"))
    for k, v in (ledger.get("derived") or {}).items():
        if not gating and k.startswith("predicted_"):
            continue
        out[k] = float(v)
    slots = max(int((ledger.get("workload") or {}).get("num_slots", 1)
                    or 1), 1)
    dec = (ledger.get("executables") or {}).get("decode")
    if dec is not None:
        for ph, p in dec.get("phases", {}).items():
            if not p.get("ops"):
                continue
            out[f"decode.{ph}_flops_per_token"] = _sig6(
                p["flops"] / slots)
            out[f"decode.{ph}_hbm_bytes_per_token"] = _sig6(
                p["hbm_bytes"] / slots)
        if gating:
            out["predicted_step_time_us"] = float(
                dec["roofline"]["predicted_step_time_us"])
    return out


def diff_ledgers(cur: Dict[str, Any],
                 base: Dict[str, Any]) -> Dict[str, Any]:
    """Per-phase / per-op-family / derived deltas between two
    provenance-compatible ledgers (``tools/cost_diff.py`` renders
    this). Ratios are current/baseline; baseline-zero rows report the
    absolute delta only."""
    def row(c: float, b: float) -> Dict[str, Any]:
        r = {"baseline": b, "current": c, "delta": _sig6(c - b)}
        if b:
            r["ratio"] = _sig6(c / b)
        return r

    out: Dict[str, Any] = {"derived": {}, "executables": {}}
    dc, db = cur.get("derived") or {}, base.get("derived") or {}
    for k in sorted(set(dc) & set(db)):
        out["derived"][k] = row(float(dc[k]), float(db[k]))
    ec, eb = cur.get("executables") or {}, base.get("executables") or {}
    for name in sorted(set(ec) & set(eb)):
        c, b = ec[name], eb[name]
        ex: Dict[str, Any] = {
            "total": {k: row(c["total"][k], b["total"][k])
                      for k in ("ops", "flops", "hbm_bytes")},
            "phases": {}, "op_families": {}}
        for ph in PHASES:
            pc = c["phases"].get(ph, {})
            pb = b["phases"].get(ph, {})
            if not (pc.get("ops") or pb.get("ops")):
                continue
            ex["phases"][ph] = {
                k: row(pc.get(k, 0), pb.get(k, 0))
                for k in ("ops", "flops", "hbm_bytes")}
        for fam in sorted(set(c.get("op_families", {}))
                          | set(b.get("op_families", {}))):
            fc = c.get("op_families", {}).get(fam, 0)
            fb = b.get("op_families", {}).get(fam, 0)
            if fc != fb:
                ex["op_families"][fam] = row(fc, fb)
        out["executables"][name] = ex
    return out
