"""apex_tpu.monitor — first-class training telemetry.

The observability layer the reference never had (SURVEY §5: ad-hoc NVTX
ranges and per-example AverageMeters). Three cooperating pieces:

- :mod:`~apex_tpu.monitor.metrics` — jit-safe :class:`TrainMetrics` pytree
  (grad/param/update norms, overflow flag, loss scale) collected INSIDE the
  step function with zero extra host syncs.
- :mod:`~apex_tpu.monitor.telemetry` — the unified :class:`Telemetry` sink:
  JSONL + console metric rows, mirrored ``structured_warning`` events,
  trace spans, per-step ``step_ms``/``tokens_per_s``/``mfu`` from the XLA
  cost model, rank-0 gating on multihost.
- :mod:`~apex_tpu.monitor.goodput` — :class:`GoodputLedger`: productive vs.
  lost step-time (overflow skips, checkpoint stalls, preemption), fed by
  the resilience event stream.

``tools/check_regression.py`` turns the emitted JSONL into a CI gate
against a committed bench baseline. See docs/observability.md.
"""

from apex_tpu.monitor.goodput import GoodputLedger  # noqa: F401
from apex_tpu.monitor.metrics import (  # noqa: F401
    TrainMetrics, collect_metrics, step_flops, tree_l2norm)
from apex_tpu.monitor.telemetry import (  # noqa: F401
    PERF_ROW_KEYS, Telemetry, read_jsonl, validate_row)

__all__ = [
    "GoodputLedger", "TrainMetrics", "collect_metrics", "step_flops",
    "tree_l2norm", "PERF_ROW_KEYS", "Telemetry", "read_jsonl",
    "validate_row",
]
