"""apex_tpu.monitor — first-class training telemetry.

The observability layer the reference never had (SURVEY §5: ad-hoc NVTX
ranges and per-example AverageMeters). Cooperating pieces:

- :mod:`~apex_tpu.monitor.metrics` — jit-safe :class:`TrainMetrics` pytree
  (grad/param/update norms, overflow flag, loss scale) collected INSIDE the
  step function with zero extra host syncs.
- :mod:`~apex_tpu.monitor.telemetry` — the unified :class:`Telemetry` sink:
  JSONL + console metric rows, mirrored ``structured_warning`` events,
  trace spans, per-step ``step_ms``/``tokens_per_s``/``mfu`` from the XLA
  cost model, rank-0 gating on multihost.
- :mod:`~apex_tpu.monitor.goodput` — :class:`GoodputLedger`: productive vs.
  lost step-time (overflow skips, checkpoint stalls, preemption), fed by
  the resilience event stream; also the registered event-name schema
  (``EVENT_SCHEMA``) every bus publisher must use.
- :mod:`~apex_tpu.monitor.trace` — request/step-scoped span-tree tracing
  (:class:`Tracer`) with Perfetto/Chrome-trace export
  (:class:`ChromeTraceWriter`), riding the same event bus.
- :mod:`~apex_tpu.monitor.memory` — HBM accounting: sampled allocator
  stats (:class:`MemoryAccountant`) and static XLA reservations at every
  AOT point, as ``hbm_snapshot`` events.
- :mod:`~apex_tpu.monitor.flight` — :class:`FlightRecorder`: bounded ring
  of bus events + open spans + memory + thread stacks, dumped atomically
  on watchdog escalation / preemption / fatal exceptions.
- :mod:`~apex_tpu.monitor.export` — live metrics: the streaming
  :class:`MetricsRegistry` (counters, gauges, log-bucketed **mergeable**
  histograms), Prometheus-text/JSON export, the stdlib
  :class:`MetricsExporter` pull endpoint, and atomic snapshot files that
  ``tools/metrics_merge.py`` folds into one fleet view.
- :mod:`~apex_tpu.monitor.slo` — :class:`SLOTracker`: declarative
  objectives over short/long rolling windows with multi-window burn
  rates, publishing ``serve_slo_breach``/``serve_slo_recovered``.

``tools/check_regression.py`` turns the emitted JSONL (or a metrics
snapshot) into a CI gate against a committed bench baseline. See
docs/observability.md.
"""

from apex_tpu.monitor import costs  # noqa: F401
from apex_tpu.monitor.export import (  # noqa: F401
    MetricsExporter, MetricsRegistry, histogram_quantile, merge_snapshots,
    percentile, snapshot_to_prometheus, write_snapshot)
from apex_tpu.monitor.flight import FlightRecorder, thread_stacks  # noqa: F401
from apex_tpu.monitor.goodput import EVENT_SCHEMA, GoodputLedger  # noqa: F401
from apex_tpu.monitor.slo import SLObjective, SLOTracker  # noqa: F401
from apex_tpu.monitor.memory import (  # noqa: F401
    MemoryAccountant, device_memory_stats, publish_compiled_memory,
    sample_device_memory)
from apex_tpu.monitor.metrics import (  # noqa: F401
    TrainMetrics, collect_metrics, step_flops, tree_l2norm)
from apex_tpu.monitor.telemetry import (  # noqa: F401
    PERF_ROW_KEYS, Telemetry, read_jsonl, validate_row)
from apex_tpu.monitor.export import FleetMetricsExporter  # noqa: F401
from apex_tpu.monitor.trace import (  # noqa: F401
    ChromeTraceWriter, Span, TailCaptureRouter, Tracer, TraceSampler,
    get_tracer, read_chrome_trace, set_tracer, spans_by_trace)

__all__ = [
    "GoodputLedger", "EVENT_SCHEMA", "TrainMetrics", "collect_metrics",
    "step_flops", "tree_l2norm", "PERF_ROW_KEYS", "Telemetry", "read_jsonl",
    "validate_row", "Tracer", "Span", "ChromeTraceWriter",
    "TraceSampler", "TailCaptureRouter", "get_tracer",
    "set_tracer", "read_chrome_trace", "spans_by_trace", "FlightRecorder",
    "thread_stacks", "MemoryAccountant", "device_memory_stats",
    "publish_compiled_memory", "sample_device_memory",
    "MetricsRegistry", "MetricsExporter", "FleetMetricsExporter",
    "percentile", "histogram_quantile", "merge_snapshots",
    "snapshot_to_prometheus", "write_snapshot", "SLObjective",
    "SLOTracker",
]
