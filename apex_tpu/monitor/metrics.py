"""In-graph training metrics — jit-safe collection with zero extra host syncs.

The reference reports training health from host-side AverageMeters fed by
``.item()`` calls in the loop (examples/imagenet/main_amp.py) — every metric
is a blocking device round-trip. Here the metrics are a :class:`TrainMetrics`
pytree computed INSIDE the jitted step function: the norms fuse into the
step's existing HBM passes, the result rides out of the jit as device
scalars, and the host never syncs for them — ``Telemetry``/``MetricLogger``
batch-fetch the whole buffer at flush time.

Fields not collected are ``None`` (an empty pytree node, so a partially
filled :class:`TrainMetrics` is still a valid jit carry/return) and are
simply absent from the emitted JSONL row.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor.functional import (multi_tensor_l2norm,
                                              tree_check_finite)


class TrainMetrics(NamedTuple):
    """Per-step training-health scalars (f32/bool device scalars or None).

    ``found_inf`` doubles as the overflow flag the loop already fetches to
    count skips, so collecting the rest adds no host traffic.
    """

    loss: Any = None
    grad_norm: Any = None
    param_norm: Any = None
    update_norm: Any = None
    found_inf: Any = None
    loss_scale: Any = None

    def to_dict(self) -> Dict[str, Any]:
        """Collected fields only (values stay device arrays — no sync)."""
        return {k: v for k, v in self._asdict().items() if v is not None}


def tree_l2norm(tree: Any) -> jax.Array:
    """Global L2 norm of a pytree (fp32 accumulation, jit-safe)."""
    return multi_tensor_l2norm(tree)[0]


def collect_metrics(grads: Any = None, params: Any = None,
                    updates: Any = None, scaler_state: Any = None, *,
                    loss: Any = None, grad_norm: Any = None,
                    found_inf: Any = None,
                    loss_scale: Optional[float] = None) -> TrainMetrics:
    """Build a :class:`TrainMetrics` from whatever the step has in hand.

    Call inside the jitted step function. Everything is pure jnp — no
    callbacks, no host syncs; tracing this under ``jit`` adds only fused
    reductions over trees the step already touches.

    - ``grads``/``params``/``updates``: pytrees to norm (any of them may be
      omitted). Pass precomputed ``grad_norm`` instead of ``grads`` when the
      unscale pass already produced it
      (:meth:`~apex_tpu.amp.grad_scaler.DynamicGradScaler.unscale_and_norm`).
    - ``scaler_state``: an ``amp.ScalerState`` — contributes ``loss_scale``;
      for unscaled (bf16-first) runs pass ``loss_scale=1.0`` explicitly so
      the emitted schema stays stable across amp on/off.
    - ``found_inf``: explicit overflow flag; derived from ``grads`` (or a
      non-finite ``grad_norm``) when omitted.
    """
    if grad_norm is None and grads is not None:
        grad_norm = tree_l2norm(grads)
    if found_inf is None:
        if grads is not None:
            found_inf = tree_check_finite(grads)
        elif grad_norm is not None:
            found_inf = ~jnp.isfinite(jnp.asarray(grad_norm, jnp.float32))
    scale = None
    if scaler_state is not None:
        scale = jnp.asarray(scaler_state.scale, jnp.float32)
    elif loss_scale is not None:
        scale = jnp.asarray(loss_scale, jnp.float32)
    return TrainMetrics(
        loss=None if loss is None else jnp.asarray(loss, jnp.float32),
        grad_norm=grad_norm,
        param_norm=None if params is None else tree_l2norm(params),
        update_norm=None if updates is None else tree_l2norm(updates),
        found_inf=found_inf,
        loss_scale=scale)


def compile_for_analysis(fn, *args):
    """Lower + compile ``fn(*args)`` for cost/memory analysis (an
    already-jitted ``fn``'s lowering is reused; plain callables are
    jitted for analysis only). Returns ``None`` when compilation fails —
    analysis consumers degrade, they don't raise."""
    lower = fn.lower if hasattr(fn, "lower") else jax.jit(fn).lower
    try:
        return lower(*args).compile()
    except Exception:
        return None


def step_flops(fn, *args, compiled=None) -> float:
    """XLA cost-model FLOPs for one call of ``fn(*args)`` — the MFU
    numerator. Pass ``compiled`` (from :func:`compile_for_analysis`) to
    reuse an executable a caller already has — ``Telemetry.calibrate``
    derives FLOPs AND the static memory analysis from one compile.
    Returns 0.0 when the backend reports no cost analysis
    (interpret-mode CPU paths)."""
    from apex_tpu.monitor import costs

    if compiled is None:
        compiled = compile_for_analysis(fn, *args)
    # ONE spelling of the cost_analysis() extraction dance, shared with
    # the ledger and utils/prof.py (monitor/costs.py owns it)
    return costs.xla_flops(compiled)
