"""Unified telemetry sink — metrics rows, event mirror, trace spans.

One object merges the pieces the seed already carried in fragments:

- per-step metric rows ride :class:`~apex_tpu.utils.logging.MetricLogger`
  (device arrays buffered, ONE batched host sync at flush) to JSONL and/or
  console;
- every ``structured_warning``/``publish_event`` record in the process —
  checkpoint retries, overflow storms, preemption — is mirrored into the
  same JSONL via the event bus, so the run log is one stream;
- :meth:`Telemetry.span` opens a named trace range (``prof.annotate``, the
  NVTX analog, visible in the device trace) AND emits a wall-clock span
  event, so host-side phases line up with the profiler timeline;
- per-step ``step_ms`` / ``tokens_per_s`` / ``mfu`` are derived host-side
  from loop wall clock and the XLA cost model
  (:func:`~apex_tpu.monitor.metrics.step_flops`,
  ``prof.CHIP_PEAKS``/``detect_chip``) — nothing extra crosses the
  host-device boundary.

Multihost: by default only process 0 writes (``rank_zero_only=True``);
other ranks keep timing/goodput accounting but emit nothing.

Row schema (metric rows; ``None``-valued fields are simply absent):
``{step, t, loss, grad_norm, param_norm, update_norm, found_inf,
loss_scale, step_ms, tokens_per_s, mfu, ...extras}``. Event rows carry an
``"event"`` key instead of ``"step"``. See docs/observability.md.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.monitor.metrics import TrainMetrics, step_flops
from apex_tpu.utils.logging import (MetricLogger, publish_event,
                                    subscribe_events)
from apex_tpu.utils.prof import CHIP_PEAKS, annotate, detect_chip

# the keys every instrumented train loop's rows must carry (the bench
# regression gate and the schema smoke test validate against this)
PERF_ROW_KEYS = ("step", "loss", "grad_norm", "loss_scale", "step_ms",
                 "tokens_per_s", "mfu")


def validate_row(row: Dict[str, Any],
                 require: Iterable[str] = PERF_ROW_KEYS) -> Dict[str, Any]:
    """Validate one metric row against the telemetry schema.

    Raises ``ValueError`` naming the offending key; returns the row so the
    call composes. Event rows (``"event"`` key) are rejected — filter them
    out first (:func:`read_jsonl` does).
    """
    if not isinstance(row, dict):
        raise ValueError(f"telemetry row is {type(row).__name__}, not dict")
    if "event" in row:
        raise ValueError(f"event row passed as metric row: {row!r}")
    for key in require:
        if key not in row:
            raise ValueError(f"telemetry row missing {key!r}: {row!r}")
    for key, val in row.items():
        if not isinstance(val, (int, float, bool, str, type(None))):
            raise ValueError(
                f"telemetry row field {key!r} is non-scalar "
                f"{type(val).__name__} (device arrays must be flushed)")
    if not isinstance(row.get("step"), int):
        raise ValueError(f"telemetry row 'step' not an int: {row!r}")
    return row


def read_jsonl(path: str) -> Tuple[List[Dict[str, Any]],
                                   List[Dict[str, Any]]]:
    """Parse a telemetry JSONL file into ``(metric_rows, event_rows)``."""
    metrics: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            (events if "event" in rec else metrics).append(rec)
    return metrics, events


class Telemetry:
    """The one observability object a training loop needs.

    Typical wiring (see bench_cli._telemetry_bench for the full pattern)::

        tel = Telemetry("run.jsonl", tokens_per_step=B * S).calibrate(
            step, state, batch)                  # MFU from the cost model
        for i in range(steps):
            state, tm = step(i, state, batch)    # ONE jitted call
            skipped = bool(tm.found_inf)         # the loop's one host sync
            tel.log_step(i, metrics=tm, skipped=skipped)
        tel.close()
        print(tel.summary())

    ``log_step`` never syncs: metric values stay device arrays until the
    batched flush. ``step_ms`` is wall clock between successive
    ``log_step`` calls (honest as long as the loop consumes something
    data-dependent per step — the ``found_inf`` fetch above).
    """

    def __init__(self, jsonl_path: Optional[str] = None, *,
                 console_every: int = 0, stream=None,
                 tokens_per_step: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 chip: Optional[str] = None,
                 rank_zero_only: bool = True,
                 goodput: bool = True,
                 mirror_events: bool = True,
                 flush_every: int = 50,
                 trace_jsonl: Optional[str] = None,
                 registry=None):
        if rank_zero_only:
            import jax

            self.enabled = jax.process_index() == 0
        else:
            self.enabled = True
        self.jsonl_path = jsonl_path if self.enabled else None
        # span-tree tracing (monitor.trace): trace_jsonl enables the
        # process tracer for this run and streams completed spans as a
        # Perfetto/Chrome-trace JSON; close() restores the previous tracer
        self.tracer = None
        self._trace_writer = None
        self._prev_tracer = None
        if trace_jsonl and self.enabled:
            from apex_tpu.monitor.trace import (ChromeTraceWriter, Tracer,
                                                set_tracer)

            self.tracer = Tracer(enabled=True)
            self._prev_tracer = set_tracer(self.tracer)
            self._trace_writer = ChromeTraceWriter(trace_jsonl)
        if self.jsonl_path:
            # per-RUN sink: truncate any previous capture — mixed-run rows
            # would silently skew check_regression's medians
            open(self.jsonl_path, "w").close()
        self.flush_every = flush_every
        self._rows_since_flush = 0
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        self.chip = chip
        self._peak = None
        self._last_t: Optional[float] = None
        self.logger = MetricLogger(self.jsonl_path,
                                   print_every=console_every, stream=stream)
        self.ledger: Optional[GoodputLedger] = (
            GoodputLedger().attach() if goodput else None)
        # live-metrics registry (monitor.export): the training-side seam
        # of the serving SLO layer — step-time lands in a mergeable
        # histogram so per-rank training snapshots aggregate exactly like
        # serving ranks do (tools/metrics_merge.py); all ranks record
        # (fleet view sums), only rank 0 writes files
        self.registry = registry
        if registry is not None:
            self._m_steps = registry.counter(
                "train_steps_total", "train steps recorded")
            self._m_skipped = registry.counter(
                "train_skipped_steps_total",
                "steps lost to overflow skips")
            self._m_step_hist = registry.histogram(
                "train_step_seconds", "wall clock per train step")
        self._unsubscribe = None
        if mirror_events and self.jsonl_path:
            self._unsubscribe = subscribe_events(self._on_event)

    # ---- cost model -----------------------------------------------------
    def calibrate(self, fn, *args,
                  tokens_per_step: Optional[float] = None) -> "Telemetry":
        """Set ``flops_per_step`` from the XLA cost model of ``fn(*args)``
        (the compiled step function — already-jitted callables reuse their
        lowering). Inherits roofline's operand-byte caveats; see
        docs/observability.md. Also captures the step's STATIC memory
        reservation (``compiled.memory_analysis()``) as an
        ``hbm_snapshot`` event — the bench's AOT point for the memory
        accounting layer (monitor.memory)."""
        from apex_tpu.monitor.metrics import compile_for_analysis

        # ONE lower+compile serves both the cost model and the memory
        # analysis (step_flops without it would compile a second copy)
        compiled = compile_for_analysis(fn, *args)
        self.flops_per_step = step_flops(fn, *args, compiled=compiled)
        if compiled is not None:
            from apex_tpu.monitor.memory import publish_compiled_memory

            publish_compiled_memory("calibrated_step", compiled)
        if tokens_per_step is not None:
            self.tokens_per_step = tokens_per_step
        return self

    def _peak_flops(self) -> float:
        if self._peak is None:
            gen = (self.chip or detect_chip()
                   or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"))
            peaks = CHIP_PEAKS.get(gen, CHIP_PEAKS["v5e"])
            self._peak = peaks["tflops"] * 1e12
        return self._peak

    # ---- per-step path --------------------------------------------------
    def start(self) -> "Telemetry":
        """Open the timing window for the first step (otherwise the first
        ``log_step`` row has no ``step_ms``)."""
        self._last_t = time.perf_counter()
        return self

    def log_step(self, step: int, metrics: Optional[TrainMetrics] = None, *,
                 loss: Any = None, tokens: Optional[float] = None,
                 step_ms: Optional[float] = None, skipped: bool = False,
                 **extra: Any) -> None:
        """Record one step. Device arrays in ``metrics``/``loss``/``extra``
        are buffered as-is (no sync) and batch-fetched at flush."""
        now = time.perf_counter()
        if step_ms is None and self._last_t is not None:
            step_ms = (now - self._last_t) * 1e3
        self._last_t = now

        fields: Dict[str, Any] = metrics.to_dict() if metrics is not None \
            else {}
        if loss is not None:
            fields["loss"] = loss
        fields.update(extra)
        if step_ms is not None:
            fields["step_ms"] = round(step_ms, 3)
            step_s = step_ms / 1e3
            n_tokens = tokens if tokens is not None else self.tokens_per_step
            if n_tokens is not None and step_s > 0:
                fields["tokens_per_s"] = round(n_tokens / step_s, 1)
            if self.flops_per_step is not None and step_s > 0:
                fields["mfu"] = round(
                    self.flops_per_step / step_s / self._peak_flops(), 6)
        if self.ledger is not None:
            # no timing window yet (first row before start()): count the
            # step/skip with zero seconds rather than dropping it
            self.ledger.record_step(step_ms / 1e3 if step_ms else 0.0,
                                    productive=not skipped)
        if self.registry is not None:
            self._m_steps.inc()
            if skipped:
                self._m_skipped.inc()
            if step_ms is not None:
                self._m_step_hist.record(step_ms / 1e3)
        if self.enabled:
            self.logger.log(step, **fields)
            self._rows_since_flush += 1
            # bound the buffer (and the JSONL's staleness): a crash must
            # not take a long run's whole metric history with it
            if self.flush_every and \
                    self._rows_since_flush >= self.flush_every:
                self.flush()

    # ---- spans + events -------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str):
        """Named range: a device-trace annotation (shows in the profiler
        timeline) plus a wall-clock span event on the bus (mirrored into
        the JSONL)."""
        t0 = time.perf_counter()
        with annotate(name):
            yield
        publish_event("span", name=name,
                      ms=round((time.perf_counter() - t0) * 1e3, 3))

    def event(self, name: str, **fields: Any) -> Dict[str, Any]:
        """Publish a structured info event on the process bus (lands in
        this sink's JSONL via the mirror, and in any attached ledger)."""
        return publish_event(name, emit=False, **fields)

    def _on_event(self, rec: Dict[str, Any]) -> None:
        # the mirror: every bus record becomes one JSONL line alongside the
        # metric rows (append-per-event; events are low-rate by design).
        # span_open/span_close are the exception — they are per-span and
        # belong in the dedicated Chrome-trace file, not the metric log
        if rec.get("event") in ("span_open", "span_close"):
            return
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True, default=float) + "\n")

    # ---- lifecycle ------------------------------------------------------
    def flush(self) -> None:
        self.logger.flush()
        self._rows_since_flush = 0

    def summary(self) -> Dict[str, Any]:
        """Flush, then return running means plus the goodput ledger."""
        out: Dict[str, Any] = {"metrics": self.logger.summary()}
        if self.ledger is not None:
            out["goodput"] = self.ledger.summary()
        return out

    def close(self) -> None:
        self.flush()
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None
        if self.ledger is not None:
            self.ledger.detach()
        if self._trace_writer is not None:
            self._trace_writer.close()
            self._trace_writer = None
        if self._prev_tracer is not None:
            from apex_tpu.monitor.trace import set_tracer

            set_tracer(self._prev_tracer)
            self._prev_tracer = None

    def __enter__(self) -> "Telemetry":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
