"""Crash-time flight recorder — a bounded postmortem of the last moments.

A stalled collective, a preemption, or a fatal scheduler exception
usually leaves nothing but a truncated log tail: the events that
*explain* the death scrolled away long before it. The flight recorder
keeps them:

- a **bounded ring** (``capacity`` records) of every event on the
  process bus — serve lifecycle, checkpoint stalls, overflow skips,
  ``span_open``/``span_close``, ``hbm_snapshot`` — oldest dropped first,
  so an event storm can never grow it (tier-1 proves the bound under a
  FaultInjector overflow storm);
- the tracer's **open spans** (what was in flight when it died);
- the latest **hbm_snapshot** (was it an OOM death?);
- an **all-thread Python stack dump** (where was every thread stuck?).

``dump()`` writes one JSON artifact with the same ``.tmp`` +
``os.replace`` atomicity as every other on-disk artifact in the repo
(``apex-tpu-lint`` rule APX004 lints it): a dump torn by the very crash
it documents would be worse than none. Auto-dump triggers, zero wiring
beyond ``attach()`` — the trigger records already ride the bus:

- ``preemption_requested`` (:class:`~apex_tpu.resilience.preemption.
  PreemptionGuard` signal/agreement),
- ``collective_stall`` with ``escalate`` dump/abort and
  ``collective_stall_abort`` (:class:`~apex_tpu.resilience.distributed.
  CollectiveWatchdog` escalation).

Fatal exceptions have no bus record — wrap the region in
:meth:`FlightRecorder.guard` (the serve scheduler's ``run()`` does when
given a recorder). See docs/observability.md "Tracing and postmortems".
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from apex_tpu.utils.logging import publish_event, subscribe_events

SCHEMA_VERSION = 1


def thread_stacks() -> Dict[str, List[str]]:
    """Every thread's Python stack as ``{"tid:name": [frames...]}`` —
    pure ``sys._current_frames`` so it works where faulthandler can't
    (captured/replaced stderr). Shared with the collective watchdog's
    stderr dump."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    try:
        frames = sys._current_frames()
    except Exception:
        return out
    for tid, frame in frames.items():
        label = f"{tid}:{names.get(tid, '?')}"
        out[label] = [line.rstrip("\n")
                      for line in traceback.format_stack(frame)]
    return out


def _is_trigger(rec: Dict[str, Any]) -> bool:
    """The bus records that mean "the run is dying — dump now"."""
    ev = rec.get("event")
    if ev in ("preemption_requested", "collective_stall_abort"):
        return True
    if ev == "collective_stall" and rec.get("escalate") in ("dump", "abort"):
        return True
    # serving fleet (PR 13): a replica declared dead — or escalated to
    # suspect, the watchdog-style early warning — gets its postmortem
    # captured the moment the registry sweep announces it (the
    # per-replica recorder's trigger_filter scopes each dump to ITS
    # replica's transitions)
    if ev in ("serve_replica_dead", "serve_replica_suspect"):
        return True
    return False


class FlightRecorder:
    """Ring-buffer bus subscriber with an atomic postmortem dump.

    Usage::

        fr = FlightRecorder("run_flight.json", tracer=tracer).attach()
        try:
            serve_or_train()
        finally:
            fr.detach()
        # a preemption / watchdog escalation mid-run left run_flight.json

    ``tracer`` defaults to the process tracer
    (:func:`~apex_tpu.monitor.trace.get_tracer`) at dump time, so open
    spans appear whenever tracing is enabled. Repeat triggers overwrite
    the dump atomically — the file always holds the LATEST complete
    postmortem.
    """

    def __init__(self, path: str, *, capacity: int = 256, tracer=None,
                 auto_dump: bool = True, trigger_filter=None,
                 context_fn=None):
        self.path = path
        self.capacity = max(1, int(capacity))
        self.tracer = tracer
        self.auto_dump = auto_dump
        # trigger_filter(rec) -> bool: an extra predicate over the
        # trigger records — a fleet's per-replica recorder dumps only on
        # ITS replica's death/suspect transition, not every peer's
        self.trigger_filter = trigger_filter
        # context_fn() -> dict, captured at dump time under "context":
        # the fleet wires the replica's registry row (state, last beat,
        # silence age) so a death postmortem says WHICH row died and how
        self.context_fn = context_fn
        self.events: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.total_events = 0
        self.dumps = 0
        self.last_hbm: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        # dump() can run concurrently: an auto-dump fires on whatever
        # thread published the trigger (watchdog heartbeat) while a
        # guard() dump runs on the crashing thread — both target the same
        # ``.tmp`` staging path, and interleaved writes would tear the
        # "atomic" artifact. A dedicated lock (not ``_lock``: snapshot()
        # holds that, and ring appends must not stall behind file I/O)
        # serializes whole dumps; the last writer leaves a complete file.
        self._dump_lock = threading.Lock()
        self._unsubscribe = None

    # ---- bus wiring ----------------------------------------------------
    def attach(self) -> "FlightRecorder":
        if self._unsubscribe is None:
            self._unsubscribe = subscribe_events(self._on_event)
        return self

    def detach(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def __enter__(self) -> "FlightRecorder":
        return self.attach()

    def __exit__(self, *exc) -> None:
        self.detach()

    def _on_event(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self.total_events += 1
            self.events.append(rec)
            if rec.get("event") == "hbm_snapshot":
                self.last_hbm = rec
        if self.auto_dump and _is_trigger(rec) and (
                self.trigger_filter is None or self.trigger_filter(rec)):
            self.dump(reason=str(rec.get("event")))

    # ---- the postmortem ------------------------------------------------
    def snapshot(self, reason: str) -> Dict[str, Any]:
        """The dump payload (pure data; tests assert this schema)."""
        from apex_tpu.monitor.trace import get_tracer

        tracer = self.tracer if self.tracer is not None else get_tracer()
        with self._lock:
            events = list(self.events)
            total = self.total_events
            last_hbm = self.last_hbm
        out = {
            "schema": SCHEMA_VERSION,
            "reason": reason,
            "t": time.time(),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "total_events": total,
            "dropped_events": max(0, total - len(events)),
            "events": events,
            "open_spans": tracer.open_spans(),
            "hbm_snapshot": last_hbm,
            "thread_stacks": thread_stacks(),
        }
        if self.context_fn is not None:
            try:
                out["context"] = self.context_fn()
            except Exception as e:
                # the postmortem must never die on its own garnish
                out["context"] = {"error": repr(e)}
        return out

    def dump(self, reason: str = "manual") -> str:
        """Write the postmortem atomically (stage to ``.tmp``, publish
        with one ``os.replace`` — a crash mid-dump leaves the previous
        complete dump, never a torn one). Returns the path."""
        with self._dump_lock:
            # snapshot INSIDE the lock: were it taken before, a stale
            # snapshot could win the write race and the surviving
            # postmortem would miss the very events (the fatal exception)
            # that triggered the later dump. snapshot() only holds _lock
            # for the in-memory copy, so ring appends still never stall
            # behind this file write.
            payload = self.snapshot(reason)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True,
                          default=str)
            os.replace(tmp, self.path)
            self.dumps += 1
        publish_event("flight_recorder_dump", emit=False, path=self.path,
                      reason=reason, events=len(payload["events"]),
                      open_spans=len(payload["open_spans"]))
        return self.path

    @contextlib.contextmanager
    def guard(self, what: str = "run"):
        """Dump on any escaping exception (fatal engine/scheduler error —
        the one death with no bus record to trigger on), then re-raise."""
        try:
            yield self
        except BaseException as e:
            try:
                self.dump(reason=f"exception:{type(e).__name__}:{what}")
            except Exception:
                pass  # the postmortem must never mask the real error
            raise
