"""Live-metrics registry — streaming counters/gauges/histograms with
exact cross-instance merge, Prometheus/JSON export, and a pull endpoint.

The serving scheduler's end-of-run ``summary()`` sorts full in-memory
latency lists — exact, but post-hoc and unbounded. This module is the
*live* layer: a :class:`MetricsRegistry` of

- :class:`Counter` — monotonic totals (``serve_requests_completed_total``),
- :class:`Gauge` — point-in-time levels (``serve_resident_tokens``) with a
  declared merge aggregation (sum/max/min/last),
- :class:`Histogram` — **log-bucketed mergeable** distributions: fixed
  bucket boundaries ``HIST_LO * HIST_GROWTH**i`` shared by every instance,
  O(1) record, bounded memory (at most :data:`HIST_MAX_INDEX` sparse
  buckets), and **exact merge**: because the boundaries are fixed and
  global, summing two histograms' bucket counts is bit-identical to having
  recorded the union stream into one histogram — the aggregation seam
  per-rank/per-run snapshots (and the coming tensor-parallel serving
  ranks) merge through.

**Quantile error bound.** :meth:`Histogram.quantile` returns the upper
edge of the bucket holding the exact nearest-rank percentile (the same
rank rule as :func:`percentile`, the repo's one exact-percentile helper).
For an exact value ``q`` in ``[HIST_LO, HIST_LO * HIST_GROWTH**HIST_MAX_INDEX]``
the estimate ``e`` satisfies ``q <= e < q * HIST_GROWTH`` — a relative
overestimate below :data:`QUANTILE_REL_ERROR` (≈ 9.1% at the default
``2**(1/8)`` growth). Below ``HIST_LO`` the estimate is ``HIST_LO``
(absolute error ≤ 1µs for second-valued series). Tier-1 holds the
scheduler's exact sorted-list percentiles against this bound.

**Label cardinality is bounded.** A family created with labels folds
series past ``max_series`` into the ``__other__`` catch-all, so a tenant
explosion can never make the registry (or a scrape) unbounded.

**Export surfaces** — all host-side, never on a traced path (apexlint
APX001 flags a registry mutation reachable from traced code):

- :meth:`MetricsRegistry.snapshot` — the JSON document
  (``schema: "apex_tpu.metrics/v1"``) that :func:`merge_snapshots` folds
  across instances/ranks/runs and ``tools/metrics_merge.py`` exposes as a
  CLI; :func:`write_snapshot` commits it atomically (``.tmp`` +
  ``os.replace``, the APX004 durability contract).
- :func:`snapshot_to_prometheus` — text exposition (format 0.0.4) of a
  snapshot; :meth:`MetricsRegistry.prometheus_text` is the live spelling.
- :class:`MetricsExporter` — a stdlib ``http.server`` pull endpoint
  (``/metrics`` Prometheus text, ``/metrics.json`` JSON snapshot) on a
  daemon thread; every scrape publishes a ``metrics_scrape`` bus event.

This module is deliberately **stdlib-only at import time** (the bus
import is call-site deferred) so ``tools/metrics_merge.py`` can load it
standalone — merging rank snapshots on a machine with no jax installed.
See docs/observability.md "Live metrics, SLOs, and fleet aggregation".
"""

from __future__ import annotations

import json
import math
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

SNAPSHOT_SCHEMA = "apex_tpu.metrics/v1"

# fixed, global histogram geometry: every histogram everywhere buckets by
# upper_bound(i) = HIST_LO * HIST_GROWTH**i — merge is exact only because
# no instance can choose different boundaries
HIST_LO = 1e-6                 # bucket 0 holds everything <= 1µs (seconds)
HIST_GROWTH = 2.0 ** 0.125     # 8 buckets per doubling
HIST_MAX_INDEX = 384           # upper bound ≈ 2.8e8 s — the overflow bucket
# documented relative quantile error (overestimate) inside the bucketed
# range: the estimate is the bucket's upper edge, the exact value is past
# the previous edge, and the two differ by one growth factor
QUANTILE_REL_ERROR = HIST_GROWTH - 1.0

OVERFLOW_LABEL = "__other__"   # where series past max_series fold

GAUGE_AGGS = ("sum", "max", "min", "last")


def percentile(values: Iterable[float], p: float) -> float:
    """THE repo's exact nearest-rank percentile: the value at 1-based rank
    ``ceil(p * n)`` of the sorted values (``p=0`` → the minimum; empty →
    0.0). Shared by the scheduler's exact end-of-run summary and the
    histogram-quantile tests so the two can never round differently —
    the bug this replaced: ``summary()`` used ``len//2`` indexing for
    TTFT but round-half-even linear indexing for step percentiles."""
    vals = sorted(values)
    if not vals:
        return 0.0
    rank = max(1, math.ceil(p * len(vals)))
    return vals[min(rank, len(vals)) - 1]


def bucket_index(value: float) -> int:
    """The fixed-boundary bucket for ``value``: 0 for anything at or
    below ``HIST_LO`` (NaN included — a poisoned sample must not crash
    accounting), the overflow bucket for anything past the range."""
    v = float(value)
    if not v > HIST_LO:          # also catches NaN
        return 0
    if math.isinf(v):
        return HIST_MAX_INDEX
    idx = math.ceil(math.log(v / HIST_LO) / math.log(HIST_GROWTH))
    return max(0, min(HIST_MAX_INDEX, idx))


def bucket_upper(idx: int) -> float:
    """Upper edge of bucket ``idx`` (the quantile estimate for any value
    that landed in it)."""
    return HIST_LO * HIST_GROWTH ** idx


def histogram_quantile(buckets: Mapping[Any, int], count: int,
                       p: float, *, lo: float = HIST_LO,
                       growth: float = HIST_GROWTH) -> float:
    """Nearest-rank quantile over a (possibly merged) bucket-count map —
    the same rank rule as :func:`percentile`, so the streaming estimate
    and the exact oracle walk to the same sample's bucket. ``lo`` /
    ``growth`` default to the global geometry; a caller reading a
    serialized snapshot passes the SNAPSHOT'S own values (the one
    quantile rule — ``tools/check_regression.py`` loads this module by
    path rather than growing a second copy)."""
    if count <= 0:
        return 0.0
    rank = max(1, math.ceil(p * count))
    cum = 0
    upper = 0.0
    for idx in sorted(int(k) for k in buckets):
        cum += int(buckets[idx] if idx in buckets else buckets[str(idx)])
        upper = lo * growth ** idx
        if cum >= rank:
            return upper
    return upper


# --------------------------------------------------------------- metrics

class Counter:
    """Monotonic total. ``inc()`` is O(1) host work under the registry
    lock; merge across snapshots is addition."""

    kind = "counter"

    def __init__(self, lock: threading.Lock, labels: Dict[str, str]):
        self._lock = lock
        self.labels = labels
        self._value = 0.0

    def inc(self, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError(f"counter increment must be >= 0: {value}")
        with self._lock:
            self._value += float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def state(self) -> Dict[str, Any]:
        # caller holds self._lock (registry snapshot)
        return {"labels": dict(self.labels), "value": self._value}


class Gauge:
    """Point-in-time level. The family's ``agg`` declares how instances
    merge across a fleet (sum resident tokens, min free-page fraction)."""

    kind = "gauge"

    def __init__(self, lock: threading.Lock, labels: Dict[str, str]):
        self._lock = lock
        self.labels = labels
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def state(self) -> Dict[str, Any]:
        # caller holds self._lock (registry snapshot)
        return {"labels": dict(self.labels), "value": self._value}


class Histogram:
    """Log-bucketed streaming distribution: O(1) :meth:`record`, bounded
    sparse bucket map, exact merge (fixed global boundaries)."""

    kind = "histogram"

    def __init__(self, lock: threading.Lock, labels: Dict[str, str]):
        self._lock = lock
        self.labels = labels
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._buckets: Dict[int, int] = {}

    def record(self, value: float) -> None:
        v = float(value)
        idx = bucket_index(v)
        # a poisoned sample (NaN/inf) is COUNTED (bucket 0 / overflow)
        # but must not contaminate sum/min/max: one NaN would make the
        # sum NaN forever, and NaN/Infinity are not valid JSON — a
        # single bad sample would break every later /metrics.json scrape
        finite = math.isfinite(v)
        with self._lock:
            self._count += 1
            if finite:
                self._sum += v
                if self._min is None or v < self._min:
                    self._min = v
                if self._max is None or v > self._max:
                    self._max = v
            self._buckets[idx] = self._buckets.get(idx, 0) + 1

    # prometheus spelling, same O(1) path
    observe = record

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, p: float) -> float:
        """Streaming nearest-rank quantile: exact value ``q`` →
        estimate in ``[q, q * HIST_GROWTH)`` (see module docstring)."""
        with self._lock:
            buckets = dict(self._buckets)
            count = self._count
        return histogram_quantile(buckets, count, p)

    def state(self) -> Dict[str, Any]:
        # caller holds self._lock (registry snapshot)
        return {"labels": dict(self.labels), "count": self._count,
                "sum": self._sum, "min": self._min, "max": self._max,
                "buckets": {str(i): n
                            for i, n in sorted(self._buckets.items())}}


_KIND_CLS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: its label names, bounded series map, and
    convenience delegates for the unlabeled case."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help: str, label_names: Tuple[str, ...], max_series: int,
                 agg: str):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = label_names
        self.max_series = max_series
        self.agg = agg
        self._series: Dict[Tuple[str, ...], Any] = {}

    def labels(self, **labels: str):
        """The series for this label set — created on first use; once the
        family holds ``max_series`` series, NEW label sets fold into the
        ``__other__`` series so cardinality (and scrape size) stays
        bounded whatever the tenant population does."""
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(labels[n]) for n in self.label_names)
        lock = self.registry._lock
        with lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    key = tuple(OVERFLOW_LABEL for _ in self.label_names)
                    series = self._series.get(key)
                if series is None:
                    series = _KIND_CLS[self.kind](
                        lock, dict(zip(self.label_names, key)))
                    self._series[key] = series
            return series

    # unlabeled ergonomics: family.inc()/record()/set() hit the () series
    def inc(self, value: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(value)

    def set(self, value: float, **labels) -> None:
        self.labels(**labels).set(value)

    def record(self, value: float, **labels) -> None:
        self.labels(**labels).record(value)

    def series(self) -> List[Any]:
        with self.registry._lock:
            return list(self._series.values())

    def state(self) -> Dict[str, Any]:
        # caller holds self._lock (registry snapshot)
        out: Dict[str, Any] = {"type": self.kind, "help": self.help,
                               "labels": list(self.label_names),
                               "series": [s.state()
                                          for s in self._series.values()]}
        if self.kind == "gauge":
            out["agg"] = self.agg
        if self.kind == "histogram":
            out["lo"] = HIST_LO
            out["growth"] = HIST_GROWTH
        return out


class MetricsRegistry:
    """Named counter/gauge/histogram families behind ONE process-local
    lock (every record is a handful of host float ops — contention is
    irrelevant next to a decode step, and one lock keeps the APX002
    discipline trivial). Family getters are idempotent: asking again
    with the same name returns the existing family; a kind mismatch is a
    loud ValueError, never silent aliasing."""

    def __init__(self, *, default_max_series: int = 64):
        self.default_max_series = int(default_max_series)
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help: str,
                labels: Sequence[str], max_series: Optional[int],
                agg: str = "sum") -> _Family:
        if agg not in GAUGE_AGGS:
            raise ValueError(f"gauge agg {agg!r} not in {GAUGE_AGGS}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}")
                return fam
            fam = _Family(self, name, kind, help, tuple(labels),
                          int(max_series or self.default_max_series), agg)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = (),
                max_series: Optional[int] = None) -> _Family:
        return self._family(name, "counter", help, labels, max_series)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = (),
              max_series: Optional[int] = None,
              agg: str = "sum") -> _Family:
        return self._family(name, "gauge", help, labels, max_series, agg)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  max_series: Optional[int] = None) -> _Family:
        return self._family(name, "histogram", help, labels, max_series)

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    # ---- export ---------------------------------------------------------
    def snapshot(self, meta: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        """The mergeable JSON document: plain data, no object refs —
        ``merge_snapshots`` folds any number of these into one."""
        with self._lock:
            metrics = {name: fam.state()
                       for name, fam in sorted(self._families.items())}
        doc: Dict[str, Any] = {"schema": SNAPSHOT_SCHEMA,
                               "metrics": metrics}
        if meta:
            doc["meta"] = dict(meta)
        return doc

    def prometheus_text(self) -> str:
        return snapshot_to_prometheus(self.snapshot())


# ------------------------------------------------------- snapshot algebra

def _series_key(labels: Mapping[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def merge_snapshots(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold N snapshot documents into one fleet view: counters add,
    gauges combine by their declared ``agg``, histograms add per-bucket —
    **exactly** equal to having recorded the union stream, because every
    instance shares the fixed global bucket boundaries. Raises
    ``ValueError`` on schema/type/geometry mismatches (merging
    incompatible captures would silently fabricate a fleet view)."""
    if not docs:
        raise ValueError("merge_snapshots needs at least one snapshot")
    for doc in docs:
        if doc.get("schema") != SNAPSHOT_SCHEMA:
            raise ValueError(
                f"not a metrics snapshot (schema="
                f"{doc.get('schema')!r}, want {SNAPSHOT_SCHEMA!r})")
    merged_metrics: Dict[str, Any] = {}
    for doc in docs:
        for name, fam in doc.get("metrics", {}).items():
            out = merged_metrics.get(name)
            if out is None:
                out = {k: v for k, v in fam.items() if k != "series"}
                out["series"] = {}
                merged_metrics[name] = out
            elif out["type"] != fam["type"]:
                raise ValueError(
                    f"metric {name!r}: type mismatch across snapshots "
                    f"({out['type']} vs {fam['type']})")
            elif fam["type"] == "histogram" and (
                    out.get("lo") != fam.get("lo")
                    or out.get("growth") != fam.get("growth")):
                raise ValueError(
                    f"metric {name!r}: histogram geometry mismatch — "
                    f"buckets are only mergeable at identical lo/growth")
            elif fam["type"] == "gauge" and \
                    out.get("agg", "sum") != fam.get("agg", "sum"):
                # the one field where merge SEMANTICS differ per
                # declaration: first-doc-wins would silently fold under
                # the wrong aggregation — refuse like type/geometry
                raise ValueError(
                    f"metric {name!r}: gauge agg mismatch across "
                    f"snapshots ({out.get('agg', 'sum')} vs "
                    f"{fam.get('agg', 'sum')})")
            for series in fam.get("series", []):
                key = _series_key(series.get("labels", {}))
                slot = out["series"].get(key)
                if slot is None:
                    out["series"][key] = json.loads(json.dumps(series))
                elif fam["type"] == "counter":
                    slot["value"] += series["value"]
                elif fam["type"] == "gauge":
                    agg = out.get("agg", "sum")
                    if agg == "sum":
                        slot["value"] += series["value"]
                    elif agg == "max":
                        slot["value"] = max(slot["value"], series["value"])
                    elif agg == "min":
                        slot["value"] = min(slot["value"], series["value"])
                    else:  # "last": later snapshots win, in argument order
                        slot["value"] = series["value"]
                else:  # histogram: the exact merge
                    slot["count"] += series["count"]
                    slot["sum"] += series["sum"]
                    for bound in ("min", "max"):
                        vals = [v for v in (slot.get(bound),
                                            series.get(bound))
                                if v is not None]
                        if vals:
                            slot[bound] = (min(vals) if bound == "min"
                                           else max(vals))
                    buckets = slot["buckets"]
                    for idx, n in series.get("buckets", {}).items():
                        buckets[idx] = buckets.get(idx, 0) + n
    for fam in merged_metrics.values():
        fam["series"] = [fam["series"][k] for k in sorted(fam["series"])]
    # provenance must survive the merge: check_regression's
    # device-mismatch guard reads snapshot meta, and a fleet view that
    # dropped it would let a CPU-smoke rank silently gate real-chip
    # numbers. Keys every input agrees on pass through; conflicting
    # values join with "|" so the guard flags the mix loudly.
    meta: Dict[str, Any] = {"merged_from": len(docs)}
    # "tp"/"tp_sync" ride along for tensor-parallel rank merges: the
    # mesh shape is comparability provenance exactly like device_kind
    # (check_regression refuses cross-mesh gates), and every rank of one
    # mesh agrees on it so it passes through raw ("tp_rank" is per-file
    # identity, deliberately NOT merged)
    for key in ("device_kind", "interpret_mode", "chip", "backend", "git",
                "tp", "tp_sync"):
        vals: List[Any] = []
        for doc in docs:
            m = doc.get("meta")
            if isinstance(m, dict) and key in m and m[key] not in vals:
                vals.append(m[key])
        if len(vals) == 1:
            meta[key] = vals[0]    # raw value: a bool must stay a bool
        elif vals:
            # a mixed fleet (cpu rank merged with a tpu rank) must read
            # as NEITHER side — the joined spelling mismatches any
            # homogeneous baseline, so the gate flags it loudly
            meta[key] = "|".join(sorted(str(v) for v in vals))
    return {"schema": SNAPSHOT_SCHEMA, "metrics": merged_metrics,
            "meta": meta}


def _format_labels(labels: Mapping[str, str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    items = sorted((str(k), str(v)) for k, v in labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    def esc(v: str) -> str:
        return v.replace("\\", r"\\").replace('"', r'\"').replace(
            "\n", r"\n")
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in items) + "}"


def _fmt(v: float) -> str:
    return f"{float(v):.10g}"


def snapshot_to_prometheus(doc: Dict[str, Any]) -> str:
    """Render a snapshot document in the Prometheus text exposition
    format (0.0.4): counters/gauges one sample per series, histograms as
    cumulative ``_bucket{le=...}`` lines over the POPULATED buckets plus
    ``+Inf``/``_sum``/``_count``."""
    lines: List[str] = []
    for name, fam in sorted(doc.get("metrics", {}).items()):
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        # bucket edges come from the SNAPSHOT'S serialized geometry, not
        # this module's constants: a snapshot captured under different
        # lo/growth must render its own ``le`` labels, never ours
        lo = float(fam.get("lo", HIST_LO))
        growth = float(fam.get("growth", HIST_GROWTH))
        for series in fam.get("series", []):
            labels = series.get("labels", {})
            if fam["type"] in ("counter", "gauge"):
                lines.append(f"{name}{_format_labels(labels)} "
                             f"{_fmt(series['value'])}")
                continue
            cum = 0
            for idx in sorted(int(k) for k in series.get("buckets", {})):
                cum += series["buckets"][str(idx)]
                le = _fmt(lo * growth ** idx)
                lines.append(
                    f"{name}_bucket{_format_labels(labels, ('le', le))} "
                    f"{cum}")
            lines.append(
                f"{name}_bucket{_format_labels(labels, ('le', '+Inf'))} "
                f"{series['count']}")
            lines.append(f"{name}_sum{_format_labels(labels)} "
                         f"{_fmt(series['sum'])}")
            lines.append(f"{name}_count{_format_labels(labels)} "
                         f"{series['count']}")
    return "\n".join(lines) + "\n"


def atomic_write_json(path: str, doc: Dict[str, Any]) -> str:
    """Commit a JSON document atomically: stage to ``.tmp``, publish with
    one ``os.replace`` — a crash mid-write leaves the previous complete
    file, never a torn one (the repo-wide APX004 contract)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, default=float)
    os.replace(tmp, path)
    return path


def atomic_write_text(path: str, text: str) -> str:
    """Same ``.tmp`` + ``os.replace`` commit for a text artifact (the
    merged Prometheus rendering ``tools/metrics_merge.py`` can emit)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def write_snapshot(registry: MetricsRegistry, path: str,
                   meta: Optional[Dict[str, Any]] = None) -> str:
    """Atomic snapshot-file mode: one mergeable document per rank/run on
    disk, for ``tools/metrics_merge.py`` to fold into the fleet view."""
    atomic_write_json(path, registry.snapshot(meta=meta))
    # deferred import: this module stays stdlib-importable standalone
    from apex_tpu.utils.logging import publish_event

    publish_event("metrics_snapshot", path=path)
    return path


# ------------------------------------------------------------- exporter

def _make_handler(registry: MetricsRegistry,
                  meta: Optional[Dict[str, Any]]):
    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            if path in ("/", "/metrics"):
                body = registry.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path in ("/metrics.json", "/snapshot", "/snapshot.json"):
                body = json.dumps(registry.snapshot(meta=meta),
                                  sort_keys=True, default=float).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "try /metrics or /metrics.json")
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            # deferred import keeps the module standalone-importable
            from apex_tpu.utils.logging import publish_event

            publish_event("metrics_scrape", path=path, bytes=len(body))

        def log_message(self, format, *args):
            # the default writes one stderr line per scrape — a 10s
            # Prometheus cadence must not spam the serving console
            pass

    return _Handler


class MetricsExporter:
    """Pull endpoint over a registry: ``/metrics`` (Prometheus text) and
    ``/metrics.json`` (the mergeable snapshot) from a stdlib
    ``ThreadingHTTPServer`` on a daemon thread. ``port=0`` binds an
    ephemeral port (read :attr:`port` after :meth:`start`).
    ``snapshot_path=`` additionally commits an atomic snapshot file at
    :meth:`stop` — the per-rank artifact ``tools/metrics_merge.py``
    merges. Scrapes are host-side HTTP work on their own thread: the
    decode loop never sees them (tier-1 scrapes a live serve loop and
    asserts ``decode_traces == 1``)."""

    def __init__(self, registry: MetricsRegistry, *, port: int = 0,
                 host: str = "127.0.0.1",
                 snapshot_path: Optional[str] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.registry = registry
        self.host = host
        self.port = int(port)
        self.snapshot_path = snapshot_path
        self.meta = meta
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        """The request-handler class this exporter serves (overridden by
        :class:`FleetMetricsExporter` to add per-replica routes)."""
        return _make_handler(self.registry, self.meta)

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        self._server = ThreadingHTTPServer(
            (self.host, self.port), self._make_handler())
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="apex-tpu-metrics",
            daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            if self._thread is not None:
                self._thread.join(timeout=2.0)
                self._thread = None
        if self.snapshot_path:
            write_snapshot(self.registry, self.snapshot_path,
                           meta=self.meta)

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def _make_fleet_handler(registries: "Dict[str, MetricsRegistry]",
                        meta: Optional[Dict[str, Any]]):
    def merged() -> Dict[str, Any]:
        return merge_snapshots([
            reg.snapshot(meta={**(meta or {}), "replica": rid})
            for rid, reg in registries.items()])

    class _FleetHandler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            doc = None
            if path in ("/", "/metrics", "/metrics.json", "/snapshot",
                        "/snapshot.json"):
                doc = merged()
            elif path.startswith("/metrics/"):
                name = path[len("/metrics/"):]
                if name.endswith(".json"):
                    name = name[:-len(".json")]
                reg = registries.get(name)
                if reg is not None:
                    doc = reg.snapshot(
                        meta={**(meta or {}), "replica": name})
            if doc is None:
                self.send_error(
                    404, "try /metrics, /metrics.json, or /metrics/<rid>"
                         f" with rid in {sorted(registries)}")
                return
            if path.endswith(".json") or path in ("/snapshot",):
                body = json.dumps(doc, sort_keys=True,
                                  default=float).encode()
                ctype = "application/json"
            else:
                body = snapshot_to_prometheus(doc).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            # deferred import keeps the module standalone-importable
            from apex_tpu.utils.logging import publish_event

            publish_event("metrics_scrape", path=path, bytes=len(body))

        def log_message(self, format, *args):
            pass    # same no-spam contract as the single-registry handler

    return _FleetHandler


class FleetMetricsExporter(MetricsExporter):
    """The fleet pull endpoint (PR 13): one HTTP server over N
    per-replica registries. ``/metrics`` (+ ``/metrics.json``) serves
    the :func:`merge_snapshots` **fleet view** — the exact merge, so a
    scrape equals recording the union stream — and ``/metrics/<rid>``
    (+ ``.json``) serves each replica's own registry, the same
    per-replica document ``--metrics-snapshot`` commits at ``PATH.rK``.
    Scrapes run on the HTTP thread; replica workers never see them."""

    def __init__(self, registries: "Dict[str, MetricsRegistry]", *,
                 port: int = 0, host: str = "127.0.0.1",
                 meta: Optional[Dict[str, Any]] = None):
        if not registries:
            raise ValueError(
                "FleetMetricsExporter needs at least one registry")
        # no registry / snapshot_path: the CLI owns per-replica snapshot
        # files (PATH.rK + the merged PATH), stop() must not write one
        super().__init__(None, port=port, host=host, meta=meta)
        self.registries = dict(registries)

    def _make_handler(self):
        return _make_fleet_handler(self.registries, self.meta)
