"""Span-tree tracing — request/step-scoped causal context over the bus.

PR-2 gave the process an event bus and a JSONL sink, but every record on
it is an island: ``serve_queue_wait``, ``serve_decode_step``, checkpoint
stalls, and ``kernel_autotune`` carry no causal thread tying one request
or one train step together end to end. This module adds that thread:

- :class:`Span` — one named range with ``trace_id``/``span_id``/
  ``parent_id``, monotonic start/end, and attributes. A *trace* is the
  tree of spans sharing a ``trace_id`` (one serve request, one train
  step).
- :class:`Tracer` — opens/closes spans and publishes each transition as a
  ``span_open``/``span_close`` record on the existing
  :func:`~apex_tpu.utils.logging.publish_event` bus (``emit=False`` —
  tracing must never spam stderr), so every bus consumer (telemetry
  mirror, goodput ledger, flight recorder) sees the same stream with
  zero new wiring. Context-manager spans nest through a ``contextvars``
  ambient parent AND enter a ``jax.profiler.TraceAnnotation`` so
  host-side spans line up with the XLA device trace.
- :class:`ChromeTraceWriter` — streams completed spans as Chrome-trace
  ``"X"`` events (one JSON object per line inside a JSON array), the
  format Perfetto and ``chrome://tracing`` load directly. Each trace gets
  its own ``tid`` track, so a serving run renders as one row per request.

The default process tracer is **disabled**: ``tracer.span(...)`` yields
``None``, publishes nothing, and allocates nothing but a generator frame
— instrumented hot paths (the serve scheduler tick, ``ResilientStep``)
cost one ``is-enabled`` check when tracing is off, and nothing host-side
ever traces into a jitted function either way (tier-1 asserts the serve
one-compile invariant holds with tracing on). Enable per run via
``Telemetry(trace_jsonl=...)``, ``apex-tpu-serve --trace-jsonl``,
``apex-tpu-bench --trace-jsonl``, or :func:`set_tracer`.

See docs/observability.md "Tracing and postmortems".
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional

from apex_tpu.utils.logging import publish_event, subscribe_events

# one process-wide origin for Chrome-trace timestamps: every span's
# ``ts`` is microseconds since this module imported, so spans from
# different tracers/threads share a timeline
_EPOCH = time.perf_counter()

# sentinel: "use the ambient contextvar parent" (None means "force root")
_AMBIENT = object()


class Span:
    """One named range in a trace tree. Mutable until :meth:`Tracer.end`
    stamps ``t1``; ``record()`` is the bus/JSON shape."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "status", "attrs")

    def __init__(self, trace_id: str, span_id: int, parent_id: Optional[int],
                 name: str, t0: float, attrs: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.status = "ok"
        self.attrs = attrs

    @property
    def dur_ms(self) -> Optional[float]:
        return None if self.t1 is None else (self.t1 - self.t0) * 1e3

    def record(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "t0": round(self.t0, 6),
        }
        if self.t1 is not None:
            rec["t1"] = round(self.t1, 6)
            rec["dur_ms"] = round(self.dur_ms, 3)
            rec["status"] = self.status
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        return rec


class Tracer:
    """Span factory over the process event bus.

    Two styles compose:

    - **context-manager** (``with tracer.span("post_step"):``) for
      regions with LIFO nesting on one thread — the ambient parent rides
      a contextvar and the range mirrors into ``jax.profiler``'s device
      trace;
    - **manual** (``begin()`` / ``end()``) for lifecycles that open and
      close across different callbacks — a serve request's ``queue`` span
      opens at submit and closes ticks later at admission. Manual spans
      accept explicit ``t0``/``t1`` stamps so they can reuse the
      instrumented component's own clock reads (the serve scheduler's
      TTFT arithmetic and its spans come from the SAME timestamps —
      reconciliation is exact, not approximate).

    Disabled tracers return ``None`` spans and publish nothing. Completed
    spans are kept (bounded deque) for export and tests; open spans are
    queryable for the flight recorder's "what was in flight" dump.
    """

    def __init__(self, enabled: bool = True, *, max_completed: int = 65536):
        self.enabled = enabled
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._lock = threading.Lock()
        self._open: Dict[int, Span] = {}
        self.completed: collections.deque = collections.deque(
            maxlen=max_completed)
        self._current: contextvars.ContextVar = contextvars.ContextVar(
            "apex_tpu_current_span", default=None)

    # ---- core ----------------------------------------------------------
    def new_trace_id(self, hint: str = "trace") -> str:
        return f"{hint}#{next(self._trace_ids)}"

    def current(self) -> Optional[Span]:
        return self._current.get()

    def begin(self, name: str, *, parent: Optional[Span] = None,
              trace_id: Optional[str] = None, t0: Optional[float] = None,
              **attrs: Any) -> Optional[Span]:
        """Open a span. ``parent`` wins over ``trace_id``; with neither,
        the span roots a new trace. Returns ``None`` when disabled."""
        if not self.enabled:
            return None
        if parent is not None:
            trace_id = parent.trace_id
        elif trace_id is None:
            trace_id = self.new_trace_id(name)
        span = Span(trace_id, next(self._span_ids),
                    parent.span_id if parent is not None else None,
                    name, t0 if t0 is not None else time.perf_counter(),
                    dict(attrs))
        with self._lock:
            self._open[span.span_id] = span
        publish_event("span_open", emit=False, **span.record())
        return span

    def end(self, span: Optional[Span], *, t1: Optional[float] = None,
            status: str = "ok", **attrs: Any) -> None:
        """Close a span (idempotent; ``None`` from a disabled begin is a
        no-op, so call sites need no enabled-guard of their own)."""
        if span is None or span.t1 is not None:
            return
        span.t1 = t1 if t1 is not None else time.perf_counter()
        span.status = status
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            self._open.pop(span.span_id, None)
            self.completed.append(span)
        publish_event("span_close", emit=False, **span.record())

    # ---- context-manager style -----------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, *, parent: Any = _AMBIENT, **attrs: Any):
        """Nested span: parent defaults to the ambient (contextvar) span;
        pass ``parent=None`` to force a new root. The region also enters a
        ``jax.profiler.TraceAnnotation`` so it shows in the device trace
        timeline next to the XLA ops it encloses."""
        if not self.enabled:
            yield None
            return
        if parent is _AMBIENT:
            parent = self._current.get()
        s = self.begin(name, parent=parent, **attrs)
        token = self._current.set(s)
        ann = None
        try:  # device-trace mirror is best-effort: no backend, no range
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
        try:
            yield s
        except BaseException:
            self.end(s, status="error")
            raise
        finally:
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:
                    pass
            self._current.reset(token)
            self.end(s)

    def trace(self, name: str, **attrs: Any):
        """Root-span context manager: always starts a NEW trace (ignores
        any ambient parent) — one call, one trace tree."""
        return self.span(name, parent=None, **attrs)

    # ---- introspection -------------------------------------------------
    def open_spans(self) -> List[Dict[str, Any]]:
        """Records of the spans currently in flight (flight-recorder
        food: "what was the process doing when it died")."""
        with self._lock:
            return [s.record() for s in
                    sorted(self._open.values(), key=lambda s: s.span_id)]

    def completed_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.record() for s in self.completed]


# --------------------------------------------------------------------------
# default process tracer
# --------------------------------------------------------------------------

_default_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process default tracer (disabled until a run enables one)."""
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Install ``tracer`` as the process default; returns the previous one
    so callers (``Telemetry``, the CLIs) can restore it on close."""
    global _default_tracer
    prev = _default_tracer
    _default_tracer = tracer
    return prev


# --------------------------------------------------------------------------
# Chrome-trace (Perfetto) export
# --------------------------------------------------------------------------

class ChromeTraceWriter:
    """Stream ``span_close`` bus records to a Chrome-trace JSON file.

    Output is the JSON Array Format: ``[`` then one complete (``"ph":
    "X"``) event object per line. Perfetto and ``chrome://tracing``
    tolerate a missing closing bracket, so a crashed run's partial file
    still loads; :meth:`close` finalizes it into strict JSON. Each
    distinct ``trace_id`` is assigned its own ``tid`` (with a thread-name
    metadata event), so traces render as parallel tracks — one row per
    serve request / train step.
    """

    def __init__(self, path: str, *, pid: Optional[int] = None):
        import os

        self.path = path
        self.pid = pid if pid is not None else os.getpid()
        self._tids: Dict[str, int] = {}
        self._f = open(path, "w")
        self._f.write("[")
        self._wrote_any = False
        # span_close records arrive on whichever thread closed the span
        # (the Tracer is thread-safe, so that can be several at once) —
        # the comma/newline framing must not interleave
        self._lock = threading.Lock()
        self.events = 0
        self._unsubscribe = subscribe_events(self._on_event)

    def _on_event(self, rec: Dict[str, Any]) -> None:
        if rec.get("event") == "span_close":
            self.write_span(rec)

    def _emit(self, obj: Dict[str, Any]) -> None:
        # caller holds self._lock
        self._f.write(("," if self._wrote_any else "") + "\n"
                      + json.dumps(obj, sort_keys=True, default=str))
        self._wrote_any = True

    def _tid(self, trace_id: str) -> int:
        # caller holds self._lock (write_span)
        tid = self._tids.get(trace_id)
        if tid is None:
            tid = self._tids[trace_id] = len(self._tids) + 1
            self._emit({"ph": "M", "name": "thread_name", "pid": self.pid,
                        "tid": tid, "args": {"name": trace_id}})
        return tid

    def write_span(self, rec: Dict[str, Any]) -> None:
        args = {"trace_id": rec.get("trace_id"),
                "span_id": rec.get("span_id"),
                "parent_id": rec.get("parent_id"),
                "status": rec.get("status")}
        args.update(rec.get("attrs") or {})
        with self._lock:
            if self._f.closed:
                return
            self._emit({
                "ph": "X", "cat": "host", "name": rec.get("name", "?"),
                "pid": self.pid,
                "tid": self._tid(str(rec.get("trace_id"))),
                "ts": round((float(rec["t0"]) - _EPOCH) * 1e6, 3),
                "dur": round((float(rec["t1"]) - float(rec["t0"])) * 1e6,
                             3),
                "args": args,
            })
            self.events += 1
            self._f.flush()  # low-rate; a crash keeps what completed

    def close(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None
        with self._lock:
            if not self._f.closed:
                self._f.write("\n]\n")
                self._f.close()

    def __enter__(self) -> "ChromeTraceWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_chrome_trace(path: str) -> List[Dict[str, Any]]:
    """Parse a Chrome-trace file, tolerating the unterminated array a
    crashed run leaves behind (exactly what Perfetto tolerates)."""
    with open(path) as f:
        text = f.read().strip()
    if not text.startswith("["):
        raise ValueError(f"{path}: not a Chrome-trace JSON array")
    if text.endswith(","):
        text = text[:-1]
    if not text.endswith("]"):
        text += "]"
    return json.loads(text)


def spans_by_trace(records: List[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    """Group span records (bus ``span_close`` records or a tracer's
    ``completed_records()``) by ``trace_id`` — one entry per request/step
    trace, spans in id (open) order."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        out.setdefault(str(rec.get("trace_id")), []).append(rec)
    for spans in out.values():
        spans.sort(key=lambda r: r.get("span_id") or 0)
    return out
