"""Span-tree tracing — request/step-scoped causal context over the bus.

PR-2 gave the process an event bus and a JSONL sink, but every record on
it is an island: ``serve_queue_wait``, ``serve_decode_step``, checkpoint
stalls, and ``kernel_autotune`` carry no causal thread tying one request
or one train step together end to end. This module adds that thread:

- :class:`Span` — one named range with ``trace_id``/``span_id``/
  ``parent_id``, monotonic start/end, and attributes. A *trace* is the
  tree of spans sharing a ``trace_id`` (one serve request, one train
  step).
- :class:`Tracer` — opens/closes spans and publishes each transition as a
  ``span_open``/``span_close`` record on the existing
  :func:`~apex_tpu.utils.logging.publish_event` bus (``emit=False`` —
  tracing must never spam stderr), so every bus consumer (telemetry
  mirror, goodput ledger, flight recorder) sees the same stream with
  zero new wiring. Context-manager spans nest through a ``contextvars``
  ambient parent AND enter a ``jax.profiler.TraceAnnotation`` so
  host-side spans line up with the XLA device trace.
- :class:`ChromeTraceWriter` — streams completed spans as Chrome-trace
  ``"X"`` events (one JSON object per line inside a JSON array), the
  format Perfetto and ``chrome://tracing`` load directly. Each trace gets
  its own ``tid`` track, so a serving run renders as one row per request.
- :class:`TraceSampler` / :class:`TailCaptureRouter` — fleet-scale trace
  volume control (PR 13): deterministic seeded head sampling over request
  *journeys* plus a bounded per-journey span ring that retroactively
  **promotes** a journey into the trace file the moment its outcome turns
  bad (deadline/evict/reject/failover/hedge, or any terminal inside an
  SLO-breach window) — the slow tail is always captured, the happy path
  is sampled. The router also splits one bus stream across several
  writers by the tracer's ``track`` tag (fleet file + one file per
  replica).

The default process tracer is **disabled**: ``tracer.span(...)`` yields
``None``, publishes nothing, and allocates nothing but a generator frame
— instrumented hot paths (the serve scheduler tick, ``ResilientStep``)
cost one ``is-enabled`` check when tracing is off, and nothing host-side
ever traces into a jitted function either way (tier-1 asserts the serve
one-compile invariant holds with tracing on). Enable per run via
``Telemetry(trace_jsonl=...)``, ``apex-tpu-serve --trace-jsonl``,
``apex-tpu-bench --trace-jsonl``, or :func:`set_tracer`.

See docs/observability.md "Tracing and postmortems".
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional

from apex_tpu.monitor.journey import (JOURNEY_PREFIXES, read_chrome_trace,
                                      spans_by_trace)
from apex_tpu.utils.logging import publish_event, subscribe_events

__all__ = [
    "Span", "Tracer", "ChromeTraceWriter", "TraceSampler",
    "TailCaptureRouter", "get_tracer", "set_tracer",
    "read_chrome_trace", "spans_by_trace",
]

# one process-wide origin for Chrome-trace timestamps: every span's
# ``ts`` is microseconds since this module imported, so spans from
# different tracers/threads share a timeline
_EPOCH = time.perf_counter()

# one process-wide span-id sequence: a fleet run has one tracer per
# replica plus the controller's, all stamping spans into the SAME
# journey trace — per-tracer counters would collide and break parent
# links in the merged analysis
_SPAN_IDS = itertools.count(1)

# sentinel: "use the ambient contextvar parent" (None means "force root")
_AMBIENT = object()


class Span:
    """One named range in a trace tree. Mutable until :meth:`Tracer.end`
    stamps ``t1``; ``record()`` is the bus/JSON shape."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "status", "attrs")

    def __init__(self, trace_id: str, span_id: int, parent_id: Optional[int],
                 name: str, t0: float, attrs: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.status = "ok"
        self.attrs = attrs

    @property
    def dur_ms(self) -> Optional[float]:
        return None if self.t1 is None else (self.t1 - self.t0) * 1e3

    def record(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "t0": round(self.t0, 6),
        }
        if self.t1 is not None:
            rec["t1"] = round(self.t1, 6)
            rec["dur_ms"] = round(self.dur_ms, 3)
            rec["status"] = self.status
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        return rec


class Tracer:
    """Span factory over the process event bus.

    Two styles compose:

    - **context-manager** (``with tracer.span("post_step"):``) for
      regions with LIFO nesting on one thread — the ambient parent rides
      a contextvar and the range mirrors into ``jax.profiler``'s device
      trace;
    - **manual** (``begin()`` / ``end()``) for lifecycles that open and
      close across different callbacks — a serve request's ``queue`` span
      opens at submit and closes ticks later at admission. Manual spans
      accept explicit ``t0``/``t1`` stamps so they can reuse the
      instrumented component's own clock reads (the serve scheduler's
      TTFT arithmetic and its spans come from the SAME timestamps —
      reconciliation is exact, not approximate).

    Disabled tracers return ``None`` spans and publish nothing. Completed
    spans are kept (bounded deque) for export and tests; open spans are
    queryable for the flight recorder's "what was in flight" dump.
    """

    def __init__(self, enabled: bool = True, *, max_completed: int = 65536,
                 tags: Optional[Dict[str, Any]] = None):
        self.enabled = enabled
        # identity attrs stamped on EVERY span this tracer opens (the
        # fleet harness tags each replica's tracer ``track="rK"`` so one
        # bus stream splits into per-replica trace files and the merged
        # Perfetto view renders one track per replica). Tags win over
        # same-named call-site attrs — they are the tracer's identity.
        self.tags = dict(tags) if tags else {}
        self._trace_ids = itertools.count(1)
        self._lock = threading.Lock()
        self._open: Dict[int, Span] = {}
        self.completed: collections.deque = collections.deque(
            maxlen=max_completed)
        self._current: contextvars.ContextVar = contextvars.ContextVar(
            "apex_tpu_current_span", default=None)

    # ---- core ----------------------------------------------------------
    def new_trace_id(self, hint: str = "trace") -> str:
        return f"{hint}#{next(self._trace_ids)}"

    def current(self) -> Optional[Span]:
        return self._current.get()

    def begin(self, name: str, *, parent: Optional[Span] = None,
              trace_id: Optional[str] = None,
              parent_id: Optional[int] = None,
              t0: Optional[float] = None,
              **attrs: Any) -> Optional[Span]:
        """Open a span. ``parent`` wins over ``trace_id``; with neither,
        the span roots a new trace. ``parent_id`` (with an explicit
        ``trace_id``) links under a span another tracer owns — the
        cross-component propagation seam: a replica scheduler's request
        trace nests under the fleet controller's attempt span. Returns
        ``None`` when disabled."""
        if not self.enabled:
            return None
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        elif trace_id is None:
            trace_id = self.new_trace_id(name)
        if self.tags:
            attrs = {**attrs, **self.tags}
        span = Span(trace_id, next(_SPAN_IDS), parent_id,
                    name, t0 if t0 is not None else time.perf_counter(),
                    dict(attrs))
        with self._lock:
            self._open[span.span_id] = span
        publish_event("span_open", emit=False, **span.record())
        return span

    def end(self, span: Optional[Span], *, t1: Optional[float] = None,
            status: str = "ok", **attrs: Any) -> None:
        """Close a span (idempotent; ``None`` from a disabled begin is a
        no-op, so call sites need no enabled-guard of their own)."""
        if span is None or span.t1 is not None:
            return
        span.t1 = t1 if t1 is not None else time.perf_counter()
        span.status = status
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            self._open.pop(span.span_id, None)
            self.completed.append(span)
        publish_event("span_close", emit=False, **span.record())

    # ---- context-manager style -----------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, *, parent: Any = _AMBIENT, **attrs: Any):
        """Nested span: parent defaults to the ambient (contextvar) span;
        pass ``parent=None`` to force a new root. The region also enters a
        ``jax.profiler.TraceAnnotation`` so it shows in the device trace
        timeline next to the XLA ops it encloses."""
        if not self.enabled:
            yield None
            return
        if parent is _AMBIENT:
            parent = self._current.get()
        s = self.begin(name, parent=parent, **attrs)
        token = self._current.set(s)
        ann = None
        try:  # device-trace mirror is best-effort: no backend, no range
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
        try:
            yield s
        except BaseException:
            self.end(s, status="error")
            raise
        finally:
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:
                    pass
            self._current.reset(token)
            self.end(s)

    def trace(self, name: str, **attrs: Any):
        """Root-span context manager: always starts a NEW trace (ignores
        any ambient parent) — one call, one trace tree."""
        return self.span(name, parent=None, **attrs)

    # ---- introspection -------------------------------------------------
    def open_spans(self) -> List[Dict[str, Any]]:
        """Records of the spans currently in flight (flight-recorder
        food: "what was the process doing when it died")."""
        with self._lock:
            return [s.record() for s in
                    sorted(self._open.values(), key=lambda s: s.span_id)]

    def completed_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.record() for s in self.completed]


# --------------------------------------------------------------------------
# default process tracer
# --------------------------------------------------------------------------

_default_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process default tracer (disabled until a run enables one)."""
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Install ``tracer`` as the process default; returns the previous one
    so callers (``Telemetry``, the CLIs) can restore it on close."""
    global _default_tracer
    prev = _default_tracer
    _default_tracer = tracer
    return prev


# --------------------------------------------------------------------------
# Chrome-trace (Perfetto) export
# --------------------------------------------------------------------------

class ChromeTraceWriter:
    """Stream ``span_close`` bus records to a Chrome-trace JSON file.

    Output is the JSON Array Format: ``[`` then one complete (``"ph":
    "X"``) event object per line. Perfetto and ``chrome://tracing``
    tolerate a missing closing bracket, so a crashed run's partial file
    still loads; :meth:`close` finalizes it into strict JSON. Each
    distinct ``trace_id`` is assigned its own ``tid`` (with a thread-name
    metadata event), so traces render as parallel tracks — one row per
    serve request / train step.
    """

    def __init__(self, path: str, *, pid: Optional[int] = None,
                 subscribe: bool = True):
        import os

        self.path = path
        self.pid = pid if pid is not None else os.getpid()
        self._tids: Dict[str, int] = {}
        self._f = open(path, "w")
        self._f.write("[")
        self._wrote_any = False
        # span_close records arrive on whichever thread closed the span
        # (the Tracer is thread-safe, so that can be several at once) —
        # the comma/newline framing must not interleave
        self._lock = threading.Lock()
        self.events = 0
        # subscribe=False makes the writer a pure sink fed through
        # write_span() — the TailCaptureRouter owns the bus subscription
        # and routes/samples/promotes before anything reaches a file
        self._unsubscribe = subscribe_events(self._on_event) \
            if subscribe else None

    def _on_event(self, rec: Dict[str, Any]) -> None:
        if rec.get("event") == "span_close":
            self.write_span(rec)

    def _emit(self, obj: Dict[str, Any]) -> None:
        # caller holds self._lock
        self._f.write(("," if self._wrote_any else "") + "\n"
                      + json.dumps(obj, sort_keys=True, default=str))
        self._wrote_any = True

    def _tid(self, trace_id: str) -> int:
        # caller holds self._lock (write_span)
        tid = self._tids.get(trace_id)
        if tid is None:
            tid = self._tids[trace_id] = len(self._tids) + 1
            self._emit({"ph": "M", "name": "thread_name", "pid": self.pid,
                        "tid": tid, "args": {"name": trace_id}})
        return tid

    def write_span(self, rec: Dict[str, Any]) -> None:
        args = {"trace_id": rec.get("trace_id"),
                "span_id": rec.get("span_id"),
                "parent_id": rec.get("parent_id"),
                "status": rec.get("status")}
        args.update(rec.get("attrs") or {})
        with self._lock:
            if self._f.closed:
                return
            self._emit({
                "ph": "X", "cat": "host", "name": rec.get("name", "?"),
                "pid": self.pid,
                "tid": self._tid(str(rec.get("trace_id"))),
                "ts": round((float(rec["t0"]) - _EPOCH) * 1e6, 3),
                "dur": round((float(rec["t1"]) - float(rec["t0"])) * 1e6,
                             3),
                "args": args,
            })
            self.events += 1
            self._f.flush()  # low-rate; a crash keeps what completed

    def close(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None
        with self._lock:
            if not self._f.closed:
                self._f.write("\n]\n")
                self._f.close()

    def __enter__(self) -> "ChromeTraceWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# read_chrome_trace / spans_by_trace live in monitor/journey.py now
# (stdlib-only, loadable by path from tools/trace_explain.py) and are
# re-exported above for every existing caller.


# --------------------------------------------------------------------------
# head sampling + tail capture (fleet-scale trace volume control)
# --------------------------------------------------------------------------

class TraceSampler:
    """Deterministic head sampling: ``sampled(key)`` is a pure function
    of ``(seed, key)`` — every process, replica, and re-run agrees on
    which journeys stream, so a fleet's writers never disagree about a
    request and a test can predict the sample set exactly. ``rate=1``
    samples everything (today's behavior)."""

    def __init__(self, rate: float = 1.0, *, seed: int = 0):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"sample rate must be in (0, 1]: {rate}")
        self.rate = float(rate)
        self.seed = int(seed)

    def sampled(self, key: str) -> bool:
        if self.rate >= 1.0:
            return True
        import hashlib

        h = hashlib.blake2b(f"{self.seed}:{key}".encode(),
                            digest_size=8).digest()
        frac = int.from_bytes(h, "big") / 2.0 ** 64
        return frac < self.rate


# lifecycle events that turn a journey's outcome BAD: its full span ring
# is promoted into the trace file even when head sampling dropped it
_BAD_OUTCOME_EVENTS = frozenset((
    "serve_request_evicted", "serve_deadline_exceeded",
    "serve_request_rejected", "serve_failover", "serve_hedge_fired",
))
# terminal lifecycle events: the journey's keep-or-drop decision point
# (every request reaches exactly one of these per attempt — PR-8's
# exactly-once contract; the fleet journey root close is the fallback
# decider for synthetic terminals that publish no event)
_TERMINAL_EVENTS = frozenset((
    "serve_request_completed", "serve_request_evicted",
    "serve_request_rejected", "serve_deadline_exceeded",
))


class TailCaptureRouter:
    """The seam between ``span_close`` bus records and Chrome-trace
    writers: route by the tracer's ``track`` tag, head-sample request
    journeys, and retroactively promote the journeys that go bad.

    - **Routing** — ``writers`` maps a ``track`` tag (``"fleet"``,
      ``"r0"``...) to a :class:`ChromeTraceWriter` built with
      ``subscribe=False``; spans with no (or an unknown) track land on
      the default writer. Non-journey traces (the per-tick scheduler
      trace, train steps) always stream.
    - **Sampling** — a journey (trace id ``journey:<rid>`` /
      ``request:<rid>``) streams immediately when the seeded
      :class:`TraceSampler` picks it; otherwise its spans buffer in a
      bounded per-journey ring.
    - **Tail capture** — the journey's terminal lifecycle event decides:
      a bad outcome anywhere in its life (deadline/evict/reject/
      failover/hedge — or ANY terminal inside an SLO-breach window)
      flushes the ring into the writers and publishes
      ``serve_trace_promoted``; a happy terminal drops the ring. The
      slow tail is always captured; only the happy path is sampled.

    Span records arrive on whichever thread closed the span (replica
    workers, the control thread) — every ring/decision mutation holds
    ``_lock``; bus publishes happen outside it (the bus's own rule)."""

    def __init__(self, writers: Dict[str, ChromeTraceWriter], *,
                 sample_rate: float = 1.0, sample_seed: int = 0,
                 ring_spans: int = 256, max_decided: int = 65536):
        if not writers:
            raise ValueError("TailCaptureRouter needs at least one writer")
        self.writers = dict(writers)
        self._default_writer = next(iter(self.writers.values()))
        self.sampler = TraceSampler(sample_rate, seed=sample_seed)
        self.ring_spans = max(1, int(ring_spans))
        self.max_decided = max(16, int(max_decided))
        self._lock = threading.Lock()
        # per-journey buffered span records, awaiting the outcome
        self._rings: Dict[str, collections.deque] = {}
        # trace_id -> True (write-through) / False (dropped)
        self._decided: Dict[str, bool] = {}
        # request_id -> the event that turned the journey bad
        self._bad: Dict[str, str] = {}
        self._breached: set = set()
        self.sampled = 0      # journeys streamed by head sampling
        self.promoted = 0     # bad-outcome journeys flushed from a ring
        self.dropped = 0      # happy-path journeys discarded
        self._unsubscribe = subscribe_events(self._on_event)

    # ---- bus wiring ----------------------------------------------------
    def close(self) -> None:
        """Unsubscribe and close every writer (undecided rings are
        dropped — the run is over, there is no outcome left to wait
        for)."""
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None
        for w in self.writers.values():
            w.close()

    def __enter__(self) -> "TailCaptureRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"sampled": self.sampled, "promoted": self.promoted,
                    "dropped": self.dropped}

    # ---- event handling ------------------------------------------------
    def _on_event(self, rec: Dict[str, Any]) -> None:
        ev = rec.get("event")
        if ev == "span_close":
            self._route_span(rec)
            return
        if ev == "serve_slo_breach":
            with self._lock:
                self._breached.add(str(rec.get("objective")))
            return
        if ev == "serve_slo_recovered":
            with self._lock:
                self._breached.discard(str(rec.get("objective")))
            return
        if ev in _BAD_OUTCOME_EVENTS and "request_id" in rec:
            with self._lock:
                self._mark_bad(str(rec["request_id"]), str(ev))
        if ev in _TERMINAL_EVENTS and "request_id" in rec:
            self._decide(str(rec["request_id"]))

    def _mark_bad(self, rid: str, why: str) -> None:
        # caller holds self._lock
        if rid not in self._bad:
            if len(self._bad) >= self.max_decided:
                self._bad.pop(next(iter(self._bad)))
            self._bad[rid] = why

    def _writer_for(self, rec: Dict[str, Any]) -> ChromeTraceWriter:
        track = (rec.get("attrs") or {}).get("track")
        return self.writers.get(str(track), self._default_writer)

    def _route_span(self, rec: Dict[str, Any]) -> None:
        tid = str(rec.get("trace_id"))
        if not tid.startswith(JOURNEY_PREFIXES):
            self._writer_for(rec).write_span(rec)
            return
        promote_payload = None
        with self._lock:
            verdict = self._decided.get(tid)
            if verdict is None:
                if self.sampler.sampled(tid):
                    self._remember(tid, True)
                    self.sampled += 1
                    verdict = True
                else:
                    ring = self._rings.get(tid)
                    if ring is None:
                        ring = self._rings[tid] = collections.deque(
                            maxlen=self.ring_spans)
                    ring.append(rec)
                    if rec.get("parent_id") is None \
                            and tid.startswith("journey:"):
                        # fallback decider: a fleet journey whose
                        # synthetic terminal published no lifecycle
                        # event (total fleet loss) still settles when
                        # its root — closed after every fleet event by
                        # contract — arrives
                        promote_payload = self._decide_locked(
                            tid.split(":", 1)[1])
                    verdict = None
            if verdict is True:
                self._writer_for(rec).write_span(rec)
        if promote_payload is not None:
            self._publish_promoted(*promote_payload)

    def _remember(self, tid: str, verdict: bool) -> None:
        # caller holds self._lock
        if len(self._decided) >= self.max_decided:
            self._decided.pop(next(iter(self._decided)))
        self._decided[tid] = verdict

    def _decide(self, rid: str) -> None:
        with self._lock:
            payload = self._decide_locked(rid)
        if payload is not None:
            self._publish_promoted(*payload)

    def _decide_locked(self, rid: str):
        # caller holds self._lock; returns a (rid, why, spans) payload
        # when a promotion event must publish (outside the lock)
        payload = None
        for tid in (f"journey:{rid}", f"request:{rid}"):
            if tid in self._decided:
                continue
            ring = self._rings.pop(tid, None)
            bad = self._bad.get(rid)
            if bad is None and self._breached:
                bad = "slo_breach:" + ",".join(sorted(self._breached))
            if bad is not None:
                self._remember(tid, True)
                if ring is not None:
                    for buffered in ring:
                        self._writer_for(buffered).write_span(buffered)
                    self.promoted += 1
                    payload = (rid, bad, len(ring))
            elif ring is not None:
                # a happy journey we actually saw spans for: drop it.
                # (Without a ring there is nothing to decide — the
                # request was never traced into this router.)
                self._remember(tid, False)
                self.dropped += 1
        return payload

    def _publish_promoted(self, rid: str, why: str, spans: int) -> None:
        publish_event("serve_trace_promoted", emit=False,
                      request_id=rid, reason=why, buffered_spans=spans)
