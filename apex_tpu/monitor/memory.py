"""HBM memory accounting — ``hbm_snapshot`` events from two sources.

The ROADMAP's paged-KV-pool and low-precision-cache items both claim HBM
wins; nothing in the stack could *measure* one. Two complementary
measurements, both published as ``hbm_snapshot`` records on the process
event bus (``emit=False`` — monitoring consumers subscribe; stderr stays
quiet):

- **sampled** (``kind="sampled"``) — :class:`MemoryAccountant` reads the
  runtime allocator's ``device.memory_stats()`` (bytes in use, peak,
  limit) per step/tick. Real numbers on TPU; CPU backends return no
  stats and the accountant degrades to silence (never fake zeros).
- **static** (``kind="static"``) — :func:`publish_compiled_memory` reads
  XLA's own ``compiled.memory_analysis()`` (argument/output/temp bytes)
  at every AOT point: serve decode + prompt buckets
  (``Engine.aot_compile``), the telemetry bench's calibrated step
  (``Telemetry.calibrate``), and autotuner sweeps. Works on every
  backend, CPU smoke included — it is the compiler's reservation, not an
  allocator sample.

The :class:`~apex_tpu.monitor.goodput.GoodputLedger` folds both into its
summary (``hbm`` section: allocator peak + static peak), and the flight
recorder keeps the latest snapshot for its postmortem dump. See
docs/observability.md "Tracing and postmortems".
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from apex_tpu.utils.logging import publish_event

# allocator stats worth keeping when present (plus any other integer
# field on backends that report a different set — never an empty record)
_SAMPLED_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                 "largest_alloc_size", "bytes_reserved",
                 "largest_free_block_bytes", "pool_bytes")

# the device-side fields of CompiledMemoryStats — moved to
# monitor/costs.py (the cost ledger reads the same record); re-exported
# here for compatibility
from apex_tpu.monitor.costs import MEMORY_STATIC_KEYS as _STATIC_KEYS


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """Integer allocator stats for ``device`` (default: first device), or
    ``None`` when the backend exposes none (CPU) or is unreachable."""
    if device is None:
        import jax  # deferred: accounting must not force backend init

        try:
            device = jax.devices()[0]
        except Exception:
            return None
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {k: int(stats[k]) for k in _SAMPLED_KEYS
           if isinstance(stats.get(k), (int, float))}
    if not out:  # unfamiliar backend: keep whatever integers it reports
        out = {k: int(v) for k, v in stats.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    return out or None


def memory_analysis_record(compiled) -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` as a plain int dict (plus the
    derived ``reserved_bytes`` total), or ``None`` when the executable
    doesn't expose one. Delegates to ``monitor/costs.py`` — the cost
    ledger's ``xla.memory_analysis`` entry and the ``hbm_snapshot``
    events extract through ONE spelling."""
    from apex_tpu.monitor import costs

    return costs.memory_analysis_record(compiled)


def publish_compiled_memory(name: str, compiled,
                            **attrs: Any) -> Optional[Dict[str, int]]:
    """Publish one static ``hbm_snapshot`` for a compiled executable (an
    AOT point). Best-effort: returns the record, or ``None`` (and
    publishes nothing) when no analysis is available."""
    rec = memory_analysis_record(compiled)
    if rec is None:
        return None
    publish_event("hbm_snapshot", emit=False, kind="static", name=name,
                  **attrs, **rec)
    return rec


def sample_device_memory(tag: str, device=None,
                         **attrs: Any) -> Optional[Dict[str, int]]:
    """One-shot allocator sample published as a sampled ``hbm_snapshot``
    (module-level convenience; loops wanting cadence control use
    :class:`MemoryAccountant`)."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    publish_event("hbm_snapshot", emit=False, kind="sampled", tag=tag,
                  **attrs, **stats)
    return stats


class MemoryAccountant:
    """Per-step/tick allocator sampling with a cadence bound.

    ``tick(tag)`` samples every ``every``-th call (a decode loop ticking
    thousands of times per second should not read allocator stats on each
    one); ``sample(tag)`` is unconditional. ``device`` is injectable for
    tests; sampling is silent on backends with no stats.
    """

    def __init__(self, device=None, *, every: int = 1):
        self.device = device
        self.every = max(1, int(every))
        self.samples = 0
        self.last: Optional[Dict[str, int]] = None
        self.peak_bytes_in_use = 0
        self._ticks = 0
        self._dead = False   # backend reported no stats: stop asking

    def tick(self, tag: str, **attrs: Any) -> Optional[Dict[str, int]]:
        self._ticks += 1
        if self._dead or self._ticks % self.every:
            return None
        return self.sample(tag, **attrs)

    def sample(self, tag: str, **attrs: Any) -> Optional[Dict[str, int]]:
        if self._dead:
            return None
        if self.device is None:
            # resolve once: a per-tick jax.devices() lookup on the decode
            # hot path would cost more than the sample itself
            import jax

            try:
                self.device = jax.devices()[0]
            except Exception:
                self._dead = True
                return None
        stats = sample_device_memory(tag, self.device, **attrs)
        if stats is None:
            # stat-less backend (CPU): the answer will not change — make
            # every later tick() a single flag check, not a failed probe
            self._dead = True
            return None
        self.samples += 1
        self.last = stats
        self.peak_bytes_in_use = max(
            self.peak_bytes_in_use,
            stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))
        return stats
