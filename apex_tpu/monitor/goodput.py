"""Goodput ledger — productive vs. lost wall-clock accounting.

"Goodput" is the fraction of run time spent making forward progress. A
training run loses time in ways no single component sees end to end:
overflow-skipped steps (the step ran, the update was discarded), checkpoint
save/restore stalls, and the unwind after a preemption signal. The ledger
aggregates all of them in one place:

- **step time** arrives from ``Telemetry.log_step`` (productive, or lost to
  an overflow skip);
- **stalls** arrive either from the :meth:`GoodputLedger.stall` context
  manager around blocking work, or by subscribing to the resilience
  subsystem's event stream (``checkpoint_save_stall``,
  ``checkpoint_restore_stall`` records carry ``seconds``) via
  :func:`apex_tpu.utils.logging.subscribe_events` — no wiring inside the
  checkpoint code paths needed;
- **event counts** (``overflow_step_skipped``, ``overflow_storm``,
  ``preemption_requested``, retries, corrupt-skip) are tallied so the
  summary explains *why* time was lost.

``summary()`` is what a run report or alert reads:
``{goodput_frac, productive_s, lost_s, lost_by_cause, steps,
skipped_steps, events}``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Optional

from apex_tpu.utils.logging import subscribe_events

# events whose records carry a ``seconds`` field of lost time
STALL_EVENTS = {
    "checkpoint_save_stall": "checkpoint_save",
    "checkpoint_restore_stall": "checkpoint_restore",
    "preemption_unwind": "preemption",
    # collective watchdog: detection charges the time waited so far, the
    # cleared record carries the residual — together the cause totals the
    # actual stall duration of the stuck collective
    "collective_stall": "collective_stall",
    "collective_stall_cleared": "collective_stall",
    # serving: time a request sat in the admission queue because no cache
    # slot was free — capacity lost to queueing, not to compute
    "serve_queue_wait": "serve_queue_wait",
    # serving overload/failure semantics (PR 8): a deadline miss charges
    # the whole submit-to-expiry span (the client gave up; everything
    # computed for it is discarded), a shed/rejected request charges the
    # queue time it wasted before the shed policy chose it. NOTE serving
    # causes can overlap each other and decode wall time (many requests
    # wait concurrently) — they attribute lost capacity, they do not
    # partition the wall clock the way training causes do.
    "serve_deadline_exceeded": "serve_deadline_exceeded",
    "serve_request_rejected": "serve_rejected",
    # paged KV pool (PR 9): admission stalled at the head of the queue
    # because no pool page was free — the whole stall window is lost
    # capacity attributable to KV bytes, distinct from serve_queue_wait
    # (slot scarcity); the two overlap in wall time by design
    "serve_page_alloc_fail": "serve_page_alloc_fail",
    # serving fleet (PR 11): a request was re-dispatched off a dead (or
    # draining) replica — ``seconds`` is the span it had already spent
    # on that replica: the prefill/decode work a survivor redoes
    # (bit-identically under greedy decoding), plus the queue time the
    # migration wasted. Overlaps other serving causes by design.
    "serve_failover": "serve_failover",
    # production trainer (apex_tpu.train, PR 14): the span between the
    # coordinated preemption agreement and the clean exit — finishing the
    # in-flight step, draining collectives, and committing the one final
    # synchronous checkpoint (rank 0 publishes once per drain)
    "train_preempt_drain": "train_preempt_drain",
    # a step re-executed after a crash rollback: real wall time spent
    # redoing work the crash discarded, never double-counted as
    # productive — the supervisor's job-scope high-water mark guarantees
    # each step index lands in the ledger as productive exactly once
    "train_step_replayed": "train_replay",
    # disaggregated serving: wall time a request spent waiting on its
    # prefill→decode KV page handoff (creation → delivery / refusal /
    # abandonment) — the transfer latency TokenWeave-style overlap must
    # hide; a stalled interconnect shows up here, never as a silent TTFT
    # regression
    "serve_handoff_wait": "serve_handoff_wait",
}

# counted (not timed) degradation signals from the resilience subsystem
# and lifecycle signals from the serving scheduler (every serve_* event
# the serve package publishes must appear here or in STALL_EVENTS —
# tests/test_monitor.py greps the sources and fails on an unregistered
# serving event)
COUNTED_EVENTS = (
    "overflow_step_skipped", "overflow_storm", "overflow_storm_cleared",
    "checkpoint_save_retry", "checkpoint_skipped_corrupt",
    "checkpoint_quarantined", "collective_stall_abort",
    "preemption_requested", "bench_preempted",
    "serve_request_admitted", "serve_request_completed",
    "serve_request_evicted", "serve_decode_step",
    "serve_engine_restart", "serve_degraded_mode",
    # a prefix-cache hit at admission: hit_tokens were served from
    # resident read-only pages instead of being re-prefilled
    "serve_prefix_hit",
    # live SLO tracking (monitor.slo): an objective's multi-window burn
    # rate crossed the breach condition / dropped back under it — one
    # event per transition, never one per tick
    "serve_slo_breach", "serve_slo_recovered",
    # serving fleet (serve.fleet): heartbeat-driven replica health
    # transitions (suspect at suspect_misses silent intervals, dead at
    # dead_misses — exactly one event per transition, dead is
    # absorbing), one hedged dispatch fired after hedge_ms with no
    # terminal status, and the rolling-restart lifecycle (drained when
    # the last in-flight request leaves, restarted on rejoin)
    "serve_replica_suspect", "serve_replica_dead",
    "serve_hedge_fired",
    "serve_replica_drained", "serve_replica_restarted",
    # fleet request journeys (monitor.trace TailCaptureRouter): a
    # head-sample-dropped journey's full span ring was retroactively
    # promoted into the trace file because its outcome turned bad —
    # counted, because every promotion is a bad-outcome request (the
    # regression gate treats trace_promoted as lower-is-better)
    "serve_trace_promoted",
    # tensor-parallel serving (serve.tp): an engine built its
    # NamedSharding mesh — counted once per engine with the mesh
    # provenance (tp, sync mode, heads per shard, the per-step
    # collective contract) so postmortems can tell which mesh shape
    # served a stream
    "serve_tp_mesh_ready",
    # production trainer (apex_tpu.train): one supervisor warm restart
    # after a fatal step error (bounded by max_restarts), a sharded
    # checkpoint restored at a different data-parallel world size than it
    # was saved under (the elastic-resize signal), and each committed
    # checkpoint (rank 0 publishes once per commit/resize/restart)
    "train_restart", "train_elastic_resized", "train_checkpoint_commit",
    # topology-portable checkpoints (resilience.topology): a restore
    # crossed a tensor-parallel topology boundary (the manifest's layout
    # block named a different tp than the restoring config — reassembled
    # and re-placed automatically, counted so the crossing is never
    # silent), and a committed checkpoint quarantined during the
    # trainer's restore walk (storage rot caught by crc32/blake2b — a
    # quarantine storm gates as a regression via check_regression)
    "train_topology_restored", "train_ckpt_quarantined",
    # disaggregated serving (apex_tpu.serve.disagg): one migrated KV
    # page landed certified in a decode pool; one handoff refused on
    # arrival (chain-hash / payload-digest mismatch — the request fell
    # back to local re-prefill); one replica spawned into a running
    # fleet; one autoscaler action per direction (hysteresis + cooldown
    # bound these — a flapping autoscaler shows up as a count storm)
    "serve_page_migrated", "serve_handoff_refused",
    "serve_replica_spawned", "serve_autoscale_up", "serve_autoscale_down",
    # speculative decoding (serve.scheduler + serve.spec): per verify
    # step, the batch's draft tokens that matched the target policy's
    # own choices (committed beyond the one-token floor) vs those rolled
    # back by cache-length truncation — counted, never timed: the cost
    # of a rejection is already inside the verify step's wall time
    "serve_spec_draft_accepted", "serve_spec_draft_rejected",
    # block-scale KV quantization (apex_tpu.quant, EngineConfig
    # kv_quant): pages committed as codec bytes + per-(token, head)
    # scales in one prefill (the quantized-capacity provenance a bench
    # capture rides on), and one disaggregated handoff refused because
    # the source and target disagreed on quantization (codec mismatch —
    # the request fell back to local re-prefill, bit-exact by the same
    # mechanism as a digest refusal)
    "serve_kv_quantized_pages", "serve_quant_fallback",
)

# informational events: on the bus for tracing/provenance/postmortem
# consumers (Telemetry mirror, ChromeTraceWriter, FlightRecorder); the
# ledger neither times nor counts them — except hbm_snapshot, folded into
# the summary's hbm section below
INFO_EVENTS = (
    "span", "span_open", "span_close",
    "hbm_snapshot", "flight_recorder_dump",
    "kernel_autotune", "kernel_autotune_failed", "tune_cache_corrupt",
    "preemption_guard_inert",
    "checkpoint_publish_failed", "checkpoint_quarantine_failed",
    # live-metrics export (monitor.export): a pull-endpoint scrape was
    # served / an atomic snapshot file was committed
    "metrics_scrape", "metrics_snapshot",
)

# THE event-name schema: every literal publish_event/structured_warning
# call site in apex_tpu/ must use a name registered in one of the three
# tables — tests/test_monitor.py audits the whole package source, so a
# new subsystem cannot ship an unregistered event
EVENT_SCHEMA = (frozenset(STALL_EVENTS) | frozenset(COUNTED_EVENTS)
                | frozenset(INFO_EVENTS))

_OVERFLOW_CAUSE = "overflow_skip"


class GoodputLedger:
    """Accumulate productive vs. lost seconds, by cause.

    ``attach()`` subscribes to the process event bus so resilience stall and
    degradation events land here automatically; ``detach()`` (or use as a
    context manager) unsubscribes. Step time is reported explicitly via
    :meth:`record_step` — by ``Telemetry.log_step`` when a ledger is
    attached to a telemetry sink.
    """

    def __init__(self):
        self.productive_s = 0.0
        self.lost_by_cause: Dict[str, float] = {}
        self.steps = 0
        self.skipped_steps = 0
        self.events: Dict[str, int] = {}
        # hbm accounting (fed by hbm_snapshot records; monitor.memory)
        self.hbm_samples = 0
        self.hbm_peak_bytes = 0          # allocator peak (sampled kind)
        self.hbm_static_peak_bytes = 0   # XLA reservation peak (static)
        self._unsubscribe: Optional[Callable[[], None]] = None

    # ---- event-bus wiring ----------------------------------------------
    def attach(self) -> "GoodputLedger":
        if self._unsubscribe is None:
            self._unsubscribe = subscribe_events(self.on_event)
        return self

    def detach(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def __enter__(self) -> "GoodputLedger":
        return self.attach()

    def __exit__(self, *exc) -> None:
        self.detach()

    def on_event(self, rec: Dict[str, Any]) -> None:
        """Event-bus callback: fold a published record into the ledger."""
        name = rec.get("event")
        cause = STALL_EVENTS.get(name)
        if cause is not None:
            self.record_stall(cause, float(rec.get("seconds", 0.0)))
        if name in STALL_EVENTS or name in COUNTED_EVENTS:
            self.events[name] = self.events.get(name, 0) + 1
        elif name == "hbm_snapshot":
            self.hbm_samples += 1
            if rec.get("kind") == "static":
                self.hbm_static_peak_bytes = max(
                    self.hbm_static_peak_bytes,
                    int(rec.get("reserved_bytes", 0)))
            else:
                self.hbm_peak_bytes = max(
                    self.hbm_peak_bytes,
                    int(rec.get("peak_bytes_in_use",
                                rec.get("bytes_in_use", 0))))

    # ---- explicit accounting -------------------------------------------
    def record_step(self, seconds: float, productive: bool = True,
                    cause: str = _OVERFLOW_CAUSE) -> None:
        """One step's wall time: productive, or lost to ``cause``."""
        self.steps += 1
        if productive:
            self.productive_s += seconds
        else:
            self.skipped_steps += 1
            self.record_stall(cause, seconds)

    def record_stall(self, cause: str, seconds: float) -> None:
        self.lost_by_cause[cause] = (self.lost_by_cause.get(cause, 0.0)
                                     + seconds)

    @contextlib.contextmanager
    def stall(self, cause: str):
        """Time a blocking region (a synchronous save, a restore at boot)
        as lost time under ``cause``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_stall(cause, time.perf_counter() - t0)

    # ---- reporting ------------------------------------------------------
    @property
    def lost_s(self) -> float:
        return sum(self.lost_by_cause.values())

    def summary(self) -> Dict[str, Any]:
        total = self.productive_s + self.lost_s
        out = {
            "goodput_frac": (self.productive_s / total) if total > 0 else 1.0,
            "productive_s": round(self.productive_s, 6),
            "lost_s": round(self.lost_s, 6),
            "lost_by_cause": {k: round(v, 6)
                              for k, v in sorted(self.lost_by_cause.items())},
            "steps": self.steps,
            "skipped_steps": self.skipped_steps,
            "events": dict(sorted(self.events.items())),
        }
        if self.hbm_samples:
            # memory report rides the goodput summary — the one place a
            # run report already reads (the paged-KV HBM-win measurement
            # foundation; see monitor.memory)
            hbm: Dict[str, Any] = {"samples": self.hbm_samples}
            if self.hbm_peak_bytes:
                hbm["peak_bytes_in_use"] = self.hbm_peak_bytes
            if self.hbm_static_peak_bytes:
                hbm["static_peak_bytes"] = self.hbm_static_peak_bytes
            out["hbm"] = hbm
        return out
