"""Jax-free request-journey analysis — merge fleet + per-replica trace
files into per-request latency attribution that reconciles EXACTLY with
the fleet summary and the goodput ledger's timed causes.

A *journey* is the complete cross-replica trace of one serving request
(:mod:`apex_tpu.serve.fleet` opens it): a ``journey`` root span with
``fleet_queue → attempt[replica=k] → backoff → hedge → failover →
terminal`` children, plus — nested under each attempt — the replica
scheduler's own ``request → queue/prefill/decode`` trace (PR 6), all
sharing one ``trace_id``. Single-scheduler runs root at ``request``
instead; the attribution here handles both.

The reconciliation contract (``tools/trace_explain.py`` exits 1 when it
fails — the reconciliation IS the test):

- fleet-plane spans are stamped from the SAME clock reads the fleet
  summary and the ``serve_failover`` events use, and carry the rounded
  ``seconds``/``ttft_s``/``latency_s`` values as attrs — so sums here
  equal the ledger's timed causes and the summary's percentiles
  *exactly*, not approximately;
- the winning attempt's replica spans obey the PR-6 identities
  (``queue + prefill + decode == latency`` within stamp rounding).

This module is deliberately **stdlib-only at import time** and loads its
one helper (:func:`percentile` from ``monitor/export.py``) by file path,
so ``tools/trace_explain.py`` can load *this* module by path and run on
a machine with no jax installed (the ``tools/metrics_merge.py``
pattern). Tier-1 asserts :data:`SERVE_TIMED_CAUSES` stays equal to the
serve subset of ``goodput.STALL_EVENTS`` — the one mapping, two homes,
cross-checked so they can never drift.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

# serve events whose records carry a ``seconds`` field of lost time —
# MUST mirror the serve_* subset of goodput.STALL_EVENTS (tier-1 holds
# them equal; goodput.py imports the package and cannot be loaded here)
SERVE_TIMED_CAUSES = {
    "serve_queue_wait": "serve_queue_wait",
    "serve_deadline_exceeded": "serve_deadline_exceeded",
    "serve_request_rejected": "serve_rejected",
    "serve_page_alloc_fail": "serve_page_alloc_fail",
    "serve_failover": "serve_failover",
    "serve_handoff_wait": "serve_handoff_wait",
}

# journey trace ids: "journey:<request_id>" (fleet) / "request:<request_id>"
JOURNEY_PREFIXES = ("journey:", "request:")

_EXPORT_MOD = None


def _export():
    """``monitor/export.py`` loaded by file path (never via the package —
    whose ``__init__`` pulls jax): the ONE nearest-rank percentile rule,
    not a second spelling that could silently diverge."""
    global _EXPORT_MOD
    if _EXPORT_MOD is None:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "export.py")
        spec = importlib.util.spec_from_file_location(
            "_apex_tpu_export_for_journey", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _EXPORT_MOD = mod
    return _EXPORT_MOD


def percentile(values: Iterable[float], p: float) -> float:
    """THE repo percentile (delegates to ``export.percentile`` by path)."""
    return _export().percentile(values, p)


# ----------------------------------------------------- trace-file loading

def read_chrome_trace(path: str) -> List[Dict[str, Any]]:
    """Parse a Chrome-trace file, tolerating the unterminated array a
    crashed run leaves behind (exactly what Perfetto tolerates)."""
    with open(path) as f:
        text = f.read().strip()
    if not text.startswith("["):
        raise ValueError(f"{path}: not a Chrome-trace JSON array")
    if text.endswith(","):
        text = text[:-1]
    if not text.endswith("]"):
        text += "]"
    return json.loads(text)


def spans_by_trace(records: List[Dict[str, Any]]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """Group span records (bus ``span_close`` records or a tracer's
    ``completed_records()``) by ``trace_id`` — one entry per request/step
    trace, spans in id (open) order."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        out.setdefault(str(rec.get("trace_id")), []).append(rec)
    for spans in out.values():
        spans.sort(key=lambda r: r.get("span_id") or 0)
    return out


def chrome_events_to_spans(events: List[Dict[str, Any]]
                           ) -> List[Dict[str, Any]]:
    """Invert :class:`~apex_tpu.monitor.trace.ChromeTraceWriter`: the
    ``"X"`` events of a trace file back into span records
    (``trace_id/span_id/parent_id/name/t0/t1/status/attrs``). ``ts`` is
    microseconds since the writer's process epoch, shared by every file
    the process wrote — fleet and per-replica timelines align."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args") or {})
        rec: Dict[str, Any] = {
            "trace_id": str(args.pop("trace_id", None)),
            "span_id": args.pop("span_id", None),
            "parent_id": args.pop("parent_id", None),
            "status": args.pop("status", "ok"),
            "name": ev.get("name", "?"),
            "t0": float(ev["ts"]) / 1e6,
            "t1": (float(ev["ts"]) + float(ev["dur"])) / 1e6,
            "attrs": args,
        }
        out.append(rec)
    return out


def load_trace_files(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """All span records across fleet + per-replica Chrome-trace files."""
    records: List[Dict[str, Any]] = []
    for path in paths:
        records.extend(chrome_events_to_spans(read_chrome_trace(path)))
    return records


def read_events_jsonl(path: str) -> List[Dict[str, Any]]:
    """Telemetry event-mirror lines (one JSON record per line; rows
    without an ``event`` key — step metrics — are skipped)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec, dict) and "event" in rec:
                out.append(rec)
    return out


def ledger_causes(events: Iterable[Mapping[str, Any]]
                  ) -> Tuple[Dict[str, float], Dict[str, int]]:
    """Recompute the goodput ledger's serve-side timed causes and event
    counts from a mirrored event stream — what an attached
    ``GoodputLedger`` would have accumulated, without importing it."""
    causes: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for rec in events:
        name = rec.get("event")
        counts[name] = counts.get(name, 0) + 1
        cause = SERVE_TIMED_CAUSES.get(name)
        if cause is not None:
            causes[cause] = causes.get(cause, 0.0) \
                + float(rec.get("seconds", 0.0))
    return causes, counts


# ----------------------------------------------------------- attribution

def _dur(span: Mapping[str, Any]) -> float:
    return float(span.get("t1", span.get("t0", 0.0))) \
        - float(span.get("t0", 0.0))


def attribute_journeys(records: List[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Per-request latency attribution from merged span records.

    Each journey contributes one row::

        {request_id, trace_id, state, finish_reason, replica,
         latency_s, ttft_s,
         fleet_queue_s, backoff_s, failover_lost_s,
         queue_s, prefill_s, decode_s,
         attempts, hedged, hedge_margin_s, failovers, migrations,
         retries, dominant, spans}

    ``latency_s``/``ttft_s``/``failover_lost_s`` come from span *attrs*
    (the exact rounded values the summary and ledger carry); the
    ``queue/prefill/decode`` components come from the winning attempt's
    replica spans (the PR-6 stamps). ``dominant`` names the largest
    component."""
    out: List[Dict[str, Any]] = []
    for trace_id, spans in sorted(spans_by_trace(records).items()):
        if not trace_id.startswith(JOURNEY_PREFIXES):
            continue
        roots = [s for s in spans if s.get("parent_id") is None]
        if not roots:
            continue    # partial capture (crashed writer): skip, loudly
        root = roots[0]
        attrs = root.get("attrs") or {}
        rid = str(attrs.get("request_id",
                            trace_id.split(":", 1)[1]))
        by_name: Dict[str, List[Dict[str, Any]]] = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        terminal = (by_name.get("terminal") or [None])[0]
        term_attrs = (terminal.get("attrs") if terminal else None) or {}
        # single-scheduler journeys: the scheduler root carries the exact
        # latency/ttft attrs and status; fleet ones carry them on the
        # fleet "terminal" span (copied from the winning record)
        state = term_attrs.get("state") or attrs.get("state")
        finish = term_attrs.get("finish_reason") \
            or attrs.get("finish_reason")
        replica = term_attrs.get("replica")
        latency = term_attrs.get("latency_s", attrs.get("latency_s"))
        ttft = term_attrs.get("ttft_s", attrs.get("ttft_s"))
        failover_spans = by_name.get("failover", [])
        failover_lost = sum(float((s.get("attrs") or {})
                                  .get("seconds", _dur(s)))
                            for s in failover_spans)
        failovers = sum((s.get("attrs") or {}).get("cause")
                        == "replica_dead" for s in failover_spans)
        migrations = sum((s.get("attrs") or {}).get("cause") == "drain"
                         for s in failover_spans)
        backoffs = by_name.get("backoff", [])
        hedges = by_name.get("hedge", [])
        attempts = by_name.get("attempt", [])
        # winning attempt: the one the terminal names; its replica
        # "request" root holds the PR-6 queue/prefill/decode stamps.
        # Single-scheduler journeys have exactly one "request" root.
        req_roots = by_name.get("request", [])
        win_root = None
        if replica is not None:
            # the LATEST attempt on the terminal replica wins (a journey
            # can revisit a replica: reject -> backoff -> re-dispatch)
            win_att = next((a for a in reversed(attempts)
                            if (a.get("attrs") or {}).get("replica")
                            == replica), None)
            if win_att is not None:
                win_root = next(
                    (r for r in req_roots
                     if r.get("parent_id") == win_att.get("span_id")),
                    None)
        if win_root is None and len(req_roots) == 1:
            win_root = req_roots[0]

        def _child(name: str) -> float:
            if win_root is None:
                return 0.0
            for s in by_name.get(name, []):
                if s.get("parent_id") == win_root.get("span_id"):
                    return _dur(s)
            return 0.0

        comp = {
            "fleet_queue_s": sum(_dur(s)
                                 for s in by_name.get("fleet_queue", [])),
            "backoff_s": sum(_dur(s) for s in backoffs),
            "failover_lost_s": failover_lost,
            "queue_s": _child("queue"),
            "prefill_s": _child("prefill"),
            "decode_s": _child("decode"),
        }
        dominant = max(comp, key=lambda k: comp[k]) if any(
            v > 0 for v in comp.values()) else "queue_s"
        row: Dict[str, Any] = {
            "request_id": rid, "trace_id": trace_id,
            "state": state, "finish_reason": finish, "replica": replica,
            "latency_s": float(latency) if latency is not None
            else _dur(root),
            "ttft_s": float(ttft) if ttft is not None else None,
            **{k: round(v, 6) for k, v in comp.items()},
            "attempts": max(len(attempts), 1 if win_root else 0),
            "hedged": bool(hedges),
            "hedge_margin_s": round(
                float(root.get("t1", 0.0))
                - float(hedges[0].get("t0", 0.0)), 6) if hedges else None,
            "failovers": failovers,
            "migrations": migrations,
            "retries": len(backoffs),
            "dominant": dominant,
            "spans": len(spans),
        }
        out.append(row)
    return out


def top_slowest(journeys: List[Dict[str, Any]], k: int = 10
                ) -> List[Dict[str, Any]]:
    return sorted(journeys, key=lambda j: j.get("latency_s") or 0.0,
                  reverse=True)[:k]


# --------------------------------------------------------- reconciliation

def _close(a: float, b: float, tol: float = 1e-9) -> bool:
    return abs(a - b) <= tol * max(1.0, abs(a), abs(b))


def reconcile(journeys: List[Dict[str, Any]],
              records: List[Dict[str, Any]],
              summary: Optional[Mapping[str, Any]] = None,
              causes: Optional[Mapping[str, float]] = None,
              counts: Optional[Mapping[str, int]] = None,
              *, stamp_tol_s: float = 2e-3,
              complete_capture: bool = True) -> List[str]:
    """Verify the attribution against the fleet summary and the ledger's
    timed causes. Returns human-readable mismatch strings (empty =
    reconciled).

    ``complete_capture=False`` (a head-sampled run) skips every check
    that needs ALL journeys present (counts, percentiles); the
    bad-outcome checks still hold — tail capture promises those journeys
    are always captured."""
    problems: List[str] = []
    by_trace = spans_by_trace(records)
    # per-journey internal sums: the PR-6 identities on the winning
    # attempt (span stamps round to the microsecond — stamp_tol covers
    # the rounding, nothing else)
    for j in journeys:
        if j["state"] != "completed" or j.get("latency_s") is None:
            continue
        parts = j["queue_s"] + j["prefill_s"] + j["decode_s"]
        if abs(parts - j["latency_s"]) > stamp_tol_s:
            problems.append(
                f"journey {j['request_id']}: queue+prefill+decode = "
                f"{parts:.6f}s does not sum to latency "
                f"{j['latency_s']:.6f}s")
    if summary is not None and complete_capture:
        ids = [j["request_id"] for j in journeys]
        if len(ids) != len(set(ids)):
            problems.append("duplicate journeys: a request traced twice")
        if len(journeys) != summary.get("requests"):
            problems.append(
                f"{len(journeys)} journeys != summary requests "
                f"{summary.get('requests')} (want exactly one fleet "
                f"trace per submitted request)")
        for state, key in (("completed", "completed"),
                           ("evicted", "evicted"),
                           ("rejected", "rejected")):
            got = sum(j["state"] == state for j in journeys)
            if got != summary.get(key, 0):
                problems.append(f"{got} {state} journeys != summary "
                                f"{key} {summary.get(key)}")
        got = sum(j["finish_reason"] == "deadline" for j in journeys)
        if got != summary.get("deadline_exceeded", 0):
            problems.append(
                f"{got} deadline journeys != summary deadline_exceeded "
                f"{summary.get('deadline_exceeded')}")
        for key, field in (("failovers", "failovers"),
                           ("migrations", "migrations"),
                           ("retries", "retries")):
            got = sum(j[field] for j in journeys)
            if key in summary and got != summary[key]:
                problems.append(f"{got} {field} spans != summary "
                                f"{key} {summary[key]}")
        if "hedge_fired" in summary:
            got = sum(j["hedged"] for j in journeys)
            if got != summary["hedge_fired"]:
                problems.append(f"{got} hedge spans != summary "
                                f"hedge_fired {summary['hedge_fired']}")
        # TTFT percentiles: journey ttfts are the EXACT rounded values
        # the summary computed its own percentiles from — equality is
        # bit-for-bit, not approximate
        ttfts = [j["ttft_s"] for j in journeys
                 if j.get("ttft_s") is not None]
        for p, key in ((0.50, "ttft_p50_ms"), (0.99, "ttft_p99_ms")):
            if key in summary:
                want = summary[key]
                got = round(percentile(ttfts, p) * 1e3, 3)
                if got != want:
                    problems.append(
                        f"journey ttft {key}: {got} != summary {want}")
    if causes is not None:
        # the failover ledger cause vs the failover spans' attrs: both
        # sum the SAME rounded per-event seconds — exact
        span_total = 0.0
        span_count = 0
        for spans in by_trace.values():
            for s in spans:
                if s["name"] == "failover":
                    span_total += float((s.get("attrs") or {})
                                        .get("seconds", 0.0))
                    span_count += 1
        want = float(causes.get("serve_failover", 0.0))
        if not _close(span_total, want):
            problems.append(
                f"failover span seconds sum {span_total:.6f} != ledger "
                f"serve_failover cause {want:.6f}")
        if counts is not None:
            n = counts.get("serve_failover", 0)
            if span_count != n:
                problems.append(f"{span_count} failover spans != "
                                f"{n} serve_failover events")
    return problems


# -------------------------------------------------- merged Perfetto view

def merged_perfetto(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One merged Chrome-trace event list with **one track per replica**
    (the ``track`` attr every fleet-run tracer stamps: ``fleet``,
    ``r0``..``rN``; untagged spans land on ``host``) — the side-by-side
    view of a request hopping replicas that per-file traces cannot
    show."""
    tracks: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    t_base = min((float(r.get("t0", 0.0)) for r in records),
                 default=0.0)
    for rec in sorted(records, key=lambda r: float(r.get("t0", 0.0))):
        attrs = rec.get("attrs") or {}
        track = str(attrs.get("track", "host"))
        tid = tracks.get(track)
        if tid is None:
            tid = tracks[track] = len(tracks) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": tid, "args": {"name": track}})
        args = {"trace_id": rec.get("trace_id"),
                "span_id": rec.get("span_id"),
                "parent_id": rec.get("parent_id"),
                "status": rec.get("status")}
        args.update(attrs)
        events.append({
            "ph": "X", "cat": "journey", "name": rec.get("name", "?"),
            "pid": 1, "tid": tid,
            "ts": round((float(rec["t0"]) - t_base) * 1e6, 3),
            "dur": round(_dur(rec) * 1e6, 3),
            "args": args,
        })
    return events
