"""Raw p2p communicator facade — TPU equivalent of ``nccl_p2p_cuda``
(apex/contrib/csrc/nccl_p2p/nccl_p2p.cpp:20-28: ``get_unique_nccl_id``,
``init_nccl_comm``, ``left_right_halo_exchange[_inplace]``, ``add_delay``).

On TPU the "communicator" is the mesh axis: rendezvous is
``jax.distributed.initialize`` + ``Mesh`` (apex_tpu.parallel.mesh), and the
p2p exchange is ppermute — or, for an explicit one-sided put matching the
reference's send/recv pairs, the Pallas remote-DMA ``p2p_shift``
re-exported below. ``add_delay`` — the reference's only fault-injection
hook (SURVEY §5) — is kept as a real latency injector for halo-exchange
race tests.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas.remote_copy import \
    peer_shift as p2p_shift  # noqa: F401  (one-sided RDMA send/recv pair)
from apex_tpu.parallel.halo import left_right_halo_exchange  # noqa: F401


def get_unique_nccl_id(n: int = 1):
    """Rendezvous-id parity shim: TPU meshes need no explicit unique id —
    jax.distributed.initialize coordinates hosts. Returns a placeholder."""
    return jnp.zeros((n, 128), jnp.uint8)


def init_nccl_comm(unique_id=None, my_rank: int = 0, num_ranks: int = 1,
                   axis_name: str = "spatial"):
    """Returns the axis name — the TPU 'communicator handle'."""
    return axis_name


def add_delay(delay_ms: int, x=None):
    """Latency injection for race/ overlap tests (nccl_p2p.cpp:28).

    Inside jit: burns ~delay proportional device cycles with a dependency on
    ``x`` so the scheduler cannot elide or reorder it. On host (x=None):
    sleeps.
    """
    if x is None:
        time.sleep(delay_ms / 1e3)
        return None
    # device-side: a serially-dependent scan the compiler can't shortcut
    iters = max(int(delay_ms * 1000), 1)

    def body(c, _):
        return c * 1.0000001 + 1e-7, None

    acc, _ = jax.lax.scan(body, jnp.float32(1.0), None, length=iters)
    return x + (acc * 0.0).astype(x.dtype)
