"""FP16_Optimizer — TPU equivalent of
``apex/contrib/optimizers/fp16_optimizer.py`` (248 LoC): the master-weight
fp32 wrapper of the deprecated contrib FusedAdam/SGD flow — flat fp32 master
buffer, loss-scale handling, fp16 model weights written back each step.

Here it wraps any apex_tpu stateful optimizer: keeps fp32 masters inside the
wrapped optimizer (``master_weights=True`` path), adds static/dynamic loss
scaling, and exposes the legacy ``backward(loss)``-less functional flow:
``params = opt.step(grads_fp16)``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.grad_scaler import DynamicGradScaler
from apex_tpu.multi_tensor.functional import tree_check_finite


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False):
        self.optimizer = init_optimizer
        if dynamic_loss_scale:
            self.scaler = DynamicGradScaler(**(dynamic_loss_args or {}))
        else:
            self.scaler = DynamicGradScaler(
                init_scale=static_loss_scale, growth_factor=1.0,
                backoff_factor=1.0, growth_interval=2 ** 31 - 1)
        self.scale_state = self.scaler.init()

    @property
    def loss_scale(self) -> float:
        return float(self.scale_state.scale)

    def scale_loss(self, loss):
        """Multiply the loss by the current scale (legacy
        ``optimizer.backward(loss)`` replacement: scale, then take grads)."""
        return self.scaler.scale(loss, self.scale_state)

    def step(self, grads: Any, lr=None):
        """grads are SCALED fp16/bf16 grads; unscale+check+step+update."""
        found_inf = tree_check_finite(grads)
        inv = 1.0 / self.scale_state.scale
        params = self.optimizer.step(grads, lr=lr, inv_scale=inv,
                                     found_inf=found_inf)
        self.scale_state = self.scaler.update(self.scale_state, found_inf)
        return params

    @property
    def parameters(self):
        return self.optimizer.parameters

    def state_dict(self):
        return {"optimizer": self.optimizer.state_dict(),
                "scale": float(self.scale_state.scale)}

    def load_state_dict(self, sd):
        self.optimizer.load_state_dict(sd["optimizer"])
        import jax.numpy as jnp
        self.scale_state = self.scale_state._replace(
            scale=jnp.float32(sd["scale"]))
