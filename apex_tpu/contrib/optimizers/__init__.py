"""contrib.optimizers — the deprecated pre-amp optimizer surface + the
distributed (ZeRO) optimizers (re-exported from apex_tpu.optimizers)."""

from apex_tpu.optimizers.distributed_fused_adam import (  # noqa: F401
    DistributedFusedAdam,
)
from apex_tpu.optimizers.distributed_fused_lamb import (  # noqa: F401
    DistributedFusedLAMB,
)
from apex_tpu.contrib.optimizers.fp16_optimizer import (  # noqa: F401
    FP16_Optimizer,
)
from apex_tpu.contrib.optimizers.fused_adam import (  # noqa: F401
    FusedAdam,
)
from apex_tpu.contrib.optimizers.fused_lamb import (  # noqa: F401
    FusedLAMB,
)
from apex_tpu.contrib.optimizers.fused_sgd import (  # noqa: F401
    FusedSGD,
)
