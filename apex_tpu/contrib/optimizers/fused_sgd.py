"""Deprecated-API contrib FusedSGD — TPU equivalent of
``apex/contrib/optimizers/fused_sgd.py`` (frontend of the legacy
``fused_adam_cuda``/SGD extensions; step signature :129).

Preserves the legacy explicit-grads flow: ``step(grads=...,
output_params=..., scale=...)`` with momentum / dampening / nesterov /
``wd_after_momentum``. Functional: returns updated params (and the
low-precision copies when requested) instead of mutating.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.logging import deprecated_warning


class FusedSGD:
    def __init__(self, params: Any, lr: float, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True):
        deprecated_warning(
            "apex_tpu.contrib.optimizers.FusedSGD is deprecated; use "
            "apex_tpu.optimizers.FusedSGD")
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        self.parameters = params
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self._first = True
        self.momentum_buffer = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def step(self, closure=None, grads: Any = None,
             output_params: Any = None, scale: float = 1.0,
             grad_norms=None, lr: Optional[float] = None,
             inv_scale=None, found_inf=False):
        """Legacy step; also accepts the modern
        ``step(grads, lr=..., inv_scale=..., found_inf=...)`` convention so
        FP16_Optimizer can wrap this class (see fused_adam.py)."""
        if closure is not None and not callable(closure):
            closure, grads = None, closure
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError("the deprecated flow passes grads explicitly")
        if inv_scale is not None:
            scale = 1.0 / inv_scale
        lr = self.lr if lr is None else lr
        mom, damp, wd = self.momentum, self.dampening, self.weight_decay
        nesterov, wd_after = self.nesterov, self.wd_after_momentum
        first = self._first
        # overflow-skipped steps must not consume the first-step flag
        # (reference: the kernel is never launched on overflow)
        try:
            if not bool(found_inf):
                self._first = False
        except Exception:
            self._first = False
        inv = 1.0 / float(scale) if not hasattr(scale, "dtype") \
            else 1.0 / scale
        keep = jnp.asarray(found_inf)

        def upd(p, g, buf):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32) * inv
            if wd and not wd_after:
                g32 = g32 + wd * p32
            if mom:
                buf_new = g32 if first else mom * buf + (1.0 - damp) * g32
                g32 = g32 + mom * buf_new if nesterov else buf_new
            else:
                buf_new = buf
            if wd and wd_after:
                g32 = g32 + wd * p32
            p_new = (p32 - lr * g32).astype(p.dtype)
            return jnp.where(keep, p, p_new), jnp.where(keep, buf, buf_new)

        # unzip on the params treedef (not is_leaf=tuple — see fused_adam)
        treedef = jax.tree_util.tree_structure(self.parameters)
        results = [
            upd(p, g, buf) for p, g, buf in zip(
                jax.tree_util.tree_leaves(self.parameters),
                jax.tree_util.tree_leaves(grads),
                jax.tree_util.tree_leaves(self.momentum_buffer))]
        self.parameters = jax.tree_util.tree_unflatten(
            treedef, [r[0] for r in results])
        self.momentum_buffer = jax.tree_util.tree_unflatten(
            treedef, [r[1] for r in results])

        if output_params is not None:
            out = jax.tree_util.tree_map(
                lambda p, o: p.astype(o.dtype), self.parameters,
                output_params)
            if loss is not None:
                return loss, self.parameters, out
            return self.parameters, out
        if loss is not None:
            return loss, self.parameters
        return self.parameters

    def state_dict(self):
        return {"momentum_buffer": self.momentum_buffer,
                "first": self._first}

    def load_state_dict(self, sd):
        self.momentum_buffer = sd["momentum_buffer"]
        self._first = bool(sd["first"])
