"""Deprecated-API contrib FusedSGD — TPU equivalent of
``apex/contrib/optimizers/fused_sgd.py`` (frontend of the legacy
``fused_adam_cuda``/SGD extensions; step signature :129).

Preserves the legacy explicit-grads flow: ``step(grads=...,
output_params=..., scale=...)`` with momentum / dampening / nesterov /
``wd_after_momentum``. Functional: returns updated params (and the
low-precision copies when requested) instead of mutating.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.logging import deprecated_warning


class FusedSGD:
    def __init__(self, params: Any, lr: float, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True):
        deprecated_warning(
            "apex_tpu.contrib.optimizers.FusedSGD is deprecated; use "
            "apex_tpu.optimizers.FusedSGD")
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        self.parameters = params
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self._first = True
        self._first_host = True  # see fused_adam.revive_state
        self.momentum_buffer = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def step(self, closure=None, grads: Any = None,
             output_params: Any = None, scale: float = 1.0,
             grad_norms=None, lr: Optional[float] = None,
             inv_scale=None, found_inf=False):
        """Legacy step; also accepts the modern
        ``step(grads, lr=..., inv_scale=..., found_inf=...)`` convention so
        FP16_Optimizer can wrap this class (see fused_adam.py)."""
        if closure is not None and not callable(closure):
            closure, grads = None, closure
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError("the deprecated flow passes grads explicitly")
        if inv_scale is not None:
            scale = 1.0 / inv_scale
        lr = self.lr if lr is None else lr
        mom, damp, wd = self.momentum, self.dampening, self.weight_decay
        nesterov, wd_after = self.nesterov, self.wd_after_momentum
        # overflow-skipped steps must not consume the first-step flag
        # (reference: the kernel is never launched on overflow). With a
        # traced found_inf (caller jits around this legacy class) the flag
        # itself goes data-dependent: it stays True only while every step so
        # far was skipped, and the first-step momentum init becomes a
        # where() select on it.
        from apex_tpu.contrib.optimizers.fused_adam import revive_state
        self._first = revive_state(self._first, self._first_host)
        fi = jnp.asarray(found_inf)
        traced = (isinstance(fi, jax.core.Tracer)
                  or isinstance(self._first, jax.core.Tracer))
        static_skip: Optional[bool]  # None = data-dependent
        if traced:
            static_skip = None
            first = jnp.asarray(self._first)
            self._first = jnp.logical_and(first, fi)
            self._first_host = False  # host mirror counts the step applied
        else:
            first = bool(self._first)
            if bool(fi):
                static_skip = True
            else:
                static_skip = False
                self._first = False
                self._first_host = False
        inv = 1.0 / float(scale) if not hasattr(scale, "dtype") \
            else 1.0 / scale
        keep = fi

        def upd(p, g, buf):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32) * inv
            if wd and not wd_after:
                g32 = g32 + wd * p32
            if mom:
                cont = mom * buf + (1.0 - damp) * g32
                if isinstance(first, bool):
                    buf_new = g32 if first else cont
                else:
                    buf_new = jnp.where(first, g32, cont)
                g32 = g32 + mom * buf_new if nesterov else buf_new
            else:
                buf_new = buf
            if wd and wd_after:
                g32 = g32 + wd * p32
            p_new = (p32 - lr * g32).astype(p.dtype)
            if static_skip is False:
                return p_new, buf_new
            return jnp.where(keep, p, p_new), jnp.where(keep, buf, buf_new)

        # unzip on the params treedef (not is_leaf=tuple — see fused_adam)
        treedef = jax.tree_util.tree_structure(self.parameters)
        results = [
            upd(p, g, buf) for p, g, buf in zip(
                jax.tree_util.tree_leaves(self.parameters),
                jax.tree_util.tree_leaves(grads),
                jax.tree_util.tree_leaves(self.momentum_buffer))]
        self.parameters = jax.tree_util.tree_unflatten(
            treedef, [r[0] for r in results])
        self.momentum_buffer = jax.tree_util.tree_unflatten(
            treedef, [r[1] for r in results])

        if output_params is not None:
            out = jax.tree_util.tree_map(
                lambda p, o: p.astype(o.dtype), self.parameters,
                output_params)
            if loss is not None:
                return loss, self.parameters, out
            return self.parameters, out
        if loss is not None:
            return loss, self.parameters
        return self.parameters

    def state_dict(self):
        from apex_tpu.contrib.optimizers.fused_adam import checkpoint_counter
        return {"momentum_buffer": self.momentum_buffer,
                "first": checkpoint_counter(self._first, self._first_host,
                                            "FusedSGD")}

    def load_state_dict(self, sd):
        self.momentum_buffer = sd["momentum_buffer"]
        self._first = self._first_host = bool(sd["first"])
