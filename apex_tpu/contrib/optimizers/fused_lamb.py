"""Deprecated-API contrib FusedLAMB — TPU equivalent of
``apex/contrib/optimizers/fused_lamb.py`` (frontend of the legacy
``fused_lamb_cuda.lamb`` kernel; step at :112, global-norm blend at
:134-146, the single multi-tensor launch at :196-230).

The legacy surface this preserves, completing the deprecated contrib trio
next to :mod:`fused_adam` / :mod:`fused_sgd`:

- construction-time hyperparameters identical to the reference
  (``adam_w_mode``, ``grad_averaging``, ``max_grad_norm`` default 1.0,
  ``eps`` default 1e-6);
- a GLOBAL gradient-norm clip computed across every parameter before the
  update — the reference computes per-dtype-list L2 norms and blends them
  (``sqrt(g32² + g16²)``, reference :134-146); on TPU there is one fused
  jnp reduction over all leaves, which is the same number;
- the per-tensor trust-ratio update of ``fused_lamb_cuda``: the update term
  is bias-corrected Adam direction (+ decoupled or L2 weight decay), and
  the applied step is ``lr · (‖p‖/‖update‖) · update`` with the ratio
  defined as 1 when either norm is zero;
- the deprecated explicit-grads flow shared by this trio:
  ``step(grads=..., output_params=..., scale=..., found_inf=...)`` —
  grads handed in explicitly, divided by ``scale`` first, with a
  low-precision copy of the updated params written out on request.

JAX is functional, so ``step`` RETURNS params (and ``(params,
output_params)`` when requested) instead of mutating.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.optimizers.fused_adam import (checkpoint_counter,
                                                    revive_state)
from apex_tpu.utils.logging import deprecated_warning


class FusedLAMB:
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 amsgrad: bool = False, adam_w_mode: bool = True,
                 grad_averaging: bool = True, set_grad_none: bool = True,
                 max_grad_norm: float = 1.0):
        deprecated_warning(
            "apex_tpu.contrib.optimizers.FusedLAMB is deprecated; use "
            "apex_tpu.optimizers.FusedLAMB")
        if amsgrad:
            raise RuntimeError(
                "FusedLAMB does not support the AMSGrad variant.")
        self.parameters = params
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self._step = 0
        self._step_host = 0  # trace-independent mirror, see revive_state
        f32 = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        self.exp_avg = jax.tree_util.tree_map(f32, params)
        self.exp_avg_sq = jax.tree_util.tree_map(f32, params)

    def step(self, closure=None, grads: Any = None,
             output_params: Any = None, scale: float = 1.0,
             grad_norms=None, lr: Optional[float] = None,
             inv_scale=None, found_inf=False):
        """Legacy step. ``grads`` handed in explicitly (possibly fp16 with
        fp32 params — the master flow), divided by ``scale`` before the
        update; ``grad_norms`` optionally supplies precomputed per-list
        norms (reference :134-146), otherwise the global norm is computed
        here. Returns updated params, or ``(params, output_params)`` when
        low-precision copies are requested. Also accepts the modern
        ``step(grads, lr=..., inv_scale=..., found_inf=...)`` convention so
        FP16_Optimizer can wrap this class (see fused_adam.py)."""
        if closure is not None and not callable(closure):
            closure, grads = None, closure
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError("the deprecated flow passes grads explicitly")
        if inv_scale is not None:
            scale = 1.0 / inv_scale
        # overflow-skipped steps never reach the kernel in the reference, so
        # the step count must not advance on them (same contract as the
        # legacy FusedAdam; see that module for the traced-found_inf story)
        self._step = revive_state(self._step, self._step_host)
        fi = jnp.asarray(found_inf)
        static_skip: Optional[bool]  # None = data-dependent
        if (isinstance(fi, jax.core.Tracer)
                or isinstance(self._step, jax.core.Tracer)):
            static_skip = None
            self._step = self._step + jnp.where(fi, 0, 1)
            self._step_host += 1
        elif bool(fi):
            static_skip = True
        else:
            static_skip = False
            self._step += 1
            self._step_host = int(self._step)
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        inv = 1.0 / scale if hasattr(scale, "dtype") else 1.0 / float(scale)

        g_leaves = jax.tree_util.tree_leaves(grads)
        # global grad norm over the UNSCALED grads (reference blends the
        # per-dtype multi_tensor_l2norm results :144-146); caller-supplied
        # grad_norms (per-list values) short-circuit the reduction
        if grad_norms is not None:
            gn = jnp.asarray(grad_norms, jnp.float32)
            global_norm = (jnp.sqrt(jnp.sum(gn ** 2)) if gn.ndim > 0
                           else gn) * inv
        else:
            global_norm = jnp.sqrt(sum(
                jnp.sum((g.astype(jnp.float32) * inv) ** 2)
                for g in g_leaves))
        # clip factor folded into the grad scale, as the kernel does with
        # its (global_grad_norm, max_grad_norm) arguments
        if self.max_grad_norm > 0:
            clip = jnp.where(global_norm > self.max_grad_norm,
                             global_norm / self.max_grad_norm, 1.0)
        else:
            clip = jnp.float32(1.0)

        if isinstance(self._step, jax.Array):
            step_for_bc = jnp.maximum(self._step, 1)
        else:
            step_for_bc = max(self._step, 1)
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step_for_bc
            bc2 = 1.0 - b2 ** step_for_bc
        else:
            bc1 = bc2 = 1.0
        beta3 = (1.0 - b1) if self.grad_averaging else 1.0
        eps, wd, adamw = self.eps, self.weight_decay, self.adam_w_mode
        keep = fi

        def upd(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32) * inv / clip
            if wd and not adamw:
                # L2 mode: decay joins the gradient before the moments
                g32 = g32 + wd * p32
            m_new = b1 * m + beta3 * g32
            v_new = b2 * v + (1.0 - b2) * g32 * g32
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if wd and adamw:
                # AdamW mode: decoupled decay joins the update term
                update = update + wd * p32
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(update * update))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / u_norm, 1.0)
            p_new = (p32 - lr * ratio * update).astype(p.dtype)
            if static_skip is False:
                return p_new, m_new, v_new
            return (jnp.where(keep, p, p_new),
                    jnp.where(keep, m, m_new), jnp.where(keep, v, v_new))

        treedef = jax.tree_util.tree_structure(self.parameters)
        results = [
            upd(p, g, m, v) for p, g, m, v in zip(
                jax.tree_util.tree_leaves(self.parameters), g_leaves,
                jax.tree_util.tree_leaves(self.exp_avg),
                jax.tree_util.tree_leaves(self.exp_avg_sq))]
        self.parameters = jax.tree_util.tree_unflatten(
            treedef, [r[0] for r in results])
        self.exp_avg = jax.tree_util.tree_unflatten(
            treedef, [r[1] for r in results])
        self.exp_avg_sq = jax.tree_util.tree_unflatten(
            treedef, [r[2] for r in results])

        if output_params is not None:
            out = jax.tree_util.tree_map(
                lambda p, o: p.astype(o.dtype), self.parameters,
                output_params)
            if loss is not None:
                return loss, self.parameters, out
            return self.parameters, out
        if loss is not None:
            return loss, self.parameters
        return self.parameters

    def state_dict(self):
        return {"step": checkpoint_counter(self._step, self._step_host,
                                           "FusedLAMB"),
                "exp_avg": self.exp_avg, "exp_avg_sq": self.exp_avg_sq}

    def load_state_dict(self, sd):
        self._step = self._step_host = int(sd["step"])
        self.exp_avg = sd["exp_avg"]
        self.exp_avg_sq = sd["exp_avg_sq"]
