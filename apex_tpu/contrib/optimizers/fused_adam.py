"""Deprecated-API contrib FusedAdam — TPU equivalent of
``apex/contrib/optimizers/fused_adam.py`` (the frontend of the legacy
``fused_adam_cuda`` extension, apex/contrib/csrc/optimizers/fused_adam_cuda.cpp:92-104).

The legacy surface this preserves (used by FP16_Optimizer and
DistributedFusedLAMB in the reference):

- ``step(grads=..., output_params=..., scale=..., grad_norms=...)`` — grads
  handed in explicitly (possibly fp16 with fp32 params = master flow), a
  low-precision copy of the updated params written out, and a divisor
  ``scale`` applied to grads before the update (the amp pre-unscale flow).
- ``eps_inside_sqrt``: denom = sqrt(v_hat + eps) instead of sqrt(v_hat)+eps.
- ``max_grad_norm``: global-norm clip folded into the combined scale, as the
  CUDA kernel does via its ``global_grad_norm`` argument.

JAX is functional, so ``step`` RETURNS ``params`` (and ``(params,
output_params)`` when output params are requested) instead of mutating.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.logging import deprecated_warning


def revive_state(val, fallback):
    """Recover a legacy-optimizer SCALAR state leaked out of a dead trace.

    These stateful classes are eager-API by contract. When a caller jits
    around a persistent optimizer, a ``found_inf``-traced step leaves
    tracers in ``self._step``/``self._first`` (and in the moment trees —
    the persistent-object-under-jit pattern is NOT supported and still
    raises UnexpectedTracerError at the moment leaves; construct the
    optimizer inside the trace, or use the modern functional API). This
    helper keeps the step counter and ``state_dict`` checkpointing sane
    regardless: it detects a dead tracer by probing it with a no-op add and
    falls back to the host-side mirror, which counts every traced step as
    applied — the best a host counter can know."""
    if not isinstance(val, jax.core.Tracer):
        return val
    try:
        val + 0  # live tracers (same active trace) tolerate ops; dead raise
        return val
    except Exception:
        return fallback


def checkpoint_counter(val, fallback, cls_name: str):
    """``revive_state`` for state_dict(): additionally WARNS when the dead-
    tracer fallback fires, because the host mirror counts TRACED calls, not
    executions — a re-executed jitted step undercounts and the checkpoint's
    bias correction goes wrong. Shared by the legacy contrib trio."""
    out = revive_state(val, fallback)
    if isinstance(val, jax.core.Tracer) and not isinstance(
            out, jax.core.Tracer):
        import warnings

        warnings.warn(
            f"{cls_name} step counter leaked out of a dead trace; "
            "state_dict() falls back to the host mirror, which counts "
            "traced calls (not executions) — checkpoint bias correction "
            "may be wrong. The persistent-optimizer-under-jit pattern is "
            "unsupported; construct the optimizer inside the trace or use "
            "the modern functional API.", RuntimeWarning, stacklevel=3)
    return out


def _adam_denom(v_new, eps, eps_mode):
    return (jnp.sqrt(v_new + eps) if eps_mode == 0
            else jnp.sqrt(v_new) + eps)


def _bc_step_size(lr, betas, step, bias_correction):
    """Bias-correction folded into step_size, as the legacy kernel's host
    side does (fused_adam_cuda_kernel.cu:182-189). Shared by step and
    undo_step so the two can never desynchronize."""
    if not bias_correction:
        return lr
    b1, b2 = betas
    bc1 = 1.0 - b1 ** step
    bc2 = 1.0 - b2 ** step
    return lr * (bc2 ** 0.5) / bc1


def reversible_adam(params: Any, grads: Any, exp_avg: Any, exp_avg_sq: Any,
                    *, step_size, betas=(0.9, 0.999), eps: float = 1e-8,
                    eps_inside_sqrt: bool = False, weight_decay: float = 0.0,
                    grad_scale: float = 1.0, output_dtype=None):
    """``reversible_adam`` (fused_adam_cuda_kernel.cu:421-494): an Adam step
    whose per-ELEMENT finite check leaves non-finite lanes untouched (the
    regular kernel skips the whole step), so the step can later be exactly
    reverted by :func:`maybe_adam_undo` given the same grads. Moments and
    the update run in fp32; ``step_size`` is the bias-corrected lr (the
    legacy kernel folds correction into step_size). Returns
    ``(params, exp_avg, exp_avg_sq, overflow[, params_copy])`` —
    ``params_copy`` (the low-precision copy-out, ``p_copy`` in the kernel)
    only when ``output_dtype`` is given; ``overflow`` is a scalar bool
    (the kernel signals it by writing inf into ``p_copy[0]``)."""
    b1, b2 = betas
    eps_mode = 0 if eps_inside_sqrt else 1
    leaves = list(zip(jax.tree_util.tree_leaves(params),
                      jax.tree_util.tree_leaves(grads),
                      jax.tree_util.tree_leaves(exp_avg),
                      jax.tree_util.tree_leaves(exp_avg_sq)))
    outs, overflow = [], jnp.asarray(False)
    for p, g, m, v in leaves:
        p32 = p.astype(jnp.float32)
        s = g.astype(jnp.float32) / grad_scale
        fin = jnp.isfinite(s)
        s0 = jnp.where(fin, s, 0.0)
        m_new = b1 * m + (1.0 - b1) * s0
        v_new = b2 * v + (1.0 - b2) * s0 * s0
        upd = m_new / _adam_denom(v_new, eps, eps_mode) + weight_decay * p32
        p_new = p32 - step_size * upd
        outs.append((jnp.where(fin, p_new, p32).astype(p.dtype),
                     jnp.where(fin, m_new, m), jnp.where(fin, v_new, v)))
        overflow = overflow | jnp.any(~fin)
    treedef = jax.tree_util.tree_structure(params)
    unflat = lambda i: jax.tree_util.tree_unflatten(  # noqa: E731
        treedef, [o[i] for o in outs])
    p_out, m_out, v_out = unflat(0), unflat(1), unflat(2)
    if output_dtype is not None:
        copy = jax.tree_util.tree_map(
            lambda p: p.astype(output_dtype), p_out)
        return p_out, m_out, v_out, overflow, copy
    return p_out, m_out, v_out, overflow


def maybe_adam_undo(params: Any, grads: Any, exp_avg: Any, exp_avg_sq: Any,
                    *, step_size, betas=(0.9, 0.999), eps: float = 1e-8,
                    eps_inside_sqrt: bool = False, weight_decay: float = 0.0,
                    grad_scale: float = 1.0, overflow_flag=True):
    """``maybe_adam_undo`` (fused_adam_cuda_kernel.cu:497-560): exact fp32
    inverse of :func:`reversible_adam` given the SAME grads — the
    step-undo the reference's DistributedFusedLAMB grad-accumulation flow
    uses to revert an optimistically-applied step once a late global
    overflow is detected. ``overflow_flag`` gates the whole undo (the
    kernel early-outs when the flag is 0); non-finite grad lanes were never
    applied, so they are left untouched here too. v is clamped at 0 against
    round-off when reverting the very first step (kernel :549-551)."""
    b1, b2 = betas
    eps_mode = 0 if eps_inside_sqrt else 1
    flag = jnp.asarray(overflow_flag)
    leaves = list(zip(jax.tree_util.tree_leaves(params),
                      jax.tree_util.tree_leaves(grads),
                      jax.tree_util.tree_leaves(exp_avg),
                      jax.tree_util.tree_leaves(exp_avg_sq)))
    outs = []
    for p, g, m, v in leaves:
        p32 = p.astype(jnp.float32)
        s = g.astype(jnp.float32) / grad_scale
        fin = jnp.isfinite(s)
        s0 = jnp.where(fin, s, 0.0)
        # denom uses the POST-step v (what the forward divided by)
        denom = _adam_denom(v, eps, eps_mode)
        p_prev = (p32 + step_size * (m / denom)) / \
            (1.0 - step_size * weight_decay)
        m_prev = (m - (1.0 - b1) * s0) / b1
        v_prev = jnp.maximum((v - (1.0 - b2) * s0 * s0) / b2, 0.0)
        do = flag & fin
        outs.append((jnp.where(do, p_prev, p32).astype(p.dtype),
                     jnp.where(do, m_prev, m), jnp.where(do, v_prev, v)))
    treedef = jax.tree_util.tree_structure(params)
    unflat = lambda i: jax.tree_util.tree_unflatten(  # noqa: E731
        treedef, [o[i] for o in outs])
    return unflat(0), unflat(1), unflat(2)


def strided_check_finite(params: Any, stride: int = 1,
                         clear_overflow_first: bool = True,
                         overflow_flag=False):
    """``strided_check_finite`` (fused_adam_cuda_kernel.cu:331-378): scan
    every ``stride``-th element of the (low-precision) param copy for
    non-finite values, returning the overflow flag. The reference uses it
    as a cheap sampled overflow detector over ``p_copy`` between steps.
    ``clear_overflow_first=False`` ORs into the incoming flag instead of
    resetting it."""
    flag = jnp.asarray(False if clear_overflow_first else overflow_flag)
    for p in jax.tree_util.tree_leaves(params):
        sampled = p.reshape(-1)[::stride].astype(jnp.float32)
        flag = flag | jnp.any(~jnp.isfinite(sampled))
    return flag


def maybe_cast(params_in: Any, params_out: Any, overflow_flag=False):
    """``maybe_cast`` / ``maybe_cast_mt`` (fused_adam_cuda_kernel.cu:381-
    418): cast ``params_in`` into ``params_out``'s dtypes UNLESS the
    overflow flag is set (the kernel early-outs, leaving ``p_out``
    untouched — the master->model copy-out is skipped on overflowed
    steps). Returns the new ``params_out`` tree."""
    flag = jnp.asarray(overflow_flag)
    return jax.tree_util.tree_map(
        lambda pi, po: jnp.where(flag, po, pi.astype(po.dtype)),
        params_in, params_out)


class FusedAdam:
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, eps_inside_sqrt: bool = False,
                 weight_decay: float = 0.0, max_grad_norm: float = 0.0,
                 amsgrad: bool = False, use_mt: bool = False,
                 amp_scale_adjustment: float = 1.0):
        deprecated_warning(
            "apex_tpu.contrib.optimizers.FusedAdam is deprecated; use "
            "apex_tpu.optimizers.FusedAdam")
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")
        self.parameters = params
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.eps_mode = 0 if eps_inside_sqrt else 1
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self._amp_scale_adjustment = amp_scale_adjustment
        self._step = 0
        self._step_host = 0  # trace-independent mirror, see revive_state
        f32 = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        self.exp_avg = jax.tree_util.tree_map(f32, params)
        self.exp_avg_sq = jax.tree_util.tree_map(f32, params)

    def step(self, closure=None, grads: Any = None,
             output_params: Any = None, scale: float = 1.0,
             grad_norms=None, lr: Optional[float] = None,
             inv_scale=None, found_inf=False):
        """Legacy step. ``grads`` may be lower precision than params (master
        flow); ``scale`` divides grads first; returns updated params, or
        ``(params, output_params)`` when ``output_params`` is not None
        (a pytree/list matching params whose dtype is reused for the
        low-precision copy-out).

        Also accepts the package's modern calling convention
        (``step(grads, lr=..., inv_scale=..., found_inf=...)``) so
        FP16_Optimizer can wrap this class like the reference pairing:
        a non-callable first positional is treated as ``grads``."""
        if closure is not None and not callable(closure):
            closure, grads = None, closure
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError("the deprecated flow passes grads explicitly")
        if inv_scale is not None:
            scale = 1.0 / inv_scale
        # reference flow: an overflow step never reaches the kernel, so the
        # step count must not advance on skipped steps. For a concrete
        # found_inf this is a host-side int; for a traced one (caller jits
        # around this legacy class) the count becomes a device scalar
        # advanced by where(), so bias correction stays consistent with the
        # number of APPLIED updates within the trace; revive_state recovers
        # persistent objects whose counter outlived that trace.
        self._step = revive_state(self._step, self._step_host)
        fi = jnp.asarray(found_inf)
        static_skip: Optional[bool]  # None = data-dependent
        if (isinstance(fi, jax.core.Tracer)
                or isinstance(self._step, jax.core.Tracer)):
            static_skip = None
            self._step = self._step + jnp.where(fi, 0, 1)
            self._step_host += 1
        elif bool(fi):
            static_skip = True
        else:
            static_skip = False
            self._step += 1
            self._step_host = int(self._step)
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas

        combined = self._combined_scale(scale, grad_norms)

        # legacy kernel folds bias correction into step_size and keeps v raw
        # (fused_adam_cuda_kernel.cu:182-189). max(step, 1): when the very
        # first call is an overflow-skip, _step is still 0 and the (discarded)
        # update must not divide by bc1 == 0
        if isinstance(self._step, jax.Array):
            step_for_bc = jnp.maximum(self._step, 1)
        else:
            step_for_bc = max(self._step, 1)
        step_size = _bc_step_size(lr, self.betas, step_for_bc,
                                  self.bias_correction)

        eps, wd, eps_mode = self.eps, self.weight_decay, self.eps_mode

        keep = fi

        def upd(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32) / combined
            m_new = b1 * m + (1.0 - b1) * g32
            v_new = b2 * v + (1.0 - b2) * g32 * g32
            denom = _adam_denom(v_new, eps, eps_mode)
            # decay joins the UPDATE term, after the moments
            # (fused_adam_cuda_kernel.cu:58)
            update = m_new / denom + wd * p32
            p32 = p32 - step_size * update
            if static_skip is False:
                # predicate statically clean — no full-tensor selects
                return p32.astype(p.dtype), m_new, v_new
            return (jnp.where(keep, p, p32.astype(p.dtype)),
                    jnp.where(keep, m, m_new), jnp.where(keep, v, v_new))

        # unzip by flattening on the PARAMS treedef (a tree_map with
        # is_leaf=tuple would mis-fire when the params container itself is
        # a tuple)
        treedef = jax.tree_util.tree_structure(self.parameters)
        results = [
            upd(p, g, m, v) for p, g, m, v in zip(
                jax.tree_util.tree_leaves(self.parameters),
                jax.tree_util.tree_leaves(grads),
                jax.tree_util.tree_leaves(self.exp_avg),
                jax.tree_util.tree_leaves(self.exp_avg_sq))]
        self.parameters = jax.tree_util.tree_unflatten(
            treedef, [r[0] for r in results])
        self.exp_avg = jax.tree_util.tree_unflatten(
            treedef, [r[1] for r in results])
        self.exp_avg_sq = jax.tree_util.tree_unflatten(
            treedef, [r[2] for r in results])

        if output_params is not None:
            out = jax.tree_util.tree_map(
                lambda p, o: p.astype(o.dtype), self.parameters,
                output_params)
            if loss is not None:
                return loss, self.parameters, out
            return self.parameters, out
        if loss is not None:
            return loss, self.parameters
        return self.parameters

    def _combined_scale(self, scale, grad_norms):
        """scale·amp-adjustment·clip — the divisor the kernel applies to
        grads (``combined_scale`` in fused_adam.py:119-126 of the
        reference). Shared by step and undo_step."""
        combined = float(scale) * self._amp_scale_adjustment
        if self.max_grad_norm > 0 and grad_norms is not None:
            gnorm = jnp.asarray(grad_norms, jnp.float32)
            if gnorm.ndim > 0:
                gnorm = jnp.sqrt(jnp.sum(gnorm ** 2))
            clip = gnorm / (combined * self.max_grad_norm)
            combined = combined * jnp.maximum(clip, 1.0)
        return combined

    def undo_step(self, grads: Any, scale: float = 1.0,
                  grad_norms=None, lr: Optional[float] = None,
                  overflow=True):
        """Revert the most recent applied ``step`` given the SAME grads —
        the class-level surface over :func:`maybe_adam_undo` (the reference
        flow: DistributedFusedLAMB applies optimistically during grad
        accumulation, then undoes when a late global overflow lands).
        Pass the same ``scale``/``grad_norms``/``lr`` the forward step got
        (``grad_norms`` matters when ``max_grad_norm`` clipping was active —
        the combined divisor must match for the inverse to be exact).
        Decrements the step counter so bias correction realigns. Exact in
        fp32 (params/moments fp32); low-precision params round-trip to
        their dtype's resolution."""
        if isinstance(self._step, jax.core.Tracer) or self._step < 1:
            raise RuntimeError("undo_step needs a concrete applied step")
        lr = self.lr if lr is None else lr
        step_size = _bc_step_size(lr, self.betas, self._step,
                                  self.bias_correction)
        self.parameters, self.exp_avg, self.exp_avg_sq = maybe_adam_undo(
            self.parameters, grads, self.exp_avg, self.exp_avg_sq,
            step_size=step_size, betas=self.betas, eps=self.eps,
            eps_inside_sqrt=(self.eps_mode == 0),
            weight_decay=self.weight_decay,
            grad_scale=self._combined_scale(scale, grad_norms),
            overflow_flag=overflow)
        self._step -= 1
        self._step_host = int(self._step)
        return self.parameters

    def state_dict(self):
        """Checkpoint state. ``step`` is exact for the supported eager flow;
        see :func:`checkpoint_counter` for the dead-tracer fallback."""
        return {"step": checkpoint_counter(self._step, self._step_host,
                                           "FusedAdam"),
                "exp_avg": self.exp_avg, "exp_avg_sq": self.exp_avg_sq}

    def load_state_dict(self, sd):
        self._step = self._step_host = int(sd["step"])
        self.exp_avg = sd["exp_avg"]
        self.exp_avg_sq = sd["exp_avg_sq"]
