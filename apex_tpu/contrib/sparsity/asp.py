"""ASP (Automatic SParsity) — TPU equivalent of
``apex/contrib/sparsity/asp.py`` (:27 class; optimizer-step mask
re-application :269-313; ``prune_trained_model`` one-call API :431; mask
state across checkpoints exercised by
apex/contrib/sparsity/test/checkpointing_test_part1.py).

JAX shape: masks are a pytree of booleans next to the params; pruning is
``params * mask``; the reference's monkey-patched optimizer step becomes
``asp.wrap_optimizer`` (re-apply masks after each step) or calling
``asp.apply_masks`` inside a jitted train step — both keep updates inside the
mask support exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask


def _default_should_prune(path: str, leaf) -> bool:
    # prune 2D+ weights (linear/conv kernels), skip biases/norm scales
    return hasattr(leaf, "ndim") and leaf.ndim >= 2


class ASP:
    """Stateful facade mirroring the reference classmethod API."""

    def __init__(self):
        self.masks: Optional[Any] = None
        self.pattern = "m4n2_1d"

    # -- reference API ------------------------------------------------------
    def init_model_for_pruning(self, params: Any,
                               mask_calculator: str = "m4n2_1d",
                               verbosity: int = 2,
                               whitelist=None,
                               allow_recompute_mask: bool = False,
                               custom_layer_dict=None,
                               allow_permutation: bool = False):
        """≈ ASP.init_model_for_pruning (asp.py:88). Records the pattern and
        the prunable-leaf structure."""
        self.pattern = mask_calculator
        self.masks = jax.tree_util.tree_map(
            lambda p: jnp.ones(p.shape, bool), params)
        return self

    def compute_sparse_masks(self, params: Any):
        """≈ ASP.compute_sparse_masks (asp.py:269): (re)compute 2:4 masks."""
        def leaf_mask(p):
            if _default_should_prune("", p):
                return create_mask(p, self.pattern)
            return jnp.ones(p.shape, bool)

        self.masks = jax.tree_util.tree_map(leaf_mask, params)
        return self.masks

    def apply_masks(self, params: Any) -> Any:
        """Zero out pruned weights (jittable)."""
        assert self.masks is not None, "compute_sparse_masks first"
        return jax.tree_util.tree_map(
            lambda p, m: jnp.where(m, p, jnp.zeros_like(p)),
            params, self.masks)

    def prune_trained_model(self, params: Any, optimizer=None) -> Any:
        """≈ ASP.prune_trained_model (asp.py:431): one call = init + compute
        + apply. Returns pruned params (optimizer wrapping via
        ``wrap_optimizer``)."""
        self.init_model_for_pruning(params, self.pattern)
        self.compute_sparse_masks(params)
        pruned = self.apply_masks(params)
        if optimizer is not None:
            self.wrap_optimizer(optimizer)
        return pruned

    def wrap_optimizer(self, optimizer):
        """Re-apply masks after every optimizer step (the reference's step
        monkey-patch, asp.py:269-313). Uses the optimizer's
        ``set_parameters`` protocol so flat/ZeRO optimizers push the masked
        values into their internal master buffers too (otherwise the
        unmasked master would be the source of truth and resurrect pruned
        weights)."""
        asp = self
        orig_step = optimizer.step

        def step(grads, *a, **kw):
            params = orig_step(grads, *a, **kw)
            pruned = asp.apply_masks(params)
            if hasattr(optimizer, "set_parameters"):
                optimizer.set_parameters(pruned)
            else:
                optimizer._params = pruned
            return pruned

        optimizer.step = step
        return optimizer

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self):
        return {"pattern": self.pattern,
                "masks": jax.tree_util.tree_map(np.asarray, self.masks)}

    def load_state_dict(self, sd):
        self.pattern = sd["pattern"]
        self.masks = jax.tree_util.tree_map(jnp.asarray, sd["masks"])
