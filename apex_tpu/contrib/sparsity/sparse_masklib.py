"""2:4 structured-sparsity mask calculators — TPU equivalent of
``apex/contrib/sparsity/sparse_masklib.py`` (``m4n2_1d`` family).

Mask logic is device-agnostic (SURVEY §7 step 9: TPUs don't accelerate 2:4 —
functional parity is the goal). Patterns: ``mMnN_1d`` keeps the N
largest-magnitude elements of every M consecutive weights along the input
dim; ``m4n2_2d`` applies the 1d rule on 4x4 tiles in both directions
(best-effort parity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _mn_1d_mask(w2: jax.Array, m: int, n: int) -> jax.Array:
    """w2: (rows, cols) with cols % m == 0. Keep n-of-m per group by |w|."""
    rows, cols = w2.shape
    g = w2.reshape(rows, cols // m, m)
    mag = jnp.abs(g.astype(_f32))
    # rank within each group of m; keep the top n
    order = jnp.argsort(mag, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = ranks >= (m - n)
    return mask.reshape(rows, cols)


def create_mask(tensor: jax.Array, pattern: str = "m4n2_1d") -> jax.Array:
    """Boolean keep-mask with the same shape as ``tensor``.

    Convention matches the reference: the mask is computed over the 2D view
    (out_features, in_features·k) with groups along the last axis.
    """
    shape = tensor.shape
    w2 = tensor.reshape(shape[0], -1) if tensor.ndim > 1 \
        else tensor.reshape(1, -1)
    if pattern.endswith("_1d"):
        m = int(pattern[1])
        n = int(pattern[3])
        if w2.shape[1] % m != 0:
            return jnp.ones(shape, bool)  # unprunable layer (ref skips too)
        mask = _mn_1d_mask(w2, m, n)
    elif pattern == "m4n2_2d" or pattern.endswith("_2d"):
        m = int(pattern[1])
        n = int(pattern[3])
        if w2.shape[1] % m != 0 or w2.shape[0] % m != 0:
            return jnp.ones(shape, bool)
        row_mask = _mn_1d_mask(w2, m, n)
        col_mask = _mn_1d_mask(w2.T, m, n).T
        mask = row_mask & col_mask
        # guarantee at least the 1d pattern survives
        mask = jnp.where(jnp.sum(mask) == 0, row_mask, mask)
    else:
        raise ValueError(f"unknown sparsity pattern {pattern!r}")
    return mask.reshape(shape)
