"""Channel-permutation search for 2:4 sparsity — TPU equivalent of
``apex/contrib/sparsity/permutation_lib.py`` + the
``permutation_search_kernels`` package (exhaustive_search.py,
channel_swap.py, permutation_utilities.py) and the
``permutation_search_cuda`` kernels.

Goal: permute a weight's input channels so the 2:4 mask preserves more
magnitude (and thus accuracy). Both reference search strategies are
implemented, vectorized in numpy (the search is a host-side preprocessing
pass — the reference only uses CUDA to batch-evaluate candidate
permutations, which numpy broadcasting does here):

- **bounded-exhaustive** (ref exhaustive_search.py ``Exhaustive_Search``):
  slide a window of ``stripe_group_size`` columns over all stripe
  combinations; within a window, evaluate EVERY canonical permutation
  (sorted groups of 4, groups sorted — the reference's duplicate
  elimination, ``is_canonical``) in one batched magnitude computation; take
  the best; repeat passes until no window improves; then bounded random
  "escape" swaps (ref ``escape_attempts``) to leave local minima.
- **greedy channel swaps** (ref channel_swap.py): build the full
  improvement map over all cross-stripe column-pair swaps, apply the best
  positive entry, recompute, until convergence (the deterministic variant
  of the reference's progressive random search).

All candidate evaluation reduces to ``sum_after_2_to_4`` (ref
permutation_utilities.py:56): the magnitude kept by ideal 2:4 pruning =
sum of the top-2 |w| in every row×4-column stripe.
"""

from __future__ import annotations

import itertools
from typing import Optional, Tuple

import numpy as np

_window_perm_cache: dict = {}


def sum_after_2_to_4(matrix: np.ndarray) -> float:
    """Magnitude kept by 2:4 pruning (top-2 |w| per row per 4-col stripe)."""
    a = np.abs(matrix.reshape(matrix.shape[0], -1, 4))
    return float(np.sum(np.sort(a, axis=2)[:, :, 2:]))


def _stripe_kept(matrix: np.ndarray) -> np.ndarray:
    """Kept magnitude per stripe: (num_stripes,)."""
    a = np.abs(matrix.reshape(matrix.shape[0], -1, 4))
    return np.sort(a, axis=2)[:, :, 2:].sum(axis=(0, 2))


def _unique_group_partitions(cols, m):
    """All partitions of ``cols`` into sorted groups of ``m`` with groups
    sorted by first element — the reference's canonical-form enumeration
    (exhaustive_search.py ``is_canonical``: column order within a stripe and
    stripe order don't change the 2:4 magnitude, so only one representative
    per equivalence class is evaluated)."""
    if not cols:
        yield ()
        return
    first = cols[0]
    rest = cols[1:]
    for grp_rest in itertools.combinations(rest, m - 1):
        grp = (first,) + grp_rest
        taken = set(grp_rest)
        remaining = tuple(c for c in rest if c not in taken)
        for tail in _unique_group_partitions(remaining, m):
            yield (grp,) + tail


def canonical_window_permutations(c: int, m: int = 4) -> np.ndarray:
    """(P, c) array of canonical permutations of ``c`` columns in groups of
    ``m`` (ref ``generate_all_unique_combinations``; P = c!/((m!)^g · g!))."""
    key = (c, m)
    if key not in _window_perm_cache:
        perms = [np.fromiter(itertools.chain.from_iterable(p), np.int64)
                 for p in _unique_group_partitions(tuple(range(c)), m)]
        _window_perm_cache[key] = np.stack(perms)
    return _window_perm_cache[key]


def _best_window_perm(matrix: np.ndarray, window_cols: np.ndarray
                      ) -> Tuple[float, np.ndarray]:
    """Batched exhaustive evaluation of one window (ref search_matrix, the
    role of the CUDA ``try_permutations_on_matrix`` kernel)."""
    perms = canonical_window_permutations(len(window_cols))
    sub = matrix[:, window_cols]                       # (R, W)
    cand = sub[:, perms]                               # (R, P, W)
    a = np.abs(cand.reshape(cand.shape[0], perms.shape[0], -1, 4))
    kept = np.sort(a, axis=3)[:, :, :, 2:].sum(axis=(0, 2, 3))  # (P,)
    best = int(np.argmax(kept))
    return float(kept[best]), perms[best]


def exhaustive_search(matrix: np.ndarray, stripe_group_size: int = 8,
                      escape_attempts: int = 100,
                      seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Bounded-exhaustive permutation search (ref ``Exhaustive_Search``).

    Returns ``(permuted_matrix, permutation)``.
    """
    matrix = np.array(matrix, dtype=np.float64, copy=True)
    r, c = matrix.shape
    assert c % 4 == 0
    num_stripes = c // 4
    stripes_per_window = stripe_group_size // 4
    perm = np.arange(c)
    rng = np.random.default_rng(seed)

    if num_stripes < stripes_per_window:
        return matrix, perm

    improved = True
    while improved:
        improved = False
        for combo in itertools.combinations(range(num_stripes),
                                            stripes_per_window):
            window_cols = np.concatenate(
                [np.arange(s * 4, s * 4 + 4) for s in combo])
            base = sum_after_2_to_4(matrix[:, window_cols])
            best_kept, best_p = _best_window_perm(matrix, window_cols)
            if best_kept > base + 1e-9:
                new_cols = window_cols[best_p]
                matrix[:, window_cols] = matrix[:, new_cols]
                perm[window_cols] = perm[new_cols]
                improved = True
        if not improved and escape_attempts > 0:
            # bounded escape (ref escape_attempts): random cross-stripe
            # swaps accepted only on improvement re-arm the window passes
            for _ in range(escape_attempts):
                i, j = (int(x) for x in rng.integers(0, c, 2))
                if i // 4 == j // 4:
                    continue
                si, sj = i // 4, j // 4
                two = np.concatenate([np.arange(si * 4, si * 4 + 4),
                                      np.arange(sj * 4, sj * 4 + 4)])
                kept0 = sum_after_2_to_4(matrix[:, two])
                matrix[:, [i, j]] = matrix[:, [j, i]]
                kept1 = sum_after_2_to_4(matrix[:, two])
                if kept1 > kept0 + 1e-9:
                    perm[[i, j]] = perm[[j, i]]
                    improved = True
                else:
                    matrix[:, [i, j]] = matrix[:, [j, i]]  # revert
            escape_attempts = 0  # one escape round per convergence
    return matrix, perm


def greedy_channel_swaps(matrix: np.ndarray, max_rounds: int = 100
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic greedy swap search (ref channel_swap.py): full
    cross-stripe pair improvement map, apply best, repeat to convergence."""
    matrix = np.array(matrix, dtype=np.float64, copy=True)
    r, c = matrix.shape
    assert c % 4 == 0
    perm = np.arange(c)

    for _ in range(max_rounds):
        kept = _stripe_kept(matrix)
        best_gain, best_pair = 0.0, None
        for i in range(c):
            si = i // 4
            others = np.array([j for j in range(c) if j // 4 != si])
            if others.size == 0:
                continue
            sj = others // 4
            # stripe si with col j in place of col i, for all j: (R, J, 4)
            stripe_i = np.repeat(matrix[:, si * 4:si * 4 + 4][:, None, :],
                                 others.size, axis=1)
            stripe_i[:, np.arange(others.size), i % 4] = matrix[:, others]
            a = np.abs(stripe_i)
            kept_i = np.sort(a, axis=2)[:, :, 2:].sum(axis=(0, 2))
            # stripe sj with col i in place of col j
            stripe_j = np.stack(
                [matrix[:, s * 4:s * 4 + 4] for s in sj], axis=1)
            stripe_j[:, np.arange(others.size), others % 4] = \
                matrix[:, [i]]
            aj = np.abs(stripe_j)
            kept_j = np.sort(aj, axis=2)[:, :, 2:].sum(axis=(0, 2))
            gains = (kept_i + kept_j) - (kept[si] + kept[sj])
            gj = int(np.argmax(gains))
            if gains[gj] > best_gain + 1e-9:
                best_gain, best_pair = float(gains[gj]), (i, int(others[gj]))
        if best_pair is None:
            break
        i, j = best_pair
        matrix[:, [i, j]] = matrix[:, [j, i]]
        perm[[i, j]] = perm[[j, i]]
    return matrix, perm


def accelerated_search_for_good_permutation(
        matrix, options: Optional[dict] = None, verbosity: int = 0):
    """Reference entry point (call_permutation_search_kernels.py:6):
    dispatches on ``options['strategy']`` ('exhaustive' default, or
    'progressive channel swap'). Accepts numpy or jax arrays; returns
    ``(permuted_matrix, permutation)`` as numpy."""
    m = np.asarray(matrix, np.float64)
    options = dict(options or {})
    strategy = options.get("strategy", "exhaustive")
    if strategy == "exhaustive":
        return exhaustive_search(
            m, stripe_group_size=options.get("stripe_group_size", 8),
            escape_attempts=options.get("escape_attempts", 100))
    if strategy == "progressive channel swap":
        return greedy_channel_swaps(
            m, max_rounds=options.get("max_rounds", 100))
    raise ValueError(f"unknown strategy {strategy!r}")


def permute_channels_to_preserve_magnitude(
        w, pattern: str = "m4n2_1d", strategy: str = "exhaustive",
        seed: int = 0, **_compat):
    """ASP integration point: search input-channel permutations of a 2D
    weight (out, in). Returns ``(permuted_w, perm)`` with
    ``permuted_w = w[:, perm]``; apply ``perm`` to the producing layer's
    outputs to keep the network function unchanged (reference semantics)."""
    import jax.numpy as jnp

    w_np = np.asarray(w)
    arr2 = w_np.reshape(w_np.shape[0], -1)  # conv weights flatten to (out, -1)
    cols = arr2.shape[1]
    if cols % 4 != 0:
        return w, np.arange(cols)
    _, perm = accelerated_search_for_good_permutation(
        arr2.astype(np.float64), {"strategy": strategy})
    # both exit paths return the INPUT's rank and dtype (w[:, perm] shape
    # semantics) — the float64 working copy stays internal to the search
    permuted = arr2[:, perm].reshape(w_np.shape)
    return jnp.asarray(permuted, dtype=w.dtype), perm
