"""Channel-permutation search for 2:4 sparsity — TPU equivalent of
``apex/contrib/sparsity/permutation_lib.py`` (2068 LoC) and the
``permutation_search_cuda`` kernels (GPU channel-permutation search).

Goal: permute input channels so the 2:4 mask preserves more magnitude
(accuracy). The reference runs a bounded greedy/exhaustive GPU search; here a
vectorized greedy column-swap search in jnp — device-agnostic, bounded
iterations, jit-friendly per sweep.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask

_f32 = jnp.float32


def _mask_magnitude(w: jax.Array, pattern: str) -> jax.Array:
    m = create_mask(w, pattern)
    return jnp.sum(jnp.abs(w.astype(_f32)) * m)


def permute_channels_to_preserve_magnitude(
        w: jax.Array, pattern: str = "m4n2_1d", sweeps: int = 2,
        seed: int = 0) -> Tuple[jax.Array, np.ndarray]:
    """Greedy search over input-channel permutations of a 2D weight
    (out, in). Returns ``(permuted_w, perm)`` with
    ``permuted_w = w[:, perm]``; apply ``perm`` to the producing layer's
    outputs to keep the network function unchanged (reference semantics).
    """
    w = w.reshape(w.shape[0], -1)
    cols = w.shape[1]
    if cols % 4 != 0:
        return w, np.arange(cols)
    perm = np.arange(cols)
    rng = np.random.default_rng(seed)
    base = float(_mask_magnitude(w, pattern))
    for _ in range(sweeps):
        # propose random transpositions; accept improvements (bounded greedy)
        for _ in range(cols):
            i, j = rng.integers(0, cols, 2)
            if i == j:
                continue
            cand = perm.copy()
            cand[i], cand[j] = cand[j], cand[i]
            mag = float(_mask_magnitude(w[:, cand], pattern))
            if mag > base:
                perm, base = cand, mag
    return w[:, perm], perm
