from apex_tpu.contrib.sparsity.sparse_masklib import create_mask  # noqa: F401
from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.permutation_lib import (  # noqa: F401
    permute_channels_to_preserve_magnitude,
)
