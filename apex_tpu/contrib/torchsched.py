"""torchsched — reference: ``apex/contrib/torchsched/`` (2377 LoC): a
multi-CUDA-stream inductor backend — graph partition → stream assignment
("dwb" scheme) + cross-stream event insertion, monkey-patching
``torch.compile`` (torchsched/__init__.py:28-81).

TPU status: **no analog by design.** The capability — overlapping independent
kernels on parallel hardware queues — is owned end-to-end by XLA's
latency-hiding scheduler: every jitted program is a static dataflow graph and
the compiler assigns compute/DMA/ICI queues and inserts the synchronization
the reference's stream/event machinery hand-builds (SURVEY §7 step 9:
"torchsched has no TPU analog — XLA schedules").

What a user ports TO: just ``jax.jit``. Knobs that influence the same
tradeoffs live in XLA flags (e.g. ``--xla_tpu_enable_latency_hiding_scheduler``,
enabled by default on recent toolchains).
"""

BACKEND_NAME = "xla"  # parity constant: the 'backend' is the compiler itself


def compile(fn=None, **_kw):
    """≈ torchsched-patched ``torch.compile`` → on TPU this is ``jax.jit``."""
    import jax

    if fn is None:
        return jax.jit
    return jax.jit(fn)
