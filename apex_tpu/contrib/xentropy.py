"""Fused softmax cross-entropy with label smoothing — TPU equivalent of
``xentropy_cuda`` (apex/contrib/csrc/xentropy/, frontend
apex/contrib/xentropy/softmax_xentropy.py:6-33).

Key property of the reference preserved: the forward saves only
``max_log_sum_exp`` (one scalar per row) instead of the softmax probabilities
(interface.cpp:42-45) — the backward reconstructs the softmax from the saved
logits + lse. Here that falls out of a custom VJP whose residuals are
(logits, lse, labels): memory cost is one fp32 scalar per row beyond the
autodiff-saved inputs, matching the reference's memory win over naive
log_softmax+nll chains.

Semantics: ``padding_idx`` rows produce zero loss and zero grad;
``smoothing`` ε splits the target as (1-ε)·one_hot + ε/K·uniform;
``half_to_float`` returns fp32 losses for low-precision logits.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_f32 = jnp.float32


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                               smoothing: float = 0.0,
                               padding_idx: Optional[int] = None):
    """Returns per-row loss, shape ``labels.shape``. logits: (..., K)."""
    loss, _ = _xent_fwd_math(logits, labels, smoothing, padding_idx)
    return loss


def _xent_fwd_math(logits, labels, smoothing, padding_idx):
    x = logits.astype(_f32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    lse = lse.squeeze(-1)                       # max_log_sum_exp per row
    picked = jnp.take_along_axis(x, labels[..., None], axis=-1).squeeze(-1)
    nll = lse - picked
    if smoothing > 0.0:
        k = x.shape[-1]
        mean_x = jnp.mean(x, axis=-1)
        smooth_loss = lse - mean_x
        loss = (1.0 - smoothing) * nll + smoothing * smooth_loss
        # note: ε/K·Σ(lse - x_j) == ε·(lse - mean_x)
    else:
        loss = nll
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    return loss, lse


def _xent_vjp_fwd(logits, labels, smoothing, padding_idx):
    loss, lse = _xent_fwd_math(logits, labels, smoothing, padding_idx)
    return loss, (logits, labels, lse)


def _xent_vjp_bwd(smoothing, padding_idx, res, dloss):
    logits, labels, lse = res
    x = logits.astype(_f32)
    probs = jnp.exp(x - lse[..., None])         # softmax from saved lse
    k = x.shape[-1]
    one_hot = jax.nn.one_hot(labels, k, dtype=_f32)
    if smoothing > 0.0:
        target = (1.0 - smoothing) * one_hot + smoothing / k
    else:
        target = one_hot
    g = (probs - target) * dloss[..., None].astype(_f32)
    if padding_idx is not None:
        g = jnp.where((labels == padding_idx)[..., None], 0.0, g)
    return g.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


class SoftmaxCrossEntropyLoss:
    """Module-style facade ≈ ``xentropy.SoftmaxCrossEntropyLoss``.

    ``half_to_float=True`` returns fp32 losses for fp16/bf16 logits (the
    reference flag of softmax_xentropy.py:6).
    """

    def __init__(self, smoothing: float = 0.0,
                 padding_idx: Optional[int] = None,
                 half_to_float: bool = True, reduction: str = "mean"):
        self.smoothing = smoothing
        self.padding_idx = padding_idx
        self.half_to_float = half_to_float
        self.reduction = reduction

    def __call__(self, logits, labels):
        loss = softmax_cross_entropy_loss(logits, labels, self.smoothing,
                                          self.padding_idx)
        if not self.half_to_float:
            loss = loss.astype(logits.dtype)
        if self.reduction == "mean":
            if self.padding_idx is not None:
                n = jnp.maximum(jnp.sum(labels != self.padding_idx), 1)
                return jnp.sum(loss) / n
            return jnp.mean(loss)
        if self.reduction == "sum":
            return jnp.sum(loss)
        return loss
