"""Transducer (RNN-T) joint + loss — TPU equivalent of
``transducer_joint_cuda`` / ``transducer_loss_cuda``
(apex/contrib/csrc/transducer/, frontend apex/contrib/transducer/transducer.py:6
``TransducerJoint``, ``TransducerLoss``; pure-python spec
_transducer_ref.py).

TPU design notes:
- the joint's tiled broadcast-add + fused ReLU/dropout is an XLA fusion;
  the reference's packed-output mode (dropping pad positions) is expressed as
  a mask (dynamic shapes don't jit).
- the loss's alpha recursion is a linear recurrence in log space along the
  label axis; it runs as ``lax.associative_scan`` per time step (log-domain
  matmul-free wavefront), scanned over time — O(T) sequential depth instead
  of the reference's per-(t,u) thread grid, which is the TPU-friendly shape.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_f32 = jnp.float32
_NEG = -1e30


def transducer_joint(f: jax.Array, g: jax.Array, f_len=None, g_len=None,
                     relu: bool = False, dropout_prob: float = 0.0,
                     key=None, mask: bool = False):
    """Joint: f (B, T, H) + g (B, U, H) → (B, T, U, H), optional fused
    ReLU+dropout (transducer_joint.cpp:45-47). ``mask=True`` zeroes positions
    past (f_len, g_len) — the packed-output equivalent."""
    h = f[:, :, None, :].astype(_f32) + g[:, None, :, :].astype(_f32)
    if relu:
        h = jnp.maximum(h, 0.0)
    if dropout_prob > 0.0:
        assert key is not None
        keep = jax.random.bernoulli(key, 1.0 - dropout_prob, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_prob), 0.0)
    if mask:
        if f_len is None and g_len is None:
            raise ValueError(
                "packed/masked joint needs f_len and/or g_len")
        b, t, u, _ = h.shape
        keep = jnp.ones((b, t, u, 1), bool)
        if f_len is not None:
            keep &= (jnp.arange(t)[None, :, None, None]
                     < f_len[:, None, None, None])
        if g_len is not None:
            keep &= (jnp.arange(u)[None, None, :, None]
                     < g_len[:, None, None, None])
        h = jnp.where(keep, h, 0.0)
    return h.astype(f.dtype)


def _alpha_row_step(alpha_prev, blank_prev, label_prev):
    """alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
                               alpha[t, u-1] + label[t, u-1])
    — a log-linear recurrence along u solved with associative_scan."""
    c = alpha_prev + blank_prev                     # (B, U) "emit from above"
    # recurrence x[u] = logaddexp(c[u], x[u-1] + d[u]) with d[u]=label[t,u-1]
    d = jnp.concatenate([jnp.full_like(label_prev[:, :1], _NEG),
                         label_prev[:, :-1]], axis=1)

    def combine(a, b):
        ld1, lc1 = a
        ld2, lc2 = b
        return ld1 + ld2, jnp.logaddexp(lc1 + ld2, lc2)

    ld, lc = jax.lax.associative_scan(combine, (d, c), axis=1)
    return lc


def transducer_loss(log_probs: jax.Array, labels: jax.Array,
                    f_len: jax.Array, y_len: jax.Array,
                    blank_idx: int = 0) -> jax.Array:
    """RNN-T negative log-likelihood per batch element.

    log_probs: (B, T, U, V) log-softmax outputs (U = max_label_len + 1);
    labels: (B, U-1) int; f_len: (B,) valid time steps; y_len: (B,) valid
    label lengths. Differentiable (autodiff through the scans reproduces the
    reference's backward kernel).
    """
    b, t, u, v = log_probs.shape
    lp = log_probs.astype(_f32)
    blank = lp[..., blank_idx]                       # (B, T, U)
    lab = jnp.take_along_axis(
        lp[:, :, :-1, :], labels[:, None, :, None], axis=3)[..., 0]
    lab = jnp.pad(lab, ((0, 0), (0, 0), (0, 1)), constant_values=_NEG)

    # row 0 uses only label transitions: alpha[0, u] = Σ_{k<u} label[0, k]
    lab0 = lab[:, 0]                                  # (B, U)
    csum = jnp.cumsum(jnp.concatenate(
        [jnp.zeros((b, 1)), lab0[:, :-1]], axis=1), axis=1)
    alpha_row0 = csum                                 # alpha[0, u]

    def step(alpha_prev, xs):
        blank_prev, label_t = xs
        row = _alpha_row_step(alpha_prev, blank_prev, label_t)
        return row, alpha_prev

    # scan over time t = 1..T-1; xs at t uses blank[t-1] and label[t]
    xs = (jnp.moveaxis(blank[:, :-1], 1, 0), jnp.moveaxis(lab[:, 1:], 1, 0))
    alpha_last, alpha_hist = jax.lax.scan(step, alpha_row0, xs)
    # alpha_hist[i] = alpha row at t=i (for i in 0..T-2); append last
    alpha_all = jnp.concatenate(
        [jnp.moveaxis(alpha_hist, 0, 1), alpha_last[:, None, :]], axis=1)

    # loss = -(alpha[f_len-1, y_len] + blank[f_len-1, y_len])
    ti = jnp.clip(f_len - 1, 0, t - 1)
    ui = jnp.clip(y_len, 0, u - 1)
    gather = alpha_all[jnp.arange(b), ti, ui] + blank[jnp.arange(b), ti, ui]
    return -gather


class TransducerJoint:
    """Module-style facade ≈ apex.contrib.transducer.TransducerJoint."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0):
        self.relu = relu
        self.dropout_prob = dropout_prob if dropout else 0.0
        self.pack_output = pack_output

    def __call__(self, f, g, f_len=None, g_len=None, key=None):
        return transducer_joint(f, g, f_len, g_len, relu=self.relu,
                                dropout_prob=self.dropout_prob, key=key,
                                mask=self.pack_output)


class TransducerLoss:
    """Module-style facade ≈ apex.contrib.transducer.TransducerLoss."""

    def __init__(self, packed_input: bool = False):
        del packed_input  # mask-based here

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
