"""Fused Conv+Bias(+ReLU/Mask) — TPU equivalent of ``fused_conv_bias_relu``
(apex/contrib/csrc/conv_bias_relu/conv_bias_relu.cpp:1902-1911 cuDNN-frontend
fused epilogues; frontend apex/contrib/conv_bias_relu/conv_bias_relu.py).

XLA fuses conv epilogues natively on TPU, so these are thin functional shims
whose value is API parity + guaranteed-fusable formulation (NHWC, bias add and
activation expressed in the conv's output dtype chain).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _conv_nhwc(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=_f32)


def conv_bias(x, weight, bias, stride: int = 1, padding: int = 0):
    """ConvBias (conv_bias_relu.py ConvBias_)."""
    y = _conv_nhwc(x, weight, stride, padding) + bias.astype(_f32)
    return y.astype(x.dtype)


def conv_bias_relu(x, weight, bias, stride: int = 1, padding: int = 0):
    """ConvBiasReLU — fused conv+bias+relu."""
    y = _conv_nhwc(x, weight, stride, padding) + bias.astype(_f32)
    return jnp.maximum(y, 0.0).astype(x.dtype)


def conv_bias_mask_relu(x, weight, bias, mask, stride: int = 1,
                        padding: int = 0):
    """ConvBiasMaskReLU — fused conv+bias+elementwise-mask+relu."""
    y = _conv_nhwc(x, weight, stride, padding) + bias.astype(_f32)
    y = y * mask.astype(_f32)
    return jnp.maximum(y, 0.0).astype(x.dtype)


def conv_frozen_scale_bias_relu(x, weight, scale, bias, stride: int = 1,
                                padding: int = 0):
    """ConvFrozenScaleBiasReLU — conv + frozen-BN affine + relu
    (conv_bias_relu.cpp frozen-scale-bias entry)."""
    y = _conv_nhwc(x, weight, stride, padding)
    y = y * scale.astype(_f32) + bias.astype(_f32)
    return jnp.maximum(y, 0.0).astype(x.dtype)
