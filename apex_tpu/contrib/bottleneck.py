"""Bottleneck / SpatialBottleneck — TPU equivalent of
``apex/contrib/bottleneck/bottleneck.py`` (``Bottleneck`` :154,
``SpatialBottleneck`` :833 over ``fast_bottleneck`` cuDNN fused convs,
spatial-parallel halo entry points bottleneck.cpp:3558-3595).

TPU design: the cuDNN fused conv+scale+bias+relu chains are XLA fusions; the
spatial (H-split) parallelism keeps the reference's structure — exchange
1-row halos with the ppermute exchangers (apex_tpu.parallel.halo, the
peer_memory/nccl_p2p equivalent), run the 3x3 conv VALID over the
halo-extended tile so each shard computes exactly its slice of the global
convolution (SURVEY §3.5 call stack).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.halo import HaloExchanger, HaloExchangerPeer
from apex_tpu.parallel.sync_batch_norm import SyncBatchNorm

_f32 = jnp.float32


class Bottleneck(nn.Module):
    """ResNet bottleneck (1x1→3x3→1x1, expansion 4) with frozen-BN-style
    scale/bias folded convs — the contrib Bottleneck's inference-friendly
    form, trainable here."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    compute_dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       param_dtype=jnp.float32)
        bn = partial(SyncBatchNorm, axis_name=self.bn_axis_name)
        residual = x
        y = conv(self.bottleneck_channels, (1, 1), name="conv1")(x)
        y = bn(self.bottleneck_channels, name="bn1", fuse_relu=True)(
            y, use_running_average)
        y = conv(self.bottleneck_channels, (3, 3),
                 strides=(self.stride,) * 2,
                 padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = bn(self.bottleneck_channels, name="bn2", fuse_relu=True)(
            y, use_running_average)
        y = conv(self.out_channels, (1, 1), name="conv3")(y)
        y = bn(self.out_channels, name="bn3")(y, use_running_average)
        if self.in_channels != self.out_channels or self.stride != 1:
            residual = conv(self.out_channels, (1, 1),
                            strides=(self.stride,) * 2, name="proj")(x)
            residual = bn(self.out_channels, name="proj_bn")(
                residual, use_running_average)
        return jnp.maximum(y + residual.astype(y.dtype), 0.0)


class SpatialBottleneck(nn.Module):
    """H-split spatially-parallel bottleneck (≈ SpatialBottleneck :833).

    Input x: the LOCAL H-shard (N, H_local, W, C), sharded over
    ``spatial_axis_name`` inside shard_map. The 3x3 conv exchanges one-row
    halos with the configured exchanger, then convolves VALID over the
    extended tile — numerically identical to the unsharded conv.
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    spatial_axis_name: str = "spatial"
    halo_ex: Optional[HaloExchanger] = None
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        assert self.stride == 1, (
            "spatial-parallel path supports stride 1 (the reference's "
            "halo exchange is likewise for the stride-1 3x3)")
        halo_ex = self.halo_ex or HaloExchangerPeer(self.spatial_axis_name)
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       param_dtype=jnp.float32)
        bn = partial(SyncBatchNorm, axis_name=self.spatial_axis_name)
        residual = x
        y = conv(self.bottleneck_channels, (1, 1), name="conv1")(x)
        y = bn(self.bottleneck_channels, name="bn1", fuse_relu=True)(
            y, use_running_average)
        # halo exchange on H (axis 1), then VALID 3x3 == global SAME 3x3
        y = halo_ex(y, 1, spatial_axis=1)
        y = conv(self.bottleneck_channels, (3, 3),
                 padding=[(0, 0), (1, 1)], name="conv2")(y)
        y = bn(self.bottleneck_channels, name="bn2", fuse_relu=True)(
            y, use_running_average)
        y = conv(self.out_channels, (1, 1), name="conv3")(y)
        y = bn(self.out_channels, name="bn3")(y, use_running_average)
        if self.in_channels != self.out_channels:
            residual = conv(self.out_channels, (1, 1), name="proj")(x)
            residual = bn(self.out_channels, name="proj_bn")(
                residual, use_running_average)
        return jnp.maximum(y + residual.astype(y.dtype), 0.0)
