"""Fused focal loss — TPU equivalent of ``focal_loss_cuda``
(apex/contrib/csrc/focal_loss/focal_loss_cuda.cpp:43-46, frontend
apex/contrib/focal_loss/focal_loss.py).

Sigmoid focal loss for dense detection (RetinaNet semantics): one fused
forward producing the summed loss normalized by num_positives_sum, with label
smoothing; backward is a single fused elementwise chain via custom VJP
(the reference ships an explicit backward kernel for the same reason).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_f32 = jnp.float32


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def focal_loss(cls_output: jax.Array, cls_targets: jax.Array,
               num_positives_sum: jax.Array, num_real_classes: int,
               alpha: float = 0.25, gamma: float = 2.0,
               label_smoothing: float = 0.0) -> jax.Array:
    """cls_output: (..., K) logits; cls_targets: (...) int class ids with
    -1 = ignore, 0 = background (no positive class), 1..K = classes offset by
    one (reference convention). Returns scalar loss."""
    loss, _ = _focal_fwd(cls_output, cls_targets, num_positives_sum,
                         num_real_classes, alpha, gamma, label_smoothing)
    return loss


def _focal_fwd(x, t, npos, k, alpha, gamma, smooth):
    x32 = x[..., :k].astype(_f32)
    valid = (t >= 0)[..., None]
    onehot = jax.nn.one_hot(t - 1, k, dtype=_f32)  # t==0 → all zeros
    if smooth > 0:
        onehot = onehot * (1.0 - smooth) + smooth / 2.0
    p = jax.nn.sigmoid(x32)
    ce = jnp.logaddexp(0.0, -jnp.abs(x32)) + jnp.maximum(x32, 0.0) \
        - x32 * onehot  # stable BCE-with-logits
    p_t = p * onehot + (1 - p) * (1 - onehot)
    a_t = alpha * onehot + (1 - alpha) * (1 - onehot)
    mod = jnp.power(1.0 - p_t, gamma)
    per = a_t * mod * ce * valid
    loss = jnp.sum(per) / jnp.maximum(npos.astype(_f32), 1.0)
    return loss, (x32, onehot, valid, npos)


def _focal_vjp_fwd(x, t, npos, k, alpha, gamma, smooth):
    loss, res = _focal_fwd(x, t, npos, k, alpha, gamma, smooth)
    x32, onehot, valid, npos_saved = res
    return loss, (x, onehot, valid, npos_saved)


def _focal_vjp_bwd(k, alpha, gamma, smooth, res, dloss):
    x, onehot, valid, npos = res
    x32 = x[..., :k].astype(_f32)

    # d/dx of a_t (1-p_t)^γ ce — one fused elementwise chain over the
    # saved residuals (the reference ships this as an explicit bwd kernel)
    def scalar(x32):
        p = jax.nn.sigmoid(x32)
        ce = jnp.logaddexp(0.0, -jnp.abs(x32)) + jnp.maximum(x32, 0.0) \
            - x32 * onehot
        p_t = p * onehot + (1 - p) * (1 - onehot)
        a_t = alpha * onehot + (1 - alpha) * (1 - onehot)
        per = a_t * jnp.power(1.0 - p_t, gamma) * ce * valid
        return jnp.sum(per) / jnp.maximum(npos.astype(_f32), 1.0)

    dx32 = jax.grad(scalar)(x32) * dloss
    dx = jnp.zeros(x.shape, x.dtype)
    dx = dx.at[..., :k].set(dx32.astype(x.dtype))
    return dx, None, None


focal_loss.defvjp(_focal_vjp_fwd, _focal_vjp_bwd)
