"""index_mul_2d — TPU equivalent of ``fused_index_mul_2d``
(apex/contrib/csrc/index_mul_2d/index_mul_2d_cuda.cpp:69-75, frontend
apex/contrib/index_mul_2d/index_mul_2d.py).

``out = in1[idx1] * in2`` with fwd / bwd / double-bwd. On TPU the gather +
multiply fuses in XLA and the backward scatter-add is a segment-sum; double
backward falls out of jnp autodiff, so no handwritten bwd-bwd kernel is
needed — the op is a plain differentiable function.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def index_mul_2d(in1: jax.Array, in2: jax.Array,
                 idx1: jax.Array) -> jax.Array:
    """in1: (N, D); in2: (M, D); idx1: (M,) int32 indices into in1.

    Returns (M, D) = in1[idx1] * in2. Differentiable to any order
    (grad w.r.t. in1 is the scatter-add the reference's bwd kernel does).
    """
    return in1[idx1] * in2
