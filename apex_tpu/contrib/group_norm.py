"""NHWC GroupNorm with fused SiLU — TPU equivalent of the contrib GroupNorm
stack: ``group_norm_cuda`` one/two-pass (27 instantiation files),
``group_norm_v2_cuda`` (SM90/100), and frontend
``apex/contrib/group_norm/group_norm.py`` (:211 module, algorithm selection
:193-209, ``torch_group_norm`` fallback :37).

TPU design: one kernel pair covers all channel counts (no SUPPORTED_CHANNELS
tables, :247-325 — per-shape instantiation is Mosaic's job), but the
reference's one-pass/two-pass ALGORITHM switch survives, translated: the
one-pass Pallas kernel normalizes on a single HBM read of x when the sample
slab fits VMEM, else the tiled two-pass pair runs (selection in
ops/pallas/group_norm_kernel.py:one_pass_ok ≈ group_norm.py:193-209).
Stats always fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas import group_norm_kernel as _gnk

_f32 = jnp.float32


def _gn_jnp(x, num_groups, weight, bias, eps, act):
    n, h, w, c = x.shape
    x32 = x.astype(_f32).reshape(n, h * w, num_groups, c // num_groups)
    mean = jnp.mean(x32, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=(1, 3), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(n, h, w, c)
    if weight is not None:
        y = y * weight.astype(_f32)
    if bias is not None:
        y = y + bias.astype(_f32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 4, 5, 6))
def _gn_pallas(x, num_groups, weight, bias, eps, act, algo):
    y, _, _ = _gnk.group_norm_nhwc_pallas(x, num_groups, weight, bias, eps,
                                          act, algo=algo)
    return y


def _gn_pallas_fwd(x, num_groups, weight, bias, eps, act, algo):
    y, mean, rstd = _gnk.group_norm_nhwc_pallas(x, num_groups, weight, bias,
                                                eps, act, algo=algo)
    return y, (x, weight, bias, mean, rstd)


def _gn_pallas_bwd(num_groups, eps, act, algo, res, dy):
    """Analytic GN backward from saved (mean, rstd) — one fused XLA chain
    (the reference ships dedicated bwd kernels; the dgamma/dbeta column
    reductions are XLA's bread and butter)."""
    x, weight, bias, mean, rstd = res
    n, h, w, c = x.shape
    g = num_groups
    cpg = c // g
    x32 = x.astype(_f32)
    mean_c = jnp.repeat(mean, cpg, axis=1)[:, None, None, :]
    rstd_c = jnp.repeat(rstd, cpg, axis=1)[:, None, None, :]
    xhat = (x32 - mean_c) * rstd_c
    dy32 = dy.astype(_f32)
    if act == "silu":
        # recompute pre-activation z and fold silu'(z) into dy
        z = xhat
        if weight is not None:
            z = z * weight.astype(_f32)
        if bias is not None:
            z = z + bias.astype(_f32)
        sig = jax.nn.sigmoid(z)
        dy32 = dy32 * (sig * (1.0 + z * (1.0 - sig)))
    dgamma = dbeta = None
    if weight is not None:
        dgamma = jnp.sum(dy32 * xhat, axis=(0, 1, 2)).astype(weight.dtype)
        wdy = dy32 * weight.astype(_f32)
    else:
        wdy = dy32
    if bias is not None:
        dbeta = jnp.sum(dy32, axis=(0, 1, 2)).astype(bias.dtype)
    # per-(n, g) means of wdy and wdy*xhat
    wdy_g = wdy.reshape(n, h * w, g, cpg)
    xhat_g = xhat.reshape(n, h * w, g, cpg)
    m1 = jnp.mean(wdy_g, axis=(1, 3), keepdims=True)
    m2 = jnp.mean(wdy_g * xhat_g, axis=(1, 3), keepdims=True)
    dx = (wdy_g - m1 - xhat_g * m2) * rstd[:, None, :, None]
    dx = dx.reshape(n, h, w, c).astype(x.dtype)
    return dx, dgamma, dbeta


_gn_pallas.defvjp(_gn_pallas_fwd, _gn_pallas_bwd)


def group_norm_nhwc(x: jax.Array, num_groups: int,
                    weight: Optional[jax.Array] = None,
                    bias: Optional[jax.Array] = None, eps: float = 1e-5,
                    act: str = "", algo: str = "auto") -> jax.Array:
    """x: (N, H, W, C); ``act`` in {"", "silu"} (the fused SiLU epilogue of
    group_norm_nhwc_one_pass_*.cu). Dispatches to the Pallas one-pass kernel
    when the sample slab fits VMEM, the tiled two-pass pair otherwise
    (``algo`` overrides — the reference's selection knob,
    group_norm.py:193-209), and the jnp path for tile-unfriendly shapes."""
    n, h, w, c = x.shape
    assert c % num_groups == 0
    if act not in ("", "silu"):
        raise ValueError(f"unsupported act {act!r}")
    if _gnk.pallas_ok(n, h * w, c):
        return _gn_pallas(x, num_groups, weight, bias, eps, act, algo)
    if algo != "auto":
        # an explicit algorithm request must not silently run the jnp path
        raise ValueError(
            f"algo={algo!r} requested but the Pallas kernels need HW % 8 "
            f"== 0 (got {h}x{w}); use algo='auto' for the jnp fallback")
    return _gn_jnp(x, num_groups, weight, bias, eps, act)


def torch_group_norm(x, num_groups, weight=None, bias=None, eps=1e-5,
                     act=""):
    """Name-parity alias for the reference's fallback (group_norm.py:37)."""
    return group_norm_nhwc(x, num_groups, weight, bias, eps, act)


class GroupNorm(nn.Module):
    """flax module ≈ apex.contrib.group_norm.GroupNorm (group_norm.py:211).

    NHWC input; ``act='silu'`` fuses the activation.
    """

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = b = None
        if self.affine:
            w = self.param("weight", nn.initializers.ones,
                           (self.num_channels,), self.param_dtype)
            b = self.param("bias", nn.initializers.zeros,
                           (self.num_channels,), self.param_dtype)
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps, self.act)
