"""NHWC GroupNorm with fused SiLU — TPU equivalent of the contrib GroupNorm
stack: ``group_norm_cuda`` one/two-pass (27 instantiation files),
``group_norm_v2_cuda`` (SM90/100), and frontend
``apex/contrib/group_norm/group_norm.py`` (:211 module, algorithm selection
:193-209, ``torch_group_norm`` fallback :37).

TPU design: one implementation for all channel counts — XLA fuses the
reduction + normalize + SiLU chain over the NHWC layout (the layout TPU convs
prefer, same reason the reference targets NHWC). Stats always fp32. The
reference's one-pass/two-pass/v2 algorithm switch and SUPPORTED_CHANNELS
tables (:247-325) are compiler concerns on TPU and intentionally absent.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

_f32 = jnp.float32


def group_norm_nhwc(x: jax.Array, num_groups: int,
                    weight: Optional[jax.Array] = None,
                    bias: Optional[jax.Array] = None, eps: float = 1e-5,
                    act: str = "") -> jax.Array:
    """x: (N, H, W, C); ``act`` in {"", "silu"} (the fused SiLU epilogue of
    group_norm_nhwc_one_pass_*.cu)."""
    n, h, w, c = x.shape
    assert c % num_groups == 0
    x32 = x.astype(_f32).reshape(n, h * w, num_groups, c // num_groups)
    mean = jnp.mean(x32, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=(1, 3), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(n, h, w, c)
    if weight is not None:
        y = y * weight.astype(_f32)
    if bias is not None:
        y = y + bias.astype(_f32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    elif act:
        raise ValueError(f"unsupported act {act!r}")
    return y.astype(x.dtype)


def torch_group_norm(x, num_groups, weight=None, bias=None, eps=1e-5,
                     act=""):
    """Name-parity alias for the reference's fallback (group_norm.py:37)."""
    return group_norm_nhwc(x, num_groups, weight, bias, eps, act)


class GroupNorm(nn.Module):
    """flax module ≈ apex.contrib.group_norm.GroupNorm (group_norm.py:211).

    NHWC input; ``act='silu'`` fuses the activation.
    """

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = b = None
        if self.affine:
            w = self.param("weight", nn.initializers.ones,
                           (self.num_channels,), self.param_dtype)
            b = self.param("bias", nn.initializers.zeros,
                           (self.num_channels,), self.param_dtype)
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps, self.act)
