"""Fused gradient clipping — TPU equivalent of
``apex/contrib/clip_grad/clip_grad.py`` (torch-compatible ``clip_grad_norm_``
built on ``multi_tensor_l2norm`` + ``multi_tensor_scale`` :17+).

Functional (JAX): returns the clipped grads and the pre-clip total norm.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor.functional import multi_tensor_l2norm

_f32 = jnp.float32


def clip_grad_norm_(grads: Any, max_norm: float,
                    norm_type: float = 2.0,
                    error_if_nonfinite: bool = False
                    ) -> Tuple[Any, jax.Array]:
    """Clip the global norm of a gradient pytree.

    Returns ``(clipped_grads, total_norm)``. norm_type 2.0 uses the fused
    L2 path; inf-norm supported for torch parity. ``error_if_nonfinite`` is
    jit-incompatible host semantics — a non-finite norm yields unclipped
    grads (caller checks the returned norm), matching the reference's
    behavior when the flag is False.
    """
    max_norm = jnp.asarray(max_norm, _f32)
    if norm_type == 2.0:
        total, _ = multi_tensor_l2norm(grads)
    elif norm_type == float("inf"):
        leaves = jax.tree_util.tree_leaves(grads)
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(_f32))) for l in leaves]))
    else:
        leaves = jax.tree_util.tree_leaves(grads)
        acc = sum(jnp.sum(jnp.abs(l.astype(_f32)) ** norm_type)
                  for l in leaves)
        total = acc ** (1.0 / norm_type)
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)

    def _scale(g):
        return (g.astype(_f32) * coef).astype(g.dtype)

    return jax.tree_util.tree_map(_scale, grads), total
