"""Peer memory pool + 1-D halo exchanger facades — TPU equivalent of
``apex/contrib/peer_memory/`` (``PeerMemoryPool`` peer_memory.py:6-42,
``PeerHaloExchanger1d`` peer_halo_exchanger_1d.py:5) over the
``peer_memory_cuda`` IPC kernels (peer_memory.cpp:20-34,
``push_pull_halos_1d``).

On TPU there is no user-managed device memory: XLA owns buffers and
chip-to-chip one-sided writes are what ``ppermute`` compiles to over ICI
(SURVEY §5 comm backend mapping). ``PeerMemoryPool`` therefore carries only
the bookkeeping surface (sizes/alignment) so reference call sites port
mechanically, and the halo exchanger delegates to apex_tpu.parallel.halo.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.parallel.halo import halo_exchange_1d, left_right_halo_exchange


class PeerMemoryPool:
    """API-parity shim (peer_memory.py:29-42). Allocation is XLA's job; the
    pool records the requested static/dynamic sizes for introspection."""

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.static_size = static_size
        self.dynamic_size = dynamic_size
        self.peer_ranks = peer_ranks
        self.alignment = 256

    def allocate_peer_tensors(self, shape, dtype, channels_last: bool,
                              dynamic: bool):
        raise NotImplementedError(
            "TPU has no user-managed peer memory: use "
            "apex_tpu.parallel.halo (ppermute lowers to direct ICI DMA).")


class PeerHaloExchanger1d:
    """≈ peer_halo_exchanger_1d.PeerHaloExchanger1d — ppermute-backed."""

    def __init__(self, ranks=None, rank_in_group: Optional[int] = None,
                 peer_pool: Optional[PeerMemoryPool] = None,
                 half_halo: int = 1, axis_name: str = "spatial"):
        self.axis_name = axis_name
        self.half_halo = half_halo

    def left_right_halo_exchange(self, left_output_halo, right_output_halo):
        return left_right_halo_exchange(left_output_halo, right_output_halo,
                                        self.axis_name)

    def __call__(self, x, spatial_axis: int = 1):
        return halo_exchange_1d(x, self.half_halo, self.axis_name,
                                spatial_axis)
