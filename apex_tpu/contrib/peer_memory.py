"""Peer memory pool + 1-D halo exchanger facades — TPU equivalent of
``apex/contrib/peer_memory/`` (``PeerMemoryPool`` peer_memory.py:6-42,
``PeerHaloExchanger1d`` peer_halo_exchanger_1d.py:5) over the
``peer_memory_cuda`` IPC kernels (peer_memory.cpp:20-34,
``push_pull_halos_1d``).

On TPU there is no user-managed device memory: XLA owns buffers and
chip-to-chip one-sided writes are what ``ppermute`` compiles to over ICI
(SURVEY §5 comm backend mapping). ``PeerMemoryPool`` therefore carries only
the bookkeeping surface (sizes/alignment) so reference call sites port
mechanically, and the halo exchanger delegates to apex_tpu.parallel.halo.

``transport="rdma"`` routes the exchange through an explicit Pallas
one-sided remote DMA (``ops/pallas/remote_copy.halo_exchange_rdma``) —
the literal TPU analog of the reference's peer put
(``push_pull_halos_1d``, peer_memory.cpp:20-34): a kernel-issued ICI put
into the neighbor's buffer, semaphore-synchronized, no collective. The
default ``transport="collective"`` keeps the compiler-scheduled
``ppermute`` path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.parallel.halo import halo_exchange_1d, left_right_halo_exchange


class PeerMemoryPool:
    """API-parity shim (peer_memory.py:29-42). Allocation is XLA's job; the
    pool records the requested static/dynamic sizes for introspection."""

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.static_size = static_size
        self.dynamic_size = dynamic_size
        self.peer_ranks = peer_ranks
        self.alignment = 256

    def allocate_peer_tensors(self, shape, dtype, channels_last: bool,
                              dynamic: bool):
        raise NotImplementedError(
            "TPU has no user-managed peer memory: the peer-put CAPABILITY "
            "is PeerHaloExchanger1d(transport='rdma') (a Pallas one-sided "
            "remote DMA), or apex_tpu.parallel.halo's ppermute path.")


class PeerHaloExchanger1d:
    """≈ peer_halo_exchanger_1d.PeerHaloExchanger1d.

    ``transport="collective"`` (default): ppermute-backed.
    ``transport="rdma"``: Pallas one-sided remote-DMA puts — the
    reference's actual mechanism (peer rank writes directly into this
    rank's buffer)."""

    def __init__(self, ranks=None, rank_in_group: Optional[int] = None,
                 peer_pool: Optional[PeerMemoryPool] = None,
                 half_halo: int = 1, axis_name: str = "spatial",
                 transport: str = "collective"):
        if transport not in ("collective", "rdma"):
            raise ValueError(f"unknown transport {transport!r}")
        self.axis_name = axis_name
        self.half_halo = half_halo
        self.transport = transport

    def left_right_halo_exchange(self, left_output_halo, right_output_halo):
        if self.transport == "rdma":
            from apex_tpu.ops.pallas.remote_copy import halo_exchange_rdma

            # stack my two edges so one kernel moves both directions, then
            # split: lo is what arrived from the left neighbor
            h = left_output_halo.shape[0]
            if right_output_halo.shape[0] != h:
                raise ValueError(
                    "rdma transport exchanges symmetric halos; got "
                    f"{h} vs {right_output_halo.shape[0]} rows — use "
                    "transport='collective' for asymmetric strips")
            both = jnp.concatenate([left_output_halo, right_output_halo], 0)
            lo, hi = halo_exchange_rdma(both, self.axis_name, h)
            return lo, hi
        return left_right_halo_exchange(left_output_halo, right_output_halo,
                                        self.axis_name)

    def __call__(self, x, spatial_axis: int = 1):
        if self.transport == "rdma":
            from apex_tpu.ops.pallas.remote_copy import halo_exchange_rdma

            # exchange only the edge STRIPS — moveaxis on (2·halo, ...)
            # strips is cheap; relayouting the full activation twice on the
            # hot conv path is not
            h = self.half_halo
            size = x.shape[spatial_axis]
            top = jax.lax.slice_in_dim(x, 0, h, axis=spatial_axis)
            bottom = jax.lax.slice_in_dim(x, size - h, size,
                                          axis=spatial_axis)
            both = jnp.concatenate([top, bottom], axis=spatial_axis)
            both = jnp.moveaxis(both, spatial_axis, 0)
            lo, hi = halo_exchange_rdma(both, self.axis_name, h)
            lo = jnp.moveaxis(lo, 0, spatial_axis)
            hi = jnp.moveaxis(hi, 0, spatial_axis)
            return jnp.concatenate([lo, x, hi], axis=spatial_axis)
        return halo_exchange_1d(x, self.half_halo, self.axis_name,
                                spatial_axis)
