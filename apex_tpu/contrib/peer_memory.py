"""Peer memory pool + 1-D halo exchanger facades — TPU equivalent of
``apex/contrib/peer_memory/`` (``PeerMemoryPool`` peer_memory.py:6-42,
``PeerHaloExchanger1d`` peer_halo_exchanger_1d.py:5) over the
``peer_memory_cuda`` IPC kernels (peer_memory.cpp:20-34,
``push_pull_halos_1d``).

On TPU chip-to-chip one-sided writes are what ``ppermute`` compiles to
over ICI (SURVEY §5 comm backend mapping), and "peer memory" is the SPMD
identification: every rank runs the same program, so the buffer a remote
DMA lands in on rank r IS rank r's instance of the allocation.
``PeerMemoryPool`` is therefore a real single-HBM-arena allocator — one
device allocation up front (the analog of ``pm.allocate_raw``,
peer_memory.py:31), 256-byte-aligned static/dynamic bump sub-allocation
with the reference's exhaustion asserts, and per-peer views that are
genuine device arrays. Pool buffers plug into the RDMA halo exchange as
DONATED landing buffers: thread them through ``shard_map`` as ARGUMENTS
and call ``halo_exchange_rdma(..., bufs=..., return_bufs=True)`` — the
remote puts land in their storage via input/output aliasing, and
re-threading the returned buffers into the next step keeps iteration
allocation-free (the reference pool's purpose). The threading must be
explicit and functional: buffers closed over inside a trace would be
baked in as constants, and re-materializing arena views per call would
allocate fresh storage — both defeat the point, so the exchanger facade
does not do it implicitly.

``transport="rdma"`` routes the exchange through an explicit Pallas
one-sided remote DMA (``ops/pallas/remote_copy.halo_exchange_rdma``) —
the literal TPU analog of the reference's peer put
(``push_pull_halos_1d``, peer_memory.cpp:20-34): a kernel-issued ICI put
into the neighbor's buffer, semaphore-synchronized, no collective. The
default ``transport="collective"`` keeps the compiler-scheduled
``ppermute`` path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.parallel.halo import halo_exchange_1d, left_right_halo_exchange


class PeerMemoryPool:
    """Real TPU peer-memory arena (reference peer_memory.py:6-106).

    One up-front HBM allocation of ``static_size + dynamic_size`` bytes
    (``pm.allocate_raw`` :31), bump-allocated at 256-byte alignment with
    the reference's static/dynamic split and exhaustion asserts (:53-106).
    ``allocate_peer_tensors`` returns one device array per peer rank —
    under SPMD these are each rank's instance of the same arena slice,
    which is exactly the storage a one-sided remote DMA writes into
    (``ops/pallas/remote_copy``). ``channels_last`` is accepted and
    recorded for call-site parity; physical layout is XLA's (there is no
    NCHW-vs-NHWC distinction to honor on a logical view).
    """

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.alignment = 256
        a = self.alignment
        self.static_size = (static_size + a - 1) // a * a
        self.dynamic_size = (dynamic_size + a - 1) // a * a
        self.peer_ranks = list(peer_ranks) if peer_ranks is not None else [0]
        # the arena: ONE device allocation, sub-allocated below
        self._raw = jnp.zeros((max(self.static_size + self.dynamic_size,
                                   1),), jnp.uint8)
        self.static_offset = 0
        self.dynamic_offset = 0
        self.allocations: list[dict] = []

    def reset(self):
        """Free all dynamic sub-allocations (reference :50-51). Records
        stay in place (marked freed) so positional indices held by
        callers — e.g. PeerHaloExchanger1d's cached landing-buffer
        indices — remain stable."""
        self.dynamic_offset = 0
        for r in self.allocations:
            if r["dynamic"]:
                r["freed"] = True

    def free(self):
        """Drop the arena (``pm.free_raw`` :47-48 analog)."""
        self._raw = None

    def _view(self, start: int, shape, dtype):
        nbytes = int(np.prod(shape)) * jnp.dtype(dtype).itemsize
        flat = jax.lax.slice(self._raw, (start,), (start + nbytes,))
        if jnp.dtype(dtype).itemsize == 1:
            out = flat.astype(dtype)
        else:
            out = jax.lax.bitcast_convert_type(
                flat.reshape(-1, jnp.dtype(dtype).itemsize), dtype)
        return out.reshape(shape)

    def allocate_peer_tensors(self, shape, dtype, channels_last: bool,
                              dynamic: bool):
        """Sub-allocate ``shape``/``dtype`` from the arena; returns one
        device array per peer rank (reference :53-106)."""
        if self._raw is None:
            raise RuntimeError("pool was freed")
        nbytes = int(np.prod(shape)) * jnp.dtype(dtype).itemsize
        a = self.alignment
        if dynamic:
            start = (self.dynamic_offset + a - 1) // a * a
            self.dynamic_offset = start + nbytes
            assert self.dynamic_offset < self.dynamic_size, \
                "Dynamic peer memory pool exhausted"
            base = self.static_size + start
        else:
            start = (self.static_offset + a - 1) // a * a
            self.static_offset = start + nbytes
            assert self.static_offset < self.static_size, \
                "Static peer memory pool exhausted"
            base = start
        self.allocations.append(
            {"shape": tuple(shape), "dtype": jnp.dtype(dtype).name,
             "offset": base, "nbytes": nbytes, "dynamic": dynamic,
             "channels_last": bool(channels_last)})
        return [self._view(base, shape, dtype) for _ in self.peer_ranks]

    def view(self, alloc_index: int):
        """Re-materialize the device view of a prior sub-allocation (the
        record survives donation of an earlier view — the arena itself is
        never donated)."""
        if self._raw is None:
            raise RuntimeError("pool was freed")
        r = self.allocations[alloc_index]
        if r.get("freed"):
            raise RuntimeError(
                f"allocation {alloc_index} was freed by reset()")
        return self._view(r["offset"], r["shape"], jnp.dtype(r["dtype"]))

    def allocate_halo_buffers(self, x_shape, halo: int, dtype,
                              dynamic: bool = False):
        """Landing buffers for ``halo_exchange_rdma(..., bufs=...)`` —
        shaped by ``halo_buf_rows`` so remote puts land in pool storage.
        Returns ``(lo, hi, (idx_lo, idx_hi))``; the indices re-materialize
        the views via :meth:`view` after a donating call."""
        from apex_tpu.ops.pallas.remote_copy import halo_buf_rows

        rows = halo_buf_rows(x_shape[0], halo, dtype)
        shape = (rows,) + tuple(x_shape[1:])
        lo = self.allocate_peer_tensors(shape, dtype, False, dynamic)[0]
        idx_lo = len(self.allocations) - 1
        hi = self.allocate_peer_tensors(shape, dtype, False, dynamic)[0]
        idx_hi = len(self.allocations) - 1
        return lo, hi, (idx_lo, idx_hi)


class PeerHaloExchanger1d:
    """≈ peer_halo_exchanger_1d.PeerHaloExchanger1d.

    ``transport="collective"`` (default): ppermute-backed.
    ``transport="rdma"``: Pallas one-sided remote-DMA puts — the
    reference's actual mechanism (peer rank writes directly into this
    rank's buffer)."""

    def __init__(self, ranks=None, rank_in_group: Optional[int] = None,
                 peer_pool: Optional[PeerMemoryPool] = None,
                 half_halo: int = 1, axis_name: str = "spatial",
                 transport: str = "collective"):
        if transport not in ("collective", "rdma"):
            raise ValueError(f"unknown transport {transport!r}")
        self.axis_name = axis_name
        self.half_halo = half_halo
        self.transport = transport
        self.peer_pool = peer_pool

    def left_right_halo_exchange(self, left_output_halo, right_output_halo):
        if self.transport == "rdma":
            from apex_tpu.ops.pallas.remote_copy import halo_exchange_rdma

            # stack my two edges so one kernel moves both directions, then
            # split: lo is what arrived from the left neighbor
            h = left_output_halo.shape[0]
            if right_output_halo.shape[0] != h:
                raise ValueError(
                    "rdma transport exchanges symmetric halos; got "
                    f"{h} vs {right_output_halo.shape[0]} rows — use "
                    "transport='collective' for asymmetric strips")
            both = jnp.concatenate([left_output_halo, right_output_halo], 0)
            lo, hi = halo_exchange_rdma(both, self.axis_name, h)
            return lo, hi
        return left_right_halo_exchange(left_output_halo, right_output_halo,
                                        self.axis_name)

    def __call__(self, x, spatial_axis: int = 1):
        if self.transport == "rdma":
            from apex_tpu.ops.pallas.remote_copy import halo_exchange_rdma

            # exchange only the edge STRIPS — moveaxis on (2·halo, ...)
            # strips is cheap; relayouting the full activation twice on the
            # hot conv path is not
            h = self.half_halo
            size = x.shape[spatial_axis]
            top = jax.lax.slice_in_dim(x, 0, h, axis=spatial_axis)
            bottom = jax.lax.slice_in_dim(x, size - h, size,
                                          axis=spatial_axis)
            both = jnp.concatenate([top, bottom], axis=spatial_axis)
            both = jnp.moveaxis(both, spatial_axis, 0)
            lo, hi = halo_exchange_rdma(both, self.axis_name, h)
            lo = jnp.moveaxis(lo, 0, spatial_axis)
            hi = jnp.moveaxis(hi, 0, spatial_axis)
            return jnp.concatenate([lo, x, hi], axis=spatial_axis)
        return halo_exchange_1d(x, self.half_halo, self.axis_name,
                                spatial_axis)
