"""contrib — TPU equivalents of ``apex/contrib`` packages (built out per SURVEY §2.3/2.4)."""
