"""contrib — TPU equivalents of the ``apex/contrib`` packages (SURVEY §2.3/2.4).

Per-package mapping:
- xentropy, focal_loss, index_mul_2d, clip_grad, transducer — fused ops
- group_norm (NHWC+SiLU), layer_norm (FastLayerNorm), groupbn / cudnn_gbn
  (group BatchNorm over device subgroups), bottleneck (+ spatial parallel)
- sparsity (ASP 2:4 masks + permutation search)
- optimizers (DistributedFusedAdam/LAMB ZeRO, FP16_Optimizer)
- peer_memory / nccl_p2p — ppermute-backed halo facades
- nccl_allocator / torchsched — documented no-op layers (XLA owns memory and
  scheduling; see module docstrings)
- openfold_triton — Pallas LN/MHA re-exports + FusedAdamSWA
- conv_bias_relu — fused conv epilogue shims
"""

from apex_tpu.contrib import xentropy  # noqa: F401
from apex_tpu.contrib import focal_loss  # noqa: F401
from apex_tpu.contrib import index_mul_2d  # noqa: F401
from apex_tpu.contrib import clip_grad  # noqa: F401
from apex_tpu.contrib import group_norm  # noqa: F401
from apex_tpu.contrib import layer_norm  # noqa: F401
from apex_tpu.contrib import groupbn  # noqa: F401
from apex_tpu.contrib import bottleneck  # noqa: F401
from apex_tpu.contrib import transducer  # noqa: F401
from apex_tpu.contrib import sparsity  # noqa: F401
from apex_tpu.contrib import peer_memory  # noqa: F401
from apex_tpu.contrib import nccl_p2p  # noqa: F401
from apex_tpu.contrib import nccl_allocator  # noqa: F401
from apex_tpu.contrib import torchsched  # noqa: F401
from apex_tpu.contrib import openfold_triton  # noqa: F401
from apex_tpu.contrib import conv_bias_relu  # noqa: F401
from apex_tpu.contrib import optimizers  # noqa: F401
