"""OpenFold kernel pack — TPU equivalent of ``apex/contrib/openfold_triton/``
(Triton LN tuned for AlphaFold shapes ``_layer_norm_*.py``, Triton fused MHA
``_mha_kernel.py``, ``FusedAdamSWA`` — Adam + stochastic weight averaging in
one kernel — ``fused_adam_swa.py``, autotune-cache sync ``__init__.py:32-40``).

TPU mapping: the LN and MHA Triton kernels are the framework's Pallas
LayerNorm and flash attention (re-exported here under the openfold names);
FusedAdamSWA is implemented as one fused tree update; the Triton autotune
cache sync has no analog (XLA compile cache is shared) — ``sync_triton_auto_tune_cache_across_gpus``
is a documented no-op.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    fused_layer_norm_affine as layer_norm,
)
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.ops.pallas.flash_attention import (  # noqa: F401
    flash_attention as mha,
)

_f32 = jnp.float32


def fused_adam_swa_update(params: Any, swa_params: Any, grads: Any,
                          exp_avg: Any, exp_avg_sq: Any, *, step, lr,
                          beta1: float = 0.9, beta2: float = 0.999,
                          eps: float = 1e-8, weight_decay: float = 0.0,
                          swa_decay_rate: float = 0.9,
                          swa_n_averaged=None):
    """One fused Adam step + EMA/SWA weight update (≈ FusedAdamSWA's single
    kernel over both param sets). Returns
    ``(params, swa_params, m, v, swa_n_averaged)``.

    ``swa_decay_rate`` < 1 gives EMA; with ``swa_n_averaged`` given, equal-
    weight SWA averaging is used instead (the reference supports both).
    """
    # Adam phase: reuse the framework's fused update (optimizers/functional)
    p_new, m_new, v_new = adam_update(
        params, grads, exp_avg, exp_avg_sq, step=step, lr=lr, beta1=beta1,
        beta2=beta2, eps=eps, weight_decay=weight_decay, adam_w_mode=True,
        bias_correction=True)

    # SWA/EMA epilogue (the only FusedAdamSWA-specific math)
    def swa_leaf(sw, p):
        p32 = p.astype(_f32)
        if swa_n_averaged is not None:
            n = swa_n_averaged.astype(_f32)
            sw_new = sw.astype(_f32) + (p32 - sw.astype(_f32)) / (n + 1.0)
        else:
            sw_new = (swa_decay_rate * sw.astype(_f32)
                      + (1.0 - swa_decay_rate) * p32)
        return sw_new.astype(sw.dtype)

    sw_new = jax.tree_util.tree_map(swa_leaf, swa_params, p_new)
    n_out = (swa_n_averaged + 1) if swa_n_averaged is not None else None
    return p_new, sw_new, m_new, v_new, n_out


class FusedAdamSWA:
    """Stateful facade ≈ openfold_triton.FusedAdamSWA."""

    def __init__(self, params: Any, lr: float = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 swa_decay_rate: float = 0.9):
        self._params = params
        self._swa = jax.tree_util.tree_map(lambda p: p.astype(_f32), params)
        self._m = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, _f32), params)
        self._v = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, _f32), params)
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.swa_decay_rate = swa_decay_rate
        self._step = jnp.zeros((), jnp.int32)

    def step(self, grads: Any):
        self._step = self._step + 1
        p, sw, m, v, _ = fused_adam_swa_update(
            self._params, self._swa, grads, self._m, self._v,
            step=self._step, lr=self.lr, beta1=self.betas[0],
            beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            swa_decay_rate=self.swa_decay_rate)
        self._params, self._swa, self._m, self._v = p, sw, m, v
        return p

    @property
    def parameters(self):
        return self._params

    @property
    def swa_parameters(self):
        return self._swa


def sync_triton_auto_tune_cache_across_gpus(*_a, **_kw):
    """No-op on TPU: XLA's compilation cache is process-wide and the Mosaic
    compiler has no per-device autotune state to synchronize
    (reference: openfold_triton/__init__.py:32-40)."""
