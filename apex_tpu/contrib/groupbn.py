"""Group BatchNorm NHWC — TPU equivalent of the ``bnp`` extension
(apex/contrib/csrc/groupbn/, NHWC BatchNorm + add+ReLU fusion with cross-GPU
group statistics over CUDA IPC, ``ipc.cu``/``interface.cpp:78``) and its
frontend ``apex/contrib/groupbn/batch_norm.py`` (``BatchNorm2d_NHWC`` :8 with
``bn_group``), plus the cuDNN-frontend variant ``cudnn_gbn``
(apex/contrib/cudnn_gbn/batch_norm.py:85 ``GroupBatchNorm2d``).

TPU design: the IPC peer-stat exchange becomes an ``all_gather`` restricted to
device subgroups (``axis_index_groups``) feeding the same Welford merge
SyncBatchNorm uses — one implementation covers syncbn (group = world), groupbn
(group = bn_group), and plain BN (group = 1). The fused add+ReLU epilogues
(``bn_addrelu_*``) are the ``fuse_add``/``fuse_relu`` flags below; XLA folds
them into the normalization loop.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batch_norm import sync_batch_norm_stats

_f32 = jnp.float32


def _bn_groups(world: int, bn_group: int):
    if bn_group <= 1:
        return None
    assert world % bn_group == 0
    return [list(range(i, i + bn_group))
            for i in range(0, world, bn_group)]


class BatchNorm2d_NHWC(nn.Module):
    """≈ ``apex.contrib.groupbn.BatchNorm2d_NHWC``.

    NHWC input (N, H, W, C). ``bn_group`` > 1 reduces statistics across that
    many consecutive devices of ``axis_name`` (the IPC group of the
    reference); ``fuse_relu`` / ``fuse_add`` mirror the bn_relu / bn_add_relu
    fused kernels (a residual ``z`` is added before the activation).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = None
    world_size: int = 1
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, z: Optional[jax.Array] = None,
                 use_running_average: bool = False):
        c = self.num_features
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), _f32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), _f32))
        weight = self.param("weight", nn.initializers.ones, (c,),
                            self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axis = None if (self.is_initializing()
                            or self.axis_name is None) else self.axis_name
            groups = (_bn_groups(self.world_size, self.bn_group)
                      if axis is not None else None)
            mean, var, count = sync_batch_norm_stats(
                x, (0, 1, 2), axis, axis_index_groups=groups)
            if not self.is_initializing():
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_mean.value = ((1 - self.momentum) * ra_mean.value
                                 + self.momentum * mean)
                ra_var.value = ((1 - self.momentum) * ra_var.value
                                + self.momentum * unbiased)

        y = (x.astype(_f32) - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * weight.astype(_f32) + bias.astype(_f32)
        if z is not None:  # bn_add_relu fusion
            y = y + z.astype(_f32)
        if self.fuse_relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype)


def GroupBatchNorm2d(num_features: int, group_size: int = 1,
                     **kw) -> BatchNorm2d_NHWC:
    """Factory ≈ ``apex.contrib.cudnn_gbn.GroupBatchNorm2d``
    (cudnn_gbn/batch_norm.py:85) — same semantics via the cuDNN graph API in
    the reference; identical module here (graph-API fusion is XLA's job)."""
    kw.setdefault("bn_group", group_size)
    return BatchNorm2d_NHWC(num_features=num_features, **kw)
