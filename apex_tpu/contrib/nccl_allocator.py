"""NCCL-registered allocator facade — reference: ``_apex_nccl_allocator``
(apex/contrib/csrc/nccl_allocator/NCCLAllocator.cpp:40 — a
``CUDAPluggableAllocator`` over ``ncclMemAlloc`` enabling NVLS zero-copy
collectives; frontend apex/contrib/nccl_allocator/nccl_allocator.py:18-82).

TPU status: **intentionally a no-op layer.** XLA owns all device memory and
collective buffers are registered with the ICI fabric by the compiler —
the capability the reference unlocks (zero-copy user-buffer collectives) is
the default on TPU. The context-manager API is preserved so reference call
sites (e.g. DistributedFusedAdam(nccl_ub=True) setups) port unchanged.
"""

from __future__ import annotations

import contextlib


def init():
    """≈ nccl_allocator.init() (:36-38 sets NCCL_NVLS_ENABLE) — no-op."""


def create_nccl_mem_pool(symmetric: bool = False):
    """Returns a handle object for API parity; carries no memory."""
    return object()


@contextlib.contextmanager
def nccl_mem(pool=None, enabled: bool = True, group=None):
    """≈ ``with nccl_allocator.nccl_mem():`` (:41-82) — allocations inside
    the context are already collective-ready on TPU; yields unchanged."""
    yield
