"""FastLayerNorm — TPU equivalent of the contrib ``fast_layer_norm``
(apex/contrib/csrc/layer_norm/, template-registry keyed on dtype × hidden size
768-65536, ln.h:154-176; frontend apex/contrib/layer_norm/layer_norm.py:8-59).

On TPU the Pallas LayerNorm kernel (ops/pallas/layer_norm_kernel.py) already
row-tiles any 128-lane-friendly hidden size — the per-hidden-size template
registry and multi-CTA gmem barrier (ln.h:15-66) are Mosaic's job. This module
is the contrib-API facade over the same kernel.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.normalization.fused_layer_norm import fused_layer_norm_affine


def ln_fwd(x, gamma, beta, epsilon: float = 1e-5):
    """Functional parity with ``fast_layer_norm.ln_fwd`` (ln_api.cpp:255)."""
    return fused_layer_norm_affine(x, gamma, beta, x.shape[-1], epsilon)


class FastLayerNorm(nn.Module):
    """≈ apex.contrib.layer_norm.FastLayerNorm (hidden sizes 768-65536)."""

    hidden_size: int
    eps: float = 1e-5
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (self.hidden_size,),
                       self.param_dtype)
        b = self.param("bias", nn.initializers.zeros, (self.hidden_size,),
                       self.param_dtype)
        return fused_layer_norm_affine(x, w, b, self.hidden_size, self.eps)
