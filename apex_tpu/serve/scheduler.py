"""Continuous-batching request scheduler.

The serving loop between decode steps, in pure host python (everything
device-side is the engine's fixed-shape compiled calls):

admission queue -> slot assignment (batched prefill) -> decode -> per-slot
termination (EOS / max new tokens / context full) -> eviction -> backfill
from the queue -> next decode step.

Lifecycle events ride the PR-2 telemetry bus
(:func:`apex_tpu.utils.logging.publish_event`) so a
:class:`~apex_tpu.monitor.goodput.GoodputLedger` or Telemetry JSONL mirror
picks them up with zero wiring:

- ``serve_request_admitted``  {request_id, slot, queue_wait_s}
- ``serve_queue_wait``        {seconds} — a timed goodput cause: time a
  request sat in the queue because no slot was free
- ``serve_request_completed`` {request_id, slot, new_tokens, ttft_s,
  latency_s, finish_reason}
- ``serve_request_evicted``   {request_id, slot, reason} — mid-stream
  abort or shutdown; completed requests publish completed, not evicted
- ``serve_decode_step``       {seconds, active} — per-step decode latency

Aborts can be driven deterministically by the resilience
:class:`~apex_tpu.resilience.fault_injection.FaultInjector`
(``abort_request(request_id, at_step)``): the scheduler polls
``serve_aborts_due`` before each decode step, which is how tier-1 proves a
mid-stream abort leaves every other slot's output stream bit-identical
under greedy decoding. (The engine's slot *arithmetic* is always
isolated — logits never depend on other slots' bytes — but under
``temperature > 0`` an abort changes backfill timing and with it the
shared PRNG stream, so surviving requests' *sampled* tokens may differ.)

**Tracing** (``tracer=``, a :class:`~apex_tpu.monitor.trace.Tracer`):
every request becomes ONE trace — ``queue → prefill → decode →
complete|evict|abort`` spans stamped from the scheduler's own
``perf_counter`` reads, so span durations reconcile EXACTLY with the
TTFT/latency accounting (``queue.dur == queue_wait``, ``queue + prefill
== ttft``, ``root.dur == latency``) — plus a scheduler-level trace of
per-tick ``decode_tick`` spans. With ``tracer=None`` (the default) no
span code runs at all, and tracing never touches the device either way:
the one-compile invariant holds with it on (asserted in tier-1).
``flight_recorder=`` arms a crash dump around :meth:`run`;
``memory_accountant=`` samples HBM per decode tick.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.serve.engine import Engine
from apex_tpu.utils.logging import publish_event


# eq=False: the queue holds request objects, not values — a resubmitted
# identical prompt must not alias an existing request in `in`/`remove`
@dataclasses.dataclass(eq=False)
class Request:
    """One generation request and its accounting."""

    request_id: Any
    tokens: Sequence[int]                  # prompt token ids
    max_new_tokens: int = 16
    eos_id: Optional[int] = None

    # filled in by the scheduler
    generated: List[int] = dataclasses.field(default_factory=list)
    state: str = "queued"     # queued|running|completed|evicted
    finish_reason: Optional[str] = None   # eos|length|context|aborted
    slot: Optional[int] = None
    submit_t: Optional[float] = None
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None or self.submit_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def latency_s(self) -> Optional[float]:
        if self.done_t is None or self.submit_t is None:
            return None
        return self.done_t - self.submit_t

    def record(self) -> Dict[str, Any]:
        out = {
            "request_id": self.request_id, "state": self.state,
            "finish_reason": self.finish_reason,
            "prompt_tokens": len(self.tokens),
            "new_tokens": len(self.generated),
            "generated": list(self.generated),
        }
        for k in ("ttft_s", "latency_s"):
            v = getattr(self, k)
            if v is not None:
                out[k] = round(v, 6)
        lat = self.latency_s
        if lat and self.generated:
            out["tokens_per_s"] = round(len(self.generated) / lat, 3)
        return out


@dataclasses.dataclass
class ServeStats:
    """Aggregate accounting over a scheduler run."""

    requests: List[Dict[str, Any]]
    decode_steps: int
    decode_step_s: List[float]
    decode_tokens: int          # tokens produced BY decode steps
    total_new_tokens: int       # includes each request's prefill-sampled
    wall_s: float               # first token

    def summary(self) -> Dict[str, Any]:
        lat = sorted(self.decode_step_s)

        def pct(p: float) -> float:
            if not lat:
                return 0.0
            i = min(len(lat) - 1, int(round(p * (len(lat) - 1))))
            return lat[i]

        ttfts = sorted(r["ttft_s"] for r in self.requests
                       if "ttft_s" in r)
        decode_s = sum(lat)
        return {
            "requests": len(self.requests),
            "completed": sum(r["state"] == "completed"
                             for r in self.requests),
            "evicted": sum(r["state"] == "evicted"
                           for r in self.requests),
            "decode_steps": self.decode_steps,
            "new_tokens": self.total_new_tokens,
            # decode throughput: decode-produced tokens over decode-step
            # time ONLY — prefill-sampled first tokens ride TTFT, not this
            # rate, so the bench headline tracks the decode hot path and
            # not the run's admission pattern
            "tokens_per_s": round(
                self.decode_tokens / decode_s, 3) if decode_s else 0.0,
            "p50_step_ms": round(pct(0.50) * 1e3, 3),
            "p99_step_ms": round(pct(0.99) * 1e3, 3),
            "ttft_p50_ms": round(
                (ttfts[len(ttfts) // 2] if ttfts else 0.0) * 1e3, 3),
            "wall_s": round(self.wall_s, 6),
        }


class ServeScheduler:
    """Drive an :class:`Engine` over a request stream with continuous
    batching. ``fault_injector`` (optional) supplies scripted mid-stream
    aborts; a real deployment calls :meth:`abort` directly —
    :meth:`submit` and :meth:`abort` are safe from other threads while
    :meth:`run` drives the loop (one reentrant lock serializes every
    queue/slot mutation; a cross-thread call lands between ticks)."""

    def __init__(self, engine: Engine, *, fault_injector=None,
                 tracer=None, flight_recorder=None, memory_accountant=None):
        self.engine = engine
        self.injector = fault_injector
        # observability seams (all optional; None = zero work per tick)
        self.tracer = tracer if tracer is not None and tracer.enabled \
            else None
        self.flight = flight_recorder
        self.memory = memory_accountant
        self._req_spans: Dict[Request, Dict[str, Any]] = {}
        self._sched_span = None    # root of the scheduler's tick trace
        # submit()/abort() are documented entry points for OTHER threads
        # (a serving frontend feeding the loop, a deployment cancelling a
        # request) while step() runs — every queue/slot/accounting
        # mutation takes this lock (apexlint APX002 keeps the
        # discipline). Reentrant: step()'s injector path calls abort().
        self._lock = threading.RLock()
        self.queue: Deque[Request] = collections.deque()
        self.slots: List[Optional[Request]] = \
            [None] * engine.config.num_slots
        self.done: List[Request] = []
        self.decode_steps = 0
        self.decode_step_s: List[float] = []
        self.decode_tokens = 0
        self._to_evict: set = set()   # slots freed, device reset pending
        self._t0: Optional[float] = None

    # --------------------------------------------------------- admission
    def submit(self, req: Request) -> None:
        if not len(req.tokens):
            raise ValueError(f"request {req.request_id!r}: empty prompt")
        if len(req.tokens) >= self.engine.max_len:
            raise ValueError(
                f"request {req.request_id!r}: prompt of {len(req.tokens)} "
                f"tokens leaves no room to generate under max_len="
                f"{self.engine.max_len}")
        req.submit_t = time.perf_counter()
        req.state = "queued"
        with self._lock:
            if self.tracer is not None:
                # one trace per request, rooted at submit; span stamps
                # reuse the scheduler's own clock reads so trace durations
                # and the TTFT/latency accounting are the same numbers
                root = self.tracer.begin(
                    "request", trace_id=f"request:{req.request_id}",
                    t0=req.submit_t, request_id=str(req.request_id),
                    prompt_tokens=len(req.tokens))
                self._req_spans[req] = {
                    "root": root,
                    "queue": self.tracer.begin("queue", parent=root,
                                               t0=req.submit_t)}
            self.queue.append(req)

    def _admit(self) -> None:
        """Fill free slots from the queue with ONE batched prefill call
        (per shared pow2 bucket) and record each admitted request's first
        sampled token."""
        # caller holds self._lock (step())
        free = [i for i, r in enumerate(self.slots) if r is None]
        if not free or not self.queue:
            return
        batch: Dict[int, Request] = {}
        while free and self.queue:
            slot = free.pop(0)
            req = self.queue.popleft()
            req.slot = slot
            self.slots[slot] = req
            batch[slot] = req
        now = time.perf_counter()
        for slot, req in batch.items():
            req.admit_t = now
            req.state = "running"
            wait = max(now - req.submit_t, 0.0)
            publish_event("serve_queue_wait", seconds=wait,
                          request_id=req.request_id)
            publish_event("serve_request_admitted",
                          request_id=req.request_id, slot=slot,
                          queue_wait_s=round(wait, 6))
            sp = self._req_spans.get(req)
            if sp is not None:
                self.tracer.end(sp["queue"], t1=now,
                                queue_wait_s=round(wait, 6))
                sp["prefill"] = self.tracer.begin(
                    "prefill", parent=sp["root"], t0=now, slot=slot)
        first, _last_logits, _all = self.engine.prefill(
            {slot: req.tokens for slot, req in batch.items()})
        t_first = time.perf_counter()
        for slot, req in batch.items():
            req.first_token_t = t_first
            sp = self._req_spans.get(req)
            if sp is not None:
                self.tracer.end(sp["prefill"], t1=t_first)
                # opened BEFORE _accept_token: a request finishing on its
                # prefill-sampled token still closes a decode span
                sp["decode"] = self.tracer.begin(
                    "decode", parent=sp["root"], t0=t_first, slot=slot)
            self._accept_token(req, int(first[slot]))

    # -------------------------------------------------------- lifecycle
    def _accept_token(self, req: Request, tok: int) -> None:
        # caller holds self._lock (step()/_admit())
        req.generated.append(tok)
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req, "eos")
        elif len(req.generated) >= req.max_new_tokens:
            self._finish(req, "length")
        elif len(req.tokens) + len(req.generated) >= self.engine.max_len:
            self._finish(req, "context")

    def _close_trace(self, req: Request, marker: str, reason: str) -> None:
        """End a request's trace: close any still-open lifecycle spans at
        ``done_t``, drop a terminal marker span, close the root."""
        # caller holds self._lock (_finish/_evict)
        sp = self._req_spans.pop(req, None)
        if sp is None or self.tracer is None:
            return
        t1 = req.done_t if req.done_t is not None else time.perf_counter()
        status = "ok" if marker == "complete" else "cancelled"
        for key in ("queue", "prefill", "decode"):
            span = sp.get(key)
            if span is not None:
                self.tracer.end(span, t1=t1, status=status)
        mark = self.tracer.begin(marker, parent=sp["root"], t0=t1,
                                 reason=reason)
        self.tracer.end(mark, t1=t1)
        self.tracer.end(sp["root"], t1=t1, status=status,
                        finish_reason=reason,
                        new_tokens=len(req.generated))

    def _finish(self, req: Request, reason: str) -> None:
        # caller holds self._lock (_accept_token)
        req.state = "completed"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        self.done.append(req)
        self._release(req)
        self._close_trace(req, "complete", reason)
        publish_event("serve_request_completed",
                      request_id=req.request_id, slot=req.slot,
                      new_tokens=len(req.generated), finish_reason=reason,
                      ttft_s=round(req.ttft_s or 0.0, 6),
                      latency_s=round(req.latency_s or 0.0, 6))

    def _release(self, req: Request) -> None:
        # caller holds self._lock (_finish/_evict)
        # the device-side length reset is deferred and batched: several
        # requests finishing on one tick cost ONE evict_slots call, and a
        # slot backfilled on the next tick needs no eviction at all
        # (prefill resets admitted slots itself)
        if req.slot is not None and self.slots[req.slot] is req:
            self.slots[req.slot] = None
            self._to_evict.add(req.slot)

    def _flush_evictions(self) -> None:
        """One mask-shaped engine.evict for every slot freed since the
        last flush, skipping slots a prefill already reclaimed."""
        # caller holds self._lock (step()/run())
        pending = {s for s in self._to_evict if self.slots[s] is None}
        if pending:
            self.engine.evict(sorted(pending))
        self._to_evict.clear()

    def abort(self, request_id) -> bool:
        """Mid-stream abort: evict a running request (or drop it from the
        queue). Other slots are untouched — bit-identical, by the static
        shapes of the engine. Safe to call from another thread while
        :meth:`run` is mid-tick."""
        with self._lock:
            for req in list(self.queue):
                if req.request_id == request_id:
                    self.queue.remove(req)
                    self._evict(req, "aborted")
                    return True
            for req in self.slots:
                if req is not None and req.request_id == request_id:
                    self._evict(req, "aborted")
                    return True
            return False

    def _evict(self, req: Request, reason: str) -> None:
        # caller holds self._lock (abort()/run())
        req.state = "evicted"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        self.done.append(req)
        self._release(req)
        self._close_trace(req, "abort" if reason == "aborted" else "evict",
                          reason)
        publish_event("serve_request_evicted", level="warning",
                      request_id=req.request_id, slot=req.slot,
                      reason=reason)

    # ------------------------------------------------------------- steps
    def step(self) -> bool:
        """One scheduler tick: scripted aborts -> backfill -> one decode
        step -> per-slot termination. Returns False when idle (no running
        or queued work). Holds the scheduler lock for the whole tick — a
        cross-thread submit/abort lands between ticks, never mid-tick."""
        with self._lock:
            if self._t0 is None:
                self._t0 = time.perf_counter()
            if self.injector is not None:
                for rid in self.injector.serve_aborts_due(
                        self.decode_steps):
                    self.abort(rid)
            self._admit()
            active = np.array([r is not None for r in self.slots], bool)
            if not active.any():
                return bool(self.queue)
            t0 = time.perf_counter()
            next_tokens, _logits = self.engine.decode_step(
                self.engine.last_tokens, active)
            dt = time.perf_counter() - t0
            self.decode_steps += 1
            self.decode_step_s.append(dt)
            self.decode_tokens += int(active.sum())
            if self.tracer is not None:
                if self._sched_span is None:
                    self._sched_span = self.tracer.begin(
                        "serve", trace_id="serve:scheduler", t0=t0,
                        num_slots=self.engine.config.num_slots)
                tick = self.tracer.begin("decode_tick",
                                         parent=self._sched_span, t0=t0,
                                         step=self.decode_steps,
                                         active=int(active.sum()))
                self.tracer.end(tick, t1=t0 + dt)
            if self.memory is not None:
                self.memory.tick("serve_decode", step=self.decode_steps)
            publish_event("serve_decode_step", seconds=dt,
                          active=int(active.sum()))
            for slot, req in enumerate(self.slots):
                if req is not None:
                    self._accept_token(req, int(next_tokens[slot]))
            self._flush_evictions()
            return any(r is not None
                       for r in self.slots) or bool(self.queue)

    def run(self, max_steps: Optional[int] = None) -> ServeStats:
        """Run until idle (or ``max_steps`` decode steps); returns stats.
        Unfinished requests are evicted with reason ``shutdown``. A fatal
        exception anywhere in the loop leaves a flight-recorder dump
        (when one is attached) before propagating."""
        try:
            with (self.flight.guard("serve") if self.flight is not None
                  else contextlib.nullcontext()):
                while self.step():
                    if max_steps is not None and \
                            self.decode_steps >= max_steps:
                        break
                with self._lock:
                    for req in list(self.queue) + [r for r in self.slots
                                                   if r is not None]:
                        if req in self.queue:
                            self.queue.remove(req)
                        self._evict(req, "shutdown")
                    self._flush_evictions()
        finally:
            if self.tracer is not None and self._sched_span is not None:
                self.tracer.end(self._sched_span,
                                ticks=self.decode_steps)
                self._sched_span = None
        return self.stats()

    def stats(self) -> ServeStats:
        wall = (time.perf_counter() - self._t0) if self._t0 else 0.0
        records = [r.record() for r in self.done]
        return ServeStats(requests=records,
                          decode_steps=self.decode_steps,
                          decode_step_s=list(self.decode_step_s),
                          decode_tokens=self.decode_tokens,
                          total_new_tokens=sum(r["new_tokens"]
                                               for r in records),
                          wall_s=wall)
