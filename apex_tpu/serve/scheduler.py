"""Continuous-batching request scheduler.

The serving loop between decode steps, in pure host python (everything
device-side is the engine's fixed-shape compiled calls):

admission queue -> slot assignment (batched prefill) -> decode -> per-slot
termination (EOS / max new tokens / context full) -> eviction -> backfill
from the queue -> next decode step.

The scheduler is **mesh-agnostic**: a tensor-parallel engine
(``EngineConfig(tp=N)``, docs/serving.md "Tensor-parallel decode")
exposes the identical prefill/decode/evict surface — slot state, the
queue, page tables, and the tick journal are all replicated host data,
sharding lives entirely behind the engine's compiled calls — so
everything here (admission control, deadlines, warm restart, metrics,
tracing) runs unchanged over a mesh.

Lifecycle events ride the PR-2 telemetry bus
(:func:`apex_tpu.utils.logging.publish_event`) so a
:class:`~apex_tpu.monitor.goodput.GoodputLedger` or Telemetry JSONL mirror
picks them up with zero wiring:

- ``serve_request_admitted``  {request_id, slot, queue_wait_s}
- ``serve_queue_wait``        {seconds} — a timed goodput cause: time a
  request sat in the queue because no slot was free (published at
  admission and at abort of a still-queued request, always the
  INCREMENT not yet charged — a warm-restart re-admission can never
  double-count a wait; a shed request's wait rides
  ``serve_request_rejected`` and a deadline expiry charges its whole
  span under ``serve_deadline_exceeded`` instead)
- ``serve_request_completed`` {request_id, slot, new_tokens, ttft_s,
  latency_s, finish_reason}
- ``serve_request_evicted``   {request_id, slot, reason} — mid-stream
  abort or shutdown; completed requests publish completed, not evicted
- ``serve_decode_step``       {seconds, active} — per-step decode latency
- ``serve_request_rejected``  {request_id, reason, retriable, seconds} —
  admission control: the backlog was full (``max_queue``) and the shed
  policy chose this request; ``seconds`` (time already queued, 0 for a
  reject-at-submit) is a timed loss cause
- ``serve_deadline_exceeded`` {request_id, slot, seconds, deadline_ms,
  admitted} — the per-request deadline expired (queued-but-never-admitted
  requests time out too); ``seconds`` — the whole submit-to-expiry span
  was lost serving time — is a timed loss cause
- ``serve_degraded_mode``     {entered, queue_depth, clamp} — sustained
  overload flipped graceful degradation on/off
- ``serve_engine_restart``    {restarts, resumed_slots, requeued, error}
  — a warm restart recovered the fleet after a fatal tick exception
- ``serve_prefix_hit``        {request_id, slot, hit_tokens, hit_pages,
  scanned_tokens} — an admission reused resident read-only prefix pages
  and skipped prefilling them (paged engines with ``prefix_cache``)
- ``serve_page_alloc_fail``   {seconds, queue_depth, free_page_frac} —
  admission stalled because the paged KV pool had no free pages;
  ``seconds`` (the whole head-of-queue stall window) is a timed loss
  cause distinct from plain ``serve_queue_wait`` — capacity lost to KV
  bytes, not to slot count

Aborts can be driven deterministically by the resilience
:class:`~apex_tpu.resilience.fault_injection.FaultInjector`
(``abort_request(request_id, at_step)``): the scheduler polls
``serve_aborts_due`` before each decode step, which is how tier-1 proves a
mid-stream abort leaves every other slot's output stream bit-identical
under greedy decoding. (The engine's slot *arithmetic* is always
isolated — logits never depend on other slots' bytes — but under
``temperature > 0`` an abort changes backfill timing and with it the
shared PRNG stream, so surviving requests' *sampled* tokens may differ.)

**Tracing** (``tracer=``, a :class:`~apex_tpu.monitor.trace.Tracer`):
every request becomes ONE trace — ``queue → prefill → decode →
complete|evict|abort`` spans stamped from the scheduler's own
``perf_counter`` reads, so span durations reconcile EXACTLY with the
TTFT/latency accounting (``queue.dur == queue_wait``, ``queue + prefill
== ttft``, ``root.dur == latency``) — plus a scheduler-level trace of
per-tick ``decode_tick`` spans. With ``tracer=None`` (the default) no
span code runs at all, and tracing never touches the device either way:
the one-compile invariant holds with it on (asserted in tier-1).
``flight_recorder=`` arms a crash dump around :meth:`run`;
``memory_accountant=`` samples HBM per decode tick.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.monitor.export import percentile
from apex_tpu.serve.engine import Engine
from apex_tpu.serve.spec import NGramDrafter
from apex_tpu.utils.logging import publish_event

# a request in one of these states has reached its exactly-one terminal
# status; recovery and the drain path must never touch it again
TERMINAL_STATES = ("completed", "evicted", "rejected")


# eq=False: the queue holds request objects, not values — a resubmitted
# identical prompt must not alias an existing request in `in`/`remove`
@dataclasses.dataclass(eq=False)
class Request:
    """One generation request and its accounting."""

    request_id: Any
    tokens: Sequence[int]                  # prompt token ids
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    # total latency budget from submit (monotonic sweep in step()); a
    # queued-but-never-admitted request times out against it too
    deadline_ms: Optional[float] = None
    priority: int = 0         # higher wins under the "priority" shed policy
    # optional tenant label for per-tenant accounting (ServeMetrics):
    # admission/latency/SLO series are recorded per tenant with bounded
    # cardinality; None lands under the "default" tenant
    tenant: Optional[str] = None
    # cross-replica trace propagation (serve.fleet): the controller's
    # journey trace id + the attempt span id this request should nest
    # under, so the replica's queue/prefill/decode spans link as children
    # of the fleet-level attempt. None (the default) keeps the PR-6
    # behavior: one standalone "request:<id>" trace per request
    trace_id: Optional[str] = None
    trace_parent: Optional[int] = None
    # per-request decode policy (the DecodePolicy seam,
    # apex_tpu.serve.spec): a policy spelling installed on the slot at
    # admission, so one batch mixes greedy/top_p/min_p requests on one
    # trace. None = the engine's default policy; needs
    # EngineConfig(decode_policy=...).
    policy: Optional[str] = None

    # filled in by the scheduler
    generated: List[int] = dataclasses.field(default_factory=list)
    state: str = "queued"     # queued|running|completed|evicted|rejected
    # eos|length|context|aborted|deadline|queue_full|shed|engine_failure
    finish_reason: Optional[str] = None
    slot: Optional[int] = None
    # effective token budget granted at admission (max_new_tokens, or the
    # degraded-mode clamp of it). A separate field — never a mutation of
    # max_new_tokens — so a warm-restart rollback re-admits against the
    # CURRENT overload state, not a stale clamp from the torn tick
    budget: Optional[int] = None
    # queue-wait seconds already charged to the ledger: a request
    # re-admitted after a warm-restart rollback charges only the
    # increment, so the cause totals its true final wait
    wait_charged: float = 0.0
    submit_t: Optional[float] = None
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None or self.submit_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def latency_s(self) -> Optional[float]:
        if self.done_t is None or self.submit_t is None:
            return None
        return self.done_t - self.submit_t

    def record(self) -> Dict[str, Any]:
        out = {
            "request_id": self.request_id, "state": self.state,
            "finish_reason": self.finish_reason,
            "prompt_tokens": len(self.tokens),
            "new_tokens": len(self.generated),
            "generated": list(self.generated),
        }
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.state == "rejected":
            # load shedding is a server condition, not a request defect —
            # the CLI surfaces the retriable status so clients back off
            # and resubmit instead of treating it as a hard failure
            out["retriable"] = True
        for k in ("ttft_s", "latency_s"):
            v = getattr(self, k)
            if v is not None:
                out[k] = round(v, 6)
        lat = self.latency_s
        if lat and self.generated:
            out["tokens_per_s"] = round(len(self.generated) / lat, 3)
        return out


@dataclasses.dataclass
class ServeStats:
    """Aggregate accounting over a scheduler run."""

    requests: List[Dict[str, Any]]
    decode_steps: int
    decode_step_s: List[float]
    decode_tokens: int          # tokens produced BY decode steps
    total_new_tokens: int       # includes each request's prefill-sampled
    wall_s: float               # first token
    restarts: int = 0           # warm restarts survived (recover() calls)
    admitted: int = 0           # requests that reached a slot
    prefix_hits: int = 0        # admissions that reused resident pages
    peak_resident_tokens: int = 0  # max cache tokens live at once
    # speculative decoding: active slot-steps (one slot taking one
    # decode/verify step), drafts proposed, drafts accepted — the
    # acceptance accounting behind accepted_tokens_per_step (exactly 1.0
    # on the one-token path, > 1 when speculation earns its keep)
    decode_slot_steps: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0

    def summary(self) -> Dict[str, Any]:
        # ONE percentile rule for every field: the exact nearest-rank
        # helper shared with the histogram-quantile tests (the seed used
        # len//2 indexing for TTFT but round-half-even linear indexing
        # for the step fields — two answers for "the median");
        # percentile() sorts internally, nothing here needs order
        lat = list(self.decode_step_s)
        ttfts = [r["ttft_s"] for r in self.requests if "ttft_s" in r]
        decode_s = sum(lat)
        rejected = sum(r["state"] == "rejected" for r in self.requests)
        return {
            "requests": len(self.requests),
            "completed": sum(r["state"] == "completed"
                             for r in self.requests),
            "evicted": sum(r["state"] == "evicted"
                           for r in self.requests),
            # SLO accounting: load shed + deadline misses + restarts are
            # first-class summary fields (the bench entry and the CLI
            # summary both carry them; shed_rate gates lower-is-better)
            "rejected": rejected,
            "deadline_exceeded": sum(
                r.get("finish_reason") == "deadline"
                for r in self.requests),
            "shed_rate": round(rejected / len(self.requests), 4)
            if self.requests else 0.0,
            "restarts": self.restarts,
            "decode_steps": self.decode_steps,
            "new_tokens": self.total_new_tokens,
            # paged-pool effectiveness: what fraction of admissions were
            # served partly from shared prefix pages, and the densest the
            # cache ever got (the capacity number the paged pool
            # multiplies; divide by the engine's kv_cache_bytes for the
            # bench's resident_tokens_per_hbm_byte)
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": round(self.prefix_hits / self.admitted, 4)
            if self.admitted else 0.0,
            "peak_resident_tokens": self.peak_resident_tokens,
            # decode throughput: decode-produced tokens over decode-step
            # time ONLY — prefill-sampled first tokens ride TTFT, not this
            # rate, so the bench headline tracks the decode hot path and
            # not the run's admission pattern
            "tokens_per_s": round(
                self.decode_tokens / decode_s, 3) if decode_s else 0.0,
            # speculative throughput: committed tokens per SLOT-step —
            # 1.0 exactly on the one-token path (and for a drafter that
            # never guesses right), > 1 when verified drafts multiply
            # each compiled step. check_regression gates it
            # higher-is-better; the spec workload axes make speculative
            # captures refuse to gate against one-token baselines.
            "accepted_tokens_per_step": round(
                self.decode_tokens / self.decode_slot_steps, 4)
            if self.decode_slot_steps else 0.0,
            "spec_accept_rate": round(
                self.spec_accepted / self.spec_proposed, 4)
            if self.spec_proposed else 0.0,
            "p50_step_ms": round(percentile(lat, 0.50) * 1e3, 3),
            "p99_step_ms": round(percentile(lat, 0.99) * 1e3, 3),
            "ttft_p50_ms": round(percentile(ttfts, 0.50) * 1e3, 3),
            # the tail the ttft_p99_ms SLO objective watches live — the
            # exact end-of-run value is the oracle the histogram estimate
            # is held against in tier-1
            "ttft_p99_ms": round(percentile(ttfts, 0.99) * 1e3, 3),
            "wall_s": round(self.wall_s, 6),
        }


class ServeScheduler:
    """Drive an :class:`Engine` over a request stream with continuous
    batching. ``fault_injector`` (optional) supplies scripted mid-stream
    aborts, decode-step crashes, latency spikes, and queue storms; a real
    deployment calls :meth:`abort` directly — :meth:`submit` and
    :meth:`abort` are safe from other threads while :meth:`run` drives
    the loop (one reentrant lock serializes every queue/slot mutation; a
    cross-thread call lands between ticks).

    Resilience seams (all optional, see
    :mod:`apex_tpu.serve.resilience`): ``admission=`` an
    :class:`~apex_tpu.serve.resilience.AdmissionController` bounds the
    backlog with an explicit shed policy and drives graceful
    degradation; ``journal=`` a
    :class:`~apex_tpu.serve.resilience.TickJournal` snapshots request
    metadata per tick so :meth:`recover` can warm-restart after a fatal
    tick exception without losing a single request's terminal status.
    Per-request ``deadline_ms`` is swept every tick (monotonic clocks)
    whether or not the request was ever admitted."""

    def __init__(self, engine: Engine, *, fault_injector=None,
                 tracer=None, flight_recorder=None, memory_accountant=None,
                 admission=None, journal=None, metrics=None, drafter=None):
        self.engine = engine
        self.injector = fault_injector
        self.admission = admission
        self.journal = journal
        self.restarts = 0
        # speculative decoding: the host-side drafter proposes each
        # tick's draft tokens (injectable — tests script pathological
        # drafters; correctness never depends on it, the engine's verify
        # step accepts exactly). Defaults to the n-gram prompt-lookup
        # drafter whenever the engine is built with spec_draft_len >= 1.
        self.drafter = drafter
        if self.drafter is None and engine.spec_draft_len:
            self.drafter = NGramDrafter()
        # observability seams (all optional; None = zero work per tick)
        self.tracer = tracer if tracer is not None and tracer.enabled \
            else None
        self.flight = flight_recorder
        self.memory = memory_accountant
        # live per-tenant accounting + SLO evaluation (serve.metrics
        # ServeMetrics): hooks fire at the same points the bus events
        # publish, all host-side — decode still compiles exactly once
        # with metrics armed (tier-1 scrapes a live loop and asserts)
        self.metrics = metrics
        self._req_spans: Dict[Request, Dict[str, Any]] = {}
        self._sched_span = None    # root of the scheduler's tick trace
        # submit()/abort() are documented entry points for OTHER threads
        # (a serving frontend feeding the loop, a deployment cancelling a
        # request) while step() runs — every queue/slot/accounting
        # mutation takes this lock (apexlint APX002 keeps the
        # discipline). Reentrant: step()'s injector path calls abort().
        self._lock = threading.RLock()
        self.queue: Deque[Request] = collections.deque()
        self.slots: List[Optional[Request]] = \
            [None] * engine.config.num_slots
        self.done: List[Request] = []
        self.decode_steps = 0
        self.decode_step_s: List[float] = []
        self.decode_tokens = 0
        self.decode_slot_steps = 0    # active slots × decode steps
        self.spec_proposed = 0        # draft tokens offered to verify
        self.spec_accepted = 0        # draft tokens the oracle accepted
        self.admitted = 0             # requests that reached a slot
        self.prefix_hits = 0          # admissions served partly from the
        #                               paged prefix index
        self.peak_resident_tokens = 0
        # head-of-queue page-allocation stall window (paged engines):
        # opened when admission is blocked on pool pages, closed + charged
        # to serve_page_alloc_fail when pages free up (or at drain)
        self._alloc_stall_t0: Optional[float] = None
        self._alloc_stall_req: Optional[Request] = None
        self._to_evict: set = set()   # slots freed, device reset pending
        self._t0: Optional[float] = None

    # --------------------------------------------------------- admission
    def submit(self, req: Request) -> bool:
        """Enqueue ``req``. Returns ``True`` when it entered the backlog,
        ``False`` when admission control rejected it (terminal state
        ``rejected``, retriable — the record and bus event carry it);
        malformed requests (empty/oversized prompt) still raise, they are
        caller errors, not load."""
        if not len(req.tokens):
            raise ValueError(f"request {req.request_id!r}: empty prompt")
        if len(req.tokens) >= self.engine.max_len:
            raise ValueError(
                f"request {req.request_id!r}: prompt of {len(req.tokens)} "
                f"tokens leaves no room to generate under max_len="
                f"{self.engine.max_len}")
        req.submit_t = time.perf_counter()
        req.state = "queued"
        with self._lock:
            if self.metrics is not None:
                # counted BEFORE the admission verdict: shed_frac is
                # rejected over everything that ASKED, so a
                # reject-at-submit must land in the submitted total too
                self.metrics.on_submit(req)
            if self.tracer is not None:
                # one trace per request, rooted at submit; span stamps
                # reuse the scheduler's own clock reads so trace durations
                # and the TTFT/latency accounting are the same numbers.
                # A fleet-dispatched request carries the controller's
                # journey trace id + attempt span id: this root becomes a
                # child in the cross-replica journey instead of a
                # standalone trace. Opened BEFORE the admission verdict:
                # a reject-at-submit is a bad outcome the tail-capture
                # router must be able to promote — a journey with zero
                # spans would be invisible to the trace file
                root = self.tracer.begin(
                    "request",
                    trace_id=req.trace_id or f"request:{req.request_id}",
                    parent_id=req.trace_parent,
                    t0=req.submit_t, request_id=str(req.request_id),
                    prompt_tokens=len(req.tokens))
                self._req_spans[req] = {
                    "root": root,
                    "queue": self.tracer.begin("queue", parent=root,
                                               t0=req.submit_t)}
            if self.admission is not None:
                verdict, victim = self.admission.on_submit(self.queue, req)
                if verdict == "reject":
                    reason = ("priority" if self.admission.shed_policy
                              == "priority" else "queue_full")
                    self._reject(req, reason, seconds=0.0)
                    return False
                if victim is not None:
                    # shed a queued request to make room: its (not yet
                    # charged) wait so far is lost time and the
                    # rejection says so
                    self.queue.remove(victim)
                    self._stall_head_removed(victim)
                    self._reject(victim, "shed",
                                 seconds=max(req.submit_t
                                             - victim.submit_t
                                             - victim.wait_charged, 0.0))
            self.queue.append(req)
        return True

    def _reject(self, req: Request, reason: str, *, seconds: float) -> None:
        """Terminal rejection (admission control / drain): accounted
        exactly once, retriable, with the wasted queue time as a timed
        loss cause."""
        # caller holds self._lock (submit()/drain_and_reject())
        req.state = "rejected"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        self.done.append(req)
        self._close_trace(req, "reject", reason)
        if self.metrics is not None:
            self.metrics.on_reject(req, reason)
        publish_event("serve_request_rejected", level="warning",
                      request_id=req.request_id, reason=reason,
                      retriable=True, seconds=round(seconds, 6),
                      queue_depth=len(self.queue))

    def _admit(self) -> None:
        """Fill free slots from the queue with ONE batched prefill call
        (per shared pow2 bucket) and record each admitted request's first
        sampled token.

        Paged engines are probed FIRST (``Engine.admission_page_cost``):
        a request whose page reservation does not fit stays at the head
        of the queue — FIFO order holds, the stall is charged to
        ``serve_page_alloc_fail`` once pages free up, and the batched
        prefill below can never fail allocation mid-batch."""
        # caller holds self._lock (step())
        free = [i for i, r in enumerate(self.slots) if r is None]
        if not free or not self.queue:
            return
        prior_stall = self._alloc_stall_t0
        batch: Dict[int, Request] = {}
        pending_pages = 0
        # prefix-hit pages promised to earlier batch members: a later
        # probe must not count them as evictable headroom (the engine's
        # batched prefill protects the whole batch's hits)
        pending_protect: set = set()
        stalled = False
        while free and self.queue:
            head = self.queue[0]
            # the admitted budget (degradation clamp included) sizes the
            # page reservation, so probe with the value admission grants
            budget = (self.admission.clamp(head.max_new_tokens)
                      if self.admission is not None
                      else head.max_new_tokens)
            cost = self.engine.admission_page_cost(head.tokens, budget,
                                                   pending_pages,
                                                   protect=pending_protect)
            if cost is None:
                # head-of-line page stall: no slot membership change, the
                # request waits for completions to free pages
                stalled = True
                break
            pending_pages += cost
            slot = free.pop(0)
            req = self.queue.popleft()
            req.slot = slot
            req.budget = budget
            self.slots[slot] = req
            batch[slot] = req
        if batch and prior_stall is not None:
            # the head that opened the window was admitted: charge its
            # whole blocked span (an admission that merely rides along
            # while the head STAYS blocked must not close — or reset —
            # the window, so the true start is never lost)
            self._end_alloc_stall()
        if stalled and self._alloc_stall_t0 is None:
            self._alloc_stall_t0 = time.perf_counter()
            self._alloc_stall_req = self.queue[0]
        if not batch:
            return
        now = time.perf_counter()
        for slot, req in batch.items():
            req.admit_t = now
            req.state = "running"
            if self.drafter is not None and \
                    hasattr(self.drafter, "observe"):
                # cross-request prompt lookup: admitted prompts feed the
                # drafter's corpus (host state only — admission order is
                # deterministic, so drafts are too)
                self.drafter.observe(req.tokens)
            if self.engine.policy_armed:
                # per-request policy mixing: the slot's knobs are DATA
                # on the compiled calls — installing them never retraces
                self.engine.set_slot_policy(slot, req.policy)
            wait = max(now - req.submit_t - req.wait_charged, 0.0)
            req.wait_charged += wait
            self.admitted += 1
            publish_event("serve_queue_wait", seconds=wait,
                          request_id=req.request_id)
            publish_event("serve_request_admitted",
                          request_id=req.request_id, slot=slot,
                          queue_wait_s=round(wait, 6))
            if self.metrics is not None:
                self.metrics.on_admit(req, wait)
            sp = self._req_spans.get(req)
            if sp is not None:
                self.tracer.end(sp["queue"], t1=now,
                                queue_wait_s=round(wait, 6))
                sp["prefill"] = self.tracer.begin(
                    "prefill", parent=sp["root"], t0=now, slot=slot)
        first, _last_logits, _all = self.engine.prefill(
            {slot: req.tokens for slot, req in batch.items()},
            budgets={slot: req.budget for slot, req in batch.items()})
        t_first = time.perf_counter()
        for slot, req in batch.items():
            hit = self.engine.last_prefill_stats.get(slot, {})
            if hit.get("hit_tokens"):
                # the shared-prefix win, per request: these tokens were
                # served from resident read-only pages instead of being
                # re-prefilled (the counted event the hit-rate audits)
                self.prefix_hits += 1
                publish_event("serve_prefix_hit",
                              request_id=req.request_id, slot=slot,
                              hit_tokens=hit["hit_tokens"],
                              hit_pages=hit["hit_pages"],
                              scanned_tokens=hit["scanned"])
                if self.metrics is not None:
                    self.metrics.on_prefix_hit(req, hit["hit_tokens"])
            req.first_token_t = t_first
            sp = self._req_spans.get(req)
            if sp is not None:
                self.tracer.end(sp["prefill"], t1=t_first)
                # opened BEFORE _accept_token: a request finishing on its
                # prefill-sampled token still closes a decode span
                sp["decode"] = self.tracer.begin(
                    "decode", parent=sp["root"], t0=t_first, slot=slot)
            self._accept_token(req, int(first[slot]))

    def _end_alloc_stall(self) -> None:
        """Close an open page-allocation stall window: the whole span the
        queue head spent blocked on pool pages is lost serving time, and
        the cause says so (a plain ``serve_queue_wait`` would blame slot
        scarcity for what is a KV-capacity shortage)."""
        # caller holds self._lock (_admit()/drain_and_reject()/run())
        if self._alloc_stall_t0 is None:
            return
        stalled = max(time.perf_counter() - self._alloc_stall_t0, 0.0)
        self._alloc_stall_t0 = None
        self._alloc_stall_req = None
        publish_event("serve_page_alloc_fail", level="warning",
                      seconds=round(stalled, 6),
                      queue_depth=len(self.queue),
                      free_page_frac=round(self.engine.free_page_frac, 4))

    def _stall_head_removed(self, req: Request) -> None:
        """A queued request left the queue by a NON-admission path (shed,
        abort, deadline expiry): when it is the head whose page stall
        opened the window, close-and-charge the window now — the span it
        spent blocked on pages is real lost capacity, but the idle span
        after its departure is not, and a window left open here would
        charge that whole idle span to ``serve_page_alloc_fail`` at the
        next admission."""
        # caller holds self._lock (submit()/abort()/_sweep_deadlines())
        if req is self._alloc_stall_req:
            self._end_alloc_stall()

    # -------------------------------------------------------- lifecycle
    def _accept_token(self, req: Request, tok: int) -> None:
        # caller holds self._lock (step()/_admit())
        req.generated.append(tok)
        budget = req.budget if req.budget is not None \
            else req.max_new_tokens
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req, "eos")
        elif len(req.generated) >= budget:
            self._finish(req, "length")
        elif len(req.tokens) + len(req.generated) >= self.engine.max_len:
            self._finish(req, "context")

    # ------------------------------------------------------- speculation
    def _build_drafts(self, spec_k: int):
        """Each active slot's host draft for this tick, clamped so the
        verify commit (up to ``draft_len + 1`` tokens) can never overrun
        the request's token budget, the model context, or the slot's
        admitted cache capacity — a fully clamped slot runs a plain
        one-token step on the SAME verify trace (``draft_len`` is
        data)."""
        # caller holds self._lock (step())
        b = self.engine.config.num_slots
        drafts = np.zeros((b, spec_k), np.int32)
        draft_lens = np.zeros((b,), np.int32)
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            budget = req.budget if req.budget is not None \
                else req.max_new_tokens
            room = min(budget - len(req.generated),
                       self.engine.max_len - len(req.tokens)
                       - len(req.generated),
                       self.engine.spec_headroom(slot))
            k = max(min(spec_k, room - 1), 0)
            if k:
                d = self.drafter.draft(
                    list(req.tokens) + req.generated, k)[:k]
                draft_lens[slot] = len(d)
                drafts[slot, :len(d)] = np.asarray(d, np.int32)
        return drafts, draft_lens

    def _accept_spec(self, committed, counts, draft_lens) -> int:
        """Commit each slot's verified token run through the one-token
        acceptance path — EOS/budget/context checks run per TOKEN in
        commit order, so deadline/evict/journey accounting counts
        tokens, not steps. Tokens the engine committed after a terminal
        state are discarded (the slot is released and its cache rows
        evicted regardless). Publishes the per-step draft acceptance
        aggregates and feeds the metrics hooks; returns the number of
        tokens that actually entered streams."""
        # caller holds self._lock (step())
        appended = 0
        acc_total = 0
        rej_total = 0
        tenant_tokens: Dict[Any, int] = {}
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            n = int(counts[slot])
            proposed = int(draft_lens[slot])
            accepted = max(n - 1, 0)   # committed minus the bonus token
            acc_total += accepted
            rej_total += max(proposed - accepted, 0)
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            if self.metrics is not None:
                self.metrics.on_spec(req, proposed=proposed,
                                     accepted=accepted)
            took = 0
            for tok in committed[slot][:n]:
                took += 1
                self._accept_token(req, int(tok))
                if req.state != "running":
                    break
            appended += took
            tenant_tokens[req.tenant] = \
                tenant_tokens.get(req.tenant, 0) + took
        if self.metrics is not None and tenant_tokens:
            self.metrics.on_spec_step(tenant_tokens)
        if acc_total:
            publish_event("serve_spec_draft_accepted", tokens=acc_total,
                          step=self.decode_steps)
        if rej_total:
            publish_event("serve_spec_draft_rejected", tokens=rej_total,
                          step=self.decode_steps)
        return appended

    def _close_trace(self, req: Request, marker: str, reason: str) -> None:
        """End a request's trace: close any still-open lifecycle spans at
        ``done_t``, drop a terminal marker span, close the root."""
        # caller holds self._lock (_finish/_evict)
        sp = self._req_spans.pop(req, None)
        if sp is None or self.tracer is None:
            return
        t1 = req.done_t if req.done_t is not None else time.perf_counter()
        status = "ok" if marker == "complete" else "cancelled"
        for key in ("queue", "prefill", "decode"):
            span = sp.get(key)
            if span is not None:
                self.tracer.end(span, t1=t1, status=status)
        mark = self.tracer.begin(marker, parent=sp["root"], t0=t1,
                                 reason=reason)
        self.tracer.end(mark, t1=t1)
        # the EXACT rounded accounting values ride the root close as
        # attrs (the same numbers record()/summary() carry), so
        # tools/trace_explain.py reconciles bit-for-bit instead of
        # re-deriving them from microsecond-rounded stamps
        extra: Dict[str, Any] = {}
        if req.ttft_s is not None:
            extra["ttft_s"] = round(req.ttft_s, 6)
        if req.latency_s is not None:
            extra["latency_s"] = round(req.latency_s, 6)
        self.tracer.end(sp["root"], t1=t1, status=status,
                        state=req.state, finish_reason=reason,
                        new_tokens=len(req.generated), **extra)

    def _finish(self, req: Request, reason: str) -> None:
        # caller holds self._lock (_accept_token)
        req.state = "completed"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        self.done.append(req)
        self._release(req)
        self._close_trace(req, "complete", reason)
        if self.metrics is not None:
            self.metrics.on_complete(req)
        publish_event("serve_request_completed",
                      request_id=req.request_id, slot=req.slot,
                      new_tokens=len(req.generated), finish_reason=reason,
                      ttft_s=round(req.ttft_s or 0.0, 6),
                      latency_s=round(req.latency_s or 0.0, 6))

    def _release(self, req: Request) -> None:
        # caller holds self._lock (_finish/_evict)
        # the device-side length reset is deferred and batched: several
        # requests finishing on one tick cost ONE evict_slots call, and a
        # slot backfilled on the next tick needs no eviction at all
        # (prefill resets admitted slots itself)
        if req.slot is not None and self.slots[req.slot] is req:
            self.slots[req.slot] = None
            self._to_evict.add(req.slot)

    def _flush_evictions(self) -> None:
        """One mask-shaped engine.evict for every slot freed since the
        last flush, skipping slots a prefill already reclaimed."""
        # caller holds self._lock (step()/run())
        pending = {s for s in self._to_evict if self.slots[s] is None}
        if pending:
            self.engine.evict(sorted(pending))
        self._to_evict.clear()

    # ------------------------------------------------- fleet hooks
    def load(self) -> int:
        """Queued + in-slot requests — the fleet router's load signal
        (and its drain-completion probe). Safe from any thread."""
        with self._lock:
            return len(self.queue) + sum(r is not None
                                         for r in self.slots)

    def progress(self):
        """``(load, done_count)`` under ONE lock acquisition — the fleet
        worker reads this between ticks and publishes it as a lock-free
        snapshot (:attr:`EngineReplica` plain-rebind), so the
        controller's per-pump probes never contend with the scheduler
        lock :meth:`step` holds across a whole tick."""
        with self._lock:
            return (len(self.queue) + sum(r is not None
                                          for r in self.slots),
                    len(self.done))

    def done_since(self, cursor: int):
        """Terminal requests appended to :attr:`done` since ``cursor``,
        plus the new cursor — the fleet router's harvest hook. Read
        under the scheduler lock; the returned :class:`Request` objects
        are terminal and never mutate again, so the caller may inspect
        them lock-free."""
        with self._lock:
            return list(self.done[cursor:]), len(self.done)

    def pop_queued(self, request_id) -> Optional[Request]:
        """Remove and return a still-queued request WITHOUT a terminal
        status — the fleet drain/migrate hook: the request is about to
        be re-submitted to another replica, so terminal-accounting it
        here (the way :meth:`abort` does) would give it two records
        fleet-wide. Its wasted queue time still lands on the ledger
        (``serve_queue_wait`` — the wait was real whichever replica
        finally serves it). Returns ``None`` when the request is not
        queued (already admitted — the caller lets it finish in place —
        or already terminal)."""
        with self._lock:
            req = self._remove_queued(request_id)
            if req is not None:
                self._close_trace(req, "evict", "migrated")
            return req

    def export_prefix_pages(self, tokens):
        """Thread-safe export of the engine's indexed prefix pages for
        ``tokens`` (:meth:`Engine.export_prefix_pages`) — the
        disaggregation controller calls this on a PREFILL replica from
        the control thread while the replica's worker may be mid-tick,
        so the read takes the scheduler lock the tick holds."""
        with self._lock:
            return self.engine.export_prefix_pages(tokens)

    def import_prefix_pages(self, payloads):
        """Thread-safe install of certified migrated pages into the
        engine's pool (:meth:`Engine.import_prefix_pages`) — the
        disaggregation controller calls this on a DECODE replica from
        the control thread; the lock serializes the pool/index/cache
        mutation against the worker's own admissions."""
        with self._lock:
            return self.engine.import_prefix_pages(payloads)

    def _remove_queued(self, request_id) -> Optional[Request]:
        """Take a request out of the queue and publish its uncharged
        wait — the ONE queue-exit bookkeeping (abort and pop_queued
        share it, so migration accounting can never diverge from abort
        accounting); the caller owns the terminal/trace handling."""
        # caller holds self._lock (abort()/pop_queued())
        for req in list(self.queue):
            if req.request_id == request_id:
                self.queue.remove(req)
                self._stall_head_removed(req)
                publish_event(
                    "serve_queue_wait",
                    seconds=max(time.perf_counter() - req.submit_t
                                - req.wait_charged, 0.0),
                    request_id=req.request_id)
                return req
        return None

    def abort(self, request_id) -> bool:
        """Mid-stream abort: evict a running request (or drop it from the
        queue). Other slots are untouched — bit-identical, by the static
        shapes of the engine. Safe to call from another thread while
        :meth:`run` is mid-tick.

        A still-queued (never-admitted) request is removed from the
        queue, accounted exactly once, and publishes the same abort
        event as an in-slot one — plus a ``serve_queue_wait`` record for
        the time it sat waiting, which was lost either way and must land
        under a goodput cause (admission publishes it for admitted
        requests; before this, an aborted queued request's wait simply
        vanished from the ledger)."""
        with self._lock:
            req = self._remove_queued(request_id)
            if req is not None:
                self._evict(req, "aborted")
                return True
            for req in self.slots:
                if req is not None and req.request_id == request_id:
                    self._evict(req, "aborted")
                    return True
            return False

    def _sweep_deadlines(self, now: float) -> None:
        """Expire every request whose ``deadline_ms`` has elapsed —
        queued-but-never-admitted requests time out too (a client that
        stopped waiting must not be prefilled). Monotonic clock deltas
        only (apexlint APX005): ``submit_t`` is a ``perf_counter``
        stamp."""
        # caller holds self._lock (step())
        for req in list(self.queue):
            if req.deadline_ms is not None and \
                    (now - req.submit_t) * 1e3 > req.deadline_ms:
                self.queue.remove(req)
                self._stall_head_removed(req)
                self._expire(req, now)
        for req in list(self.slots):
            if req is not None and req.deadline_ms is not None and \
                    (now - req.submit_t) * 1e3 > req.deadline_ms:
                self._expire(req, now)

    def _expire(self, req: Request, now: float) -> None:
        # caller holds self._lock (_sweep_deadlines())
        waited = max(now - req.submit_t, 0.0)
        req.state = "evicted"
        req.finish_reason = "deadline"
        req.done_t = now
        self.done.append(req)
        self._release(req)
        self._close_trace(req, "deadline", "deadline")
        if self.metrics is not None:
            self.metrics.on_deadline(req)
        # the whole submit-to-expiry span is lost serving time: the
        # client gave up, whatever was computed is discarded
        publish_event("serve_deadline_exceeded", level="warning",
                      request_id=req.request_id, slot=req.slot,
                      seconds=round(waited, 6),
                      deadline_ms=req.deadline_ms,
                      new_tokens=len(req.generated),
                      admitted=req.admit_t is not None)

    def _evict(self, req: Request, reason: str) -> None:
        # caller holds self._lock (abort()/run())
        req.state = "evicted"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        self.done.append(req)
        self._release(req)
        self._close_trace(req, "abort" if reason == "aborted" else "evict",
                          reason)
        if self.metrics is not None:
            self.metrics.on_evict(req, reason)
        publish_event("serve_request_evicted", level="warning",
                      request_id=req.request_id, slot=req.slot,
                      reason=reason)

    # ------------------------------------------------------------- steps
    def step(self) -> bool:
        """One scheduler tick: scripted faults -> deadline sweep ->
        backfill -> one decode step -> per-slot termination -> journal.
        Returns False when idle (no running or queued work). Holds the
        scheduler lock for the whole tick — a cross-thread submit/abort
        lands between ticks, never mid-tick."""
        with self._lock:
            if self._t0 is None:
                self._t0 = time.perf_counter()
            if self.journal is not None and self.journal.snapshot is None:
                # pre-traffic baseline: a crash on the very first decode
                # step still has a consistent state to recover to
                self._journal_tick()
            if self.injector is not None:
                for rid in self.injector.serve_aborts_due(
                        self.decode_steps):
                    self.abort(rid)
                for spec in self.injector.serve_storm_due(
                        self.decode_steps):
                    # a scripted client burst: storms go through the
                    # normal submit path so admission control is what is
                    # actually under test
                    self.submit(Request(**spec))
            self._sweep_deadlines(time.perf_counter())
            if self.admission is not None:
                if self.memory is not None:
                    self.admission.note_hbm(self.memory.last)
                if self.engine.paged:
                    # pool occupancy is the serving-side memory-pressure
                    # signal (the allocator stats above are process-wide):
                    # a drained free list degrades admitted budgets just
                    # like a deep queue does
                    self.admission.note_pool(self.engine.free_page_frac)
                flip = self.admission.on_tick(len(self.queue))
                if flip is not None:
                    publish_event(
                        "serve_degraded_mode", level="warning",
                        entered=flip, queue_depth=len(self.queue),
                        clamp=self.admission.degraded_max_new_tokens)
            self._admit()
            self.peak_resident_tokens = max(
                self.peak_resident_tokens, self.engine.resident_tokens)
            active = np.array([r is not None for r in self.slots], bool)
            if not active.any():
                # no decode step will run this tick, so the end-of-tick
                # eviction flush below is unreachable — flush HERE or a
                # paged engine livelocks: pages of slots freed by the
                # deadline sweep / an abort stay refcounted, the queue
                # head's page probe keeps failing, and no decode step
                # ever advances decode_steps toward max_steps
                self._flush_evictions()
                # idle ticks still move the occupancy gauges and the SLO
                # windows: a deadline storm expiring queued-only requests
                # must be able to breach (and later recover) with zero
                # decode steps run
                self._metrics_tick(None, 0)
                if self.journal is not None:
                    self._journal_tick()
                return bool(self.queue)
            t0 = time.perf_counter()
            if self.injector is not None:
                spike = self.injector.latency_spike_due(self.decode_steps)
                if spike:
                    time.sleep(spike)  # a stalled device/host hiccup
                self.injector.maybe_crash_decode(self.decode_steps)
            spec_k = self.engine.spec_draft_len
            if spec_k and self.drafter is not None:
                # speculative tick: host drafts -> ONE compiled verify
                # step for every slot (the multi-token analog of
                # decode_step — same trace under any churn)
                drafts, draft_lens = self._build_drafts(spec_k)
                committed, counts = self.engine.spec_decode_step(
                    self.engine.last_tokens, drafts, draft_lens, active)
            else:
                next_tokens, _logits = self.engine.decode_step(
                    self.engine.last_tokens, active)
            dt = time.perf_counter() - t0
            self.decode_steps += 1
            self.decode_step_s.append(dt)
            self.decode_slot_steps += int(active.sum())
            # second residency sample, AFTER the append: a completing
            # slot's final token is resident right now and gone before
            # the next tick's sample — without this the true peak is
            # systematically one token per completion low
            self.peak_resident_tokens = max(
                self.peak_resident_tokens, self.engine.resident_tokens)
            if self.tracer is not None:
                if self._sched_span is None:
                    self._sched_span = self.tracer.begin(
                        "serve", trace_id="serve:scheduler", t0=t0,
                        num_slots=self.engine.config.num_slots)
                tick = self.tracer.begin("decode_tick",
                                         parent=self._sched_span, t0=t0,
                                         step=self.decode_steps,
                                         active=int(active.sum()))
                self.tracer.end(tick, t1=t0 + dt)
            if self.memory is not None:
                self.memory.tick("serve_decode", step=self.decode_steps)
            publish_event("serve_decode_step", seconds=dt,
                          active=int(active.sum()))
            if spec_k and self.drafter is not None:
                self.decode_tokens += self._accept_spec(
                    committed, counts, draft_lens)
            else:
                self.decode_tokens += int(active.sum())
                for slot, req in enumerate(self.slots):
                    if req is not None:
                        self._accept_token(req, int(next_tokens[slot]))
            self._flush_evictions()
            # AFTER the accept loop: completions landing on this tick
            # feed the SLO windows before this tick's evaluate() — a
            # breach crossed by the final tick's events must publish
            # before run() exits, and the exit snapshot's burn gauges
            # must reflect this tick, not the previous one
            self._metrics_tick(dt, int(active.sum()))
            if self.journal is not None:
                # end-of-tick: the state is consistent again — this is
                # the snapshot a crash in the NEXT tick rolls back to
                self._journal_tick()
            return any(r is not None
                       for r in self.slots) or bool(self.queue)

    def _metrics_tick(self, dt_s: Optional[float], active: int) -> None:
        """Feed the live-metrics layer one tick: the decode-step sample
        (None on idle ticks), occupancy gauges, and the SLO evaluation —
        all host-side, nothing touches the device."""
        # caller holds self._lock (step())
        if self.metrics is None:
            return
        self.metrics.on_tick(
            dt_s=dt_s, active=active, queue_depth=len(self.queue),
            resident_tokens=self.engine.resident_tokens,
            free_page_frac=self.engine.free_page_frac)

    # --------------------------------------------- journal / warm restart
    def _journal_tick(self) -> None:
        """Record the current consistent state into the journal: request
        metadata copies (a half-applied crashing tick can never poison
        them) plus the engine's sampling state and PRNG key."""
        # caller holds self._lock (step())
        self.journal.record({
            "decode_steps": self.decode_steps,
            "decode_tokens": self.decode_tokens,
            "decode_slot_steps": self.decode_slot_steps,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "engine": self.engine.sampling_state(),
            # page accounting (None for slot engines): page tables +
            # refcounts, for the postmortem journal and the paged-recovery
            # integrity story — recovery itself re-derives allocation by
            # re-prefilling, sharing whatever prefix pages survived
            "paging": self.engine.paging_state(),
            "slots": [None if r is None else {
                "req": r, "request_id": r.request_id,
                # the prompt is immutable for the request's lifetime —
                # a reference is crash-safe; only `generated` changes
                # between ticks and needs the per-tick copy
                "prompt": r.tokens,
                "generated": list(r.generated),
            } for r in self.slots],
            "queued": list(self.queue),
        })

    def recover(self, error: Optional[str] = None) -> int:
        """Warm restart after a fatal tick exception: roll back to the
        journal's last consistent snapshot without losing any request.

        Device state is rebuilt by re-prefilling each surviving slot's
        accepted prefix (prompt + all but the last generated token)
        through the existing bucketed prefill — bit-exact by the PR-5
        prefill/decode invariant — then restoring the journaled sampling
        state (PRNG key, last tokens), so surviving streams continue
        exactly where the snapshot left them. Compiled executables are
        reused: ``Engine.decode_traces`` does not grow (tier-1 asserts).
        Requests that reached a terminal status during the crashing tick
        keep it (their events already published — exactly-once); every
        other in-flight request resumes, and queued ones (including
        arrivals after the snapshot) are requeued in order. Returns the
        number of slots re-prefilled."""
        with self._lock:
            if self.journal is None or self.journal.snapshot is None:
                raise RuntimeError(
                    "recover() needs ServeScheduler(journal=TickJournal"
                    "(...)) — there is no snapshot to roll back to")
            snap = self.journal.snapshot
            self.restarts += 1
            # state drop; compiled artifacts kept. Paged engines with a
            # prefix index keep the pool bytes + index too: shared prefix
            # pages are read-only (a crash cannot have torn them), so
            # recovery re-prefills ONLY the unshared pages of each
            # surviving slot — the re-prefill below hits the index for
            # the prompt portion and pays just the generated tail
            self.engine.reset(keep_prefix_cache=True)
            snap_ids = {id(ent["req"]) for ent in snap["slots"]
                        if ent is not None}
            # requeue: journaled order first, then post-snapshot arrivals
            # that got ADMITTED during the crashing tick (popped from the
            # live queue into a slot the snapshot never saw — they must
            # roll back to queued, not vanish), then the rest of the live
            # queue — nothing is dropped, and relative submit order holds
            requeue: List[Request] = []
            seen = set()
            for req in snap["queued"]:
                seen.add(id(req))
                if req.state in TERMINAL_STATES:
                    continue
                self._rollback_to_queued(req)
                requeue.append(req)
            for req in list(self.slots):
                if req is None or id(req) in snap_ids \
                        or id(req) in seen \
                        or req.state in TERMINAL_STATES:
                    continue
                seen.add(id(req))
                self._rollback_to_queued(req)
                requeue.append(req)
            for req in list(self.queue):
                if id(req) in seen or req.state in TERMINAL_STATES:
                    continue
                self._rollback_to_queued(req)
                requeue.append(req)
            self.queue = collections.deque(requeue)
            self.slots = [None] * self.engine.config.num_slots
            self._to_evict.clear()
            # an open page-stall window is void: the rollback re-derives
            # allocation, and the requeued head's wait is charged as
            # queue time at its (re-)admission
            self._alloc_stall_t0 = None
            self._alloc_stall_req = None
            prefixes: Dict[int, List[int]] = {}
            budgets: Dict[int, int] = {}
            cacheable: Dict[int, int] = {}
            for slot, ent in enumerate(snap["slots"]):
                if ent is None:
                    continue
                req = ent["req"]
                if req.state in TERMINAL_STATES:
                    continue  # finished mid-crash-tick: status stands
                req.state = "running"
                req.slot = slot
                req.generated = list(ent["generated"])
                self.slots[slot] = req
                # the cache must hold prompt + generated[:-1]: the last
                # generated token is the NEXT decode input, not resident
                prefixes[slot] = list(ent["prompt"]) + req.generated[:-1]
                # page reservation for the REMAINING stream: the admitted
                # budget minus tokens already generated (the re-prefilled
                # tail counts as resident, not budget)
                budget = req.budget if req.budget is not None \
                    else req.max_new_tokens
                budgets[slot] = max(budget - len(req.generated) + 1, 1)
                # only the original prompt may enter the prefix index —
                # generated-token pages are one stream's state, not a
                # shareable prefix, and must not pin the index
                cacheable[slot] = len(ent["prompt"])
            if prefixes:
                # ONE prefill call, exactly like _admit: the engine pads
                # every prefix to the shared pow2 bucket itself, so a
                # mixed-length recovery pays at most one fresh bucket
                # trace, never one per length class
                self.engine.prefill(prefixes, budgets=budgets,
                                    cacheable=cacheable)
            self.engine.restore_sampling_state(snap["engine"],
                                               slots=sorted(prefixes))
            self.decode_steps = snap["decode_steps"]
            del self.decode_step_s[self.decode_steps:]
            self.decode_tokens = snap["decode_tokens"]
            # spec counters ride the same snapshot (PR-18); .get keeps
            # journals from pre-spec builds replayable
            self.decode_slot_steps = snap.get("decode_slot_steps", 0)
            self.spec_proposed = snap.get("spec_proposed", 0)
            self.spec_accepted = snap.get("spec_accepted", 0)
            publish_event("serve_engine_restart", level="warning",
                          restarts=self.restarts,
                          resumed_slots=len(prefixes),
                          requeued=len(self.queue),
                          error=error or "")
            return len(prefixes)

    def _rollback_to_queued(self, req: Request) -> None:
        """Return a (possibly mid-crash-tick admitted) request to the
        queue: progress from the torn tick is discarded — under greedy
        decoding the replay regenerates it bit-for-bit."""
        # caller holds self._lock (recover())
        req.state = "queued"
        req.slot = None
        req.generated.clear()
        req.admit_t = None
        req.first_token_t = None
        # the torn tick's admitted budget is void: re-admission grants a
        # fresh one against the CURRENT degradation state, so a clamp
        # from a past overload never outlives the overload
        req.budget = None
        sp = self._req_spans.get(req)
        if sp is not None:
            prefill = sp.pop("prefill", None)
            if prefill is not None:
                # it was admitted during the crashing tick: close the
                # torn lifecycle spans and reopen the queue wait
                self.tracer.end(prefill, status="cancelled", restart=True)
                decode = sp.pop("decode", None)
                if decode is not None:
                    self.tracer.end(decode, status="cancelled",
                                    restart=True)
                sp["queue"] = self.tracer.begin("queue", parent=sp["root"],
                                                restart=True)

    def drain_and_reject(self, reason: str = "engine_failure") -> int:
        """Terminal-status every still-live request WITHOUT touching the
        (presumed dead) engine: queued requests are rejected (retriable
        — a healthy replica can serve them), in-flight ones evicted.
        The supervisor calls this when the restart budget is exhausted;
        after it, every submitted request has exactly one terminal
        status. Returns the number drained."""
        n = 0
        with self._lock:
            self._end_alloc_stall()
            now = time.perf_counter()
            while self.queue:
                req = self.queue.popleft()
                self._reject(req, reason,
                             seconds=max(now - req.submit_t
                                         - req.wait_charged, 0.0))
                n += 1
            for slot, req in enumerate(self.slots):
                if req is None:
                    continue
                # clear the slot FIRST so _release never schedules a
                # device-side eviction on the dead engine
                self.slots[slot] = None
                self._evict(req, reason)
                n += 1
            self._to_evict.clear()
        return n

    def run(self, max_steps: Optional[int] = None) -> ServeStats:
        """Run until idle (or ``max_steps`` decode steps); returns stats.
        Unfinished requests are evicted with reason ``shutdown``. A fatal
        exception anywhere in the loop leaves a flight-recorder dump
        (when one is attached) before propagating."""
        try:
            with (self.flight.guard("serve") if self.flight is not None
                  else contextlib.nullcontext()):
                while self.step():
                    if max_steps is not None and \
                            self.decode_steps >= max_steps:
                        break
                with self._lock:
                    self._end_alloc_stall()
                    for req in list(self.queue) + [r for r in self.slots
                                                   if r is not None]:
                        if req in self.queue:
                            self.queue.remove(req)
                        self._evict(req, "shutdown")
                    self._flush_evictions()
                    # the shutdown drain's evictions observed SLO events
                    # with no tick left to evaluate them — one final
                    # tick keeps the exit snapshot's gauges and breach
                    # state current with everything above
                    self._metrics_tick(None, 0)
        finally:
            if self.tracer is not None and self._sched_span is not None:
                self.tracer.end(self._sched_span,
                                ticks=self.decode_steps)
                self._sched_span = None
        return self.stats()

    def stats(self) -> ServeStats:
        wall = (time.perf_counter() - self._t0) if self._t0 else 0.0
        records = [r.record() for r in self.done]
        return ServeStats(requests=records,
                          decode_steps=self.decode_steps,
                          decode_step_s=list(self.decode_step_s),
                          decode_tokens=self.decode_tokens,
                          total_new_tokens=sum(r["new_tokens"]
                                               for r in records),
                          wall_s=wall,
                          restarts=self.restarts,
                          admitted=self.admitted,
                          prefix_hits=self.prefix_hits,
                          peak_resident_tokens=self.peak_resident_tokens,
                          decode_slot_steps=self.decode_slot_steps,
                          spec_proposed=self.spec_proposed,
                          spec_accepted=self.spec_accepted)
