"""AOT prefill + one-jit decode over the static KV cache (slot or paged).

The engine owns three compiled artifacts and NOTHING else touches the
device:

- ``decode_step`` — ONE jitted function, ``[num_slots]`` tokens in,
  ``[num_slots]`` sampled tokens out. Admission, completion, eviction, and
  backfill all happen by changing *values* (masks, lengths, page-table
  rows), so the jit cache holds exactly one entry for the life of the
  engine — asserted by tier-1 (``Engine.decode_traces``).
- ``prefill`` — a ``lax.scan`` of the *same* single-token forward over the
  prompt positions, at the same ``[num_slots]`` width (non-admitted slots
  mask their writes). One compile per pow2 prompt-length bucket. Because
  prefill and decode share the forward at identical shapes, an
  incrementally decoded token's logits are bit-identical (fp32) to the
  same token's logits under full-sequence prefill — there is no
  "prefill path" to drift from.
- ``evict`` — a mask-shaped length reset (kv_cache.evict_slots), one
  compile total.

**Paged mode** (``EngineConfig(page_size=...)``) swaps the per-slot
``max_len`` reservation for a shared block pool
(:class:`~apex_tpu.serve.kv_cache.PagedKVCache`): the per-slot page table
is DATA threaded through the same compiled calls, host-side allocation
lives in :mod:`apex_tpu.serve.paging`, and the attention chunk arithmetic
is shared with the slot path — so a paged engine is **bit-exact in fp32
against the slot engine** on identical request traces at the same
``block_k`` (the slot cache is the oracle in tier-1; the default chunk
is tuned per layout, so pin ``block_k`` for bitwise comparison). With ``prefix_cache=True`` a hash-based prefix
index shares read-only prompt pages across requests: a request whose
prompt prefix is already resident skips prefill for those pages (the
scan covers only the tail; a partially-used boundary page is
copied-on-write first), which is what removes the repeated fleet-wide
system-prompt prefill. Pages for a request's whole admitted budget are
reserved at admission, so decode can never page-fault mid-stream —
conservative, but it keeps admission the single choke point
(``serve_page_alloc_fail`` accounts the stall when the pool is the
bottleneck).

**Tensor-parallel mode** (``EngineConfig(tp=N)``) shards the whole
engine over a 1-D ``NamedSharding`` mesh on the **head axis**: params
(q/k/v columns, output-projection rows, MLP slices — see
:mod:`apex_tpu.serve.tp`) and both cache layouts' K/V bytes shard per
head block, while ``lengths``, the page table, and every scheduler-side
structure stay replicated data — so the allocator, prefix index,
journal, and scheduler are mesh-agnostic and the one-compile invariant
becomes **one compile per mesh shape** (``decode_traces`` still reads
1). The per-rank forward runs under ``shard_map`` inside the SAME
jitted decode step and prefill scan; per-layer cross-rank sync is
``tp_sync="exact"`` (all-gather concatenation — **bit-identical in fp32
to the single-chip engine at equal ``block_k``**, greedy and sampled;
the tier-1 oracle), ``"overlap"`` (TokenWeave: the two per-layer
all-reduces each split into slot halves interleaved with norm/residual
compute so async collectives hide behind compute on real chips), or
``"relaxed"`` (partially-synchronized activations: ONE deferred
all-reduce per layer; opt-in approximation). Sampling runs on the full
replicated logits outside ``shard_map``, so the PRNG key path — and
with it sampled-stream replay — is identical to a single chip.

Sampling (temperature / top-k, greedy at ``temperature=0``) runs inside
the jitted step under a threaded PRNG key: the key is part of engine
state, split in-graph, and returned — a fixed seed replays a stream
bit-for-bit.

**Speculative mode** (``EngineConfig(spec_draft_len=K)``) adds a FOURTH
compiled artifact: ``verify`` — structurally the prefill scan over
``K + 1`` positions (column 0 re-feeds the slot's last committed token,
columns 1..K are host-side draft guesses from
:class:`~apex_tpu.serve.spec.NGramDrafter`). Acceptance is exact and
in-graph: position ``p``'s logits produce the target policy's own next
token, a draft is committed iff it equals that target, and the leading
match run plus one bonus token advance the slot — ``set_lengths``
truncation rolls back every rejected draft row (the evict mechanism:
K/V beyond ``lengths`` is unreachable because attention reachability is
keyed on the position argument). Draft width is a static shape, the
accepted length is data, so the invariant extends to one decode trace
PLUS one verify trace per mesh shape (``verify_traces``), and a greedy
speculative stream is bit-identical to the one-token engine — slot and
paged, tp=1 and tp=2-exact. The ``DecodePolicy`` seam
(``EngineConfig(decode_policy=...)``, :mod:`apex_tpu.serve.spec`)
threads per-slot temperature/top_p/min_p as DATA through the same
compiled calls for per-request policy mixing in one batch.

``aot_compile()`` lowers and compiles decode (and any requested prompt
buckets) ahead of time — the serving analog of the repo's AOT tooling: no
request ever pays a trace.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt2 import (GPT2Config, gpt2_token_forward,
                                  gpt2_token_forward_tp)
from apex_tpu.ops.pallas.tiling import pow2_ceil
from apex_tpu.serve import kv_cache, paging
from apex_tpu.serve import spec as serve_spec
from apex_tpu.serve import tp as serve_tp
from apex_tpu.serve.attention import resolve_block_k
from apex_tpu.serve.kv_cache import (init_cache, init_paged_cache,
                                     shard_cache, tp_cache_specs)
from apex_tpu.serve.paging import PagePool, PrefixIndex
from apex_tpu.utils.compat import shard_map
# bound at module import, NOT function-locally (the scheduler's
# precedent): a sys.modules purge-and-reimport mid-process (the
# test_chip_worker pattern) would otherwise make engine builds publish
# to a FRESH event bus that collection-time subscribers never see
from apex_tpu.utils.logging import publish_event


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-side knobs (the model config stays ``GPT2Config``)."""

    num_slots: int = 4
    max_len: Optional[int] = None      # default: model n_positions
    temperature: float = 1.0           # 0 => greedy argmax
    top_k: int = 0                     # 0 => full vocab
    block_k: Optional[int] = None      # decode-attention KV chunk (tuned)
    # paged KV pool: tokens per page (None => per-slot slot cache). Must
    # divide max_len; the tuned decode_attention block_k must divide it.
    page_size: Optional[int] = None
    # pool capacity in pages INCLUDING the reserved null page. Default
    # num_slots * (max_len / page_size) + 1 — same token capacity as the
    # slot cache; size it SMALLER to overcommit (the point of paging:
    # mixed-length traffic shares the pool).
    num_pages: Optional[int] = None
    # hash-based prompt-prefix sharing across requests (paged mode only)
    prefix_cache: bool = False
    # keep per-position prefill logits (parity tests / scoring). O(P*B*V)
    # memory — leave False for real vocabularies.
    keep_prefill_logits: bool = False
    # tensor-parallel mesh size (1 = single chip). Must divide n_head:
    # the engine shards params and the KV pool on the HEAD axis over a
    # 1-D NamedSharding mesh and lowers decode/prefill under shard_map —
    # one compile per mesh shape (docs/serving.md "Tensor-parallel
    # decode")
    tp: int = 1
    # per-layer cross-rank synchronization (tp >= 2 only): "exact" (the
    # default and THE oracle — all-gather concatenation, bit-identical
    # in fp32 to the single-chip engine at equal block_k), "overlap"
    # (TokenWeave: row-parallel psums split in slot halves, interleaved
    # with norm/residual compute), or "relaxed" (partially-synchronized
    # activations: ONE deferred all-reduce per layer; opt-in
    # approximation)
    tp_sync: str = "exact"
    # speculative decoding (docs/serving.md "Speculative decoding and
    # the decode-policy zoo"): static draft width per verify step; 0 is
    # the one-token engine. The verify step scores draft_len + 1
    # positions per slot in ONE compiled call; the accepted length is
    # data, so the one-compile invariant survives speculation.
    spec_draft_len: int = 0
    # the DecodePolicy seam (apex_tpu.serve.spec): None keeps the legacy
    # static sampler above (temperature/top_k baked into the trace) and
    # the decode signature unchanged; a policy spelling ("greedy",
    # "top_p[=P]", "min_p[=M]", "spec(POLICY)") arms per-slot policy
    # mixing — per-slot temperature/top_p/min_p ride the compiled calls
    # as [num_slots] f32 DATA, so mixing policies in one batch never
    # retraces. Parse/validation errors are build-time ValueErrors.
    decode_policy: Optional[str] = None
    # block-scale KV quantization (apex_tpu.quant,
    # docs/quantization.md): None stores fp32/compute-dtype K/V; "int8"
    # / "mxfp8" stores codec bytes plus one fp32 scale per (token,
    # head) in the cache pytree — scales are DATA, so the one-compile
    # invariant is untouched and scales ride prefix sharing, COW,
    # eviction, export/import, and tp head sharding with their pages.
    # Requires fp32 compute_dtype (the tolerance oracle is calibrated
    # against the fp32 engine) and spec_draft_len == 0 (the spec
    # acceptance oracle is bit-exact; quant is tolerance-based — the
    # combination is refused until proven, the repo's standing policy).
    kv_quant: Optional[str] = None


class Engine:
    """A servable GPT-2: static cache + compiled prefill/decode.

    ``params`` is the standard flax param pytree of ``models.gpt2.GPT2``
    (``model.init(...)`` or a training checkpoint); serving casts to the
    model config's ``compute_dtype`` on the fly. Use fp32 configs for
    bit-exactness claims.
    """

    def __init__(self, model_cfg: GPT2Config, params,
                 config: EngineConfig = EngineConfig(), *, seed: int = 0):
        self.model_cfg = model_cfg
        self.config = config
        self.params = params
        self.max_len = int(config.max_len or model_cfg.n_positions)
        if self.max_len > model_cfg.n_positions:
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's "
                f"n_positions={model_cfg.n_positions}")
        self._paged = config.page_size is not None
        if self._paged:
            ps = int(config.page_size)
            if ps <= 0 or self.max_len % ps:
                raise ValueError(
                    f"page_size={config.page_size} must be positive and "
                    f"divide max_len={self.max_len}")
            self._max_pages = self.max_len // ps
            self._num_pages = int(
                config.num_pages
                or config.num_slots * self._max_pages + 1)
            if self._num_pages < self._max_pages + 1:
                raise ValueError(
                    f"num_pages={self._num_pages} cannot hold one "
                    f"full-context request plus the null page (need "
                    f">= {self._max_pages + 1})")
        elif config.prefix_cache:
            raise ValueError(
                "prefix_cache=True needs the paged pool: set page_size "
                "(prefix sharing is page-granular)")
        elif config.num_pages is not None:
            raise ValueError("num_pages needs page_size (paged mode)")
        h, d = model_cfg.n_head, model_cfg.n_embd // model_cfg.n_head
        # tensor-parallel mesh (docs/serving.md "Tensor-parallel
        # decode"): every geometry error is a build-time ValueError,
        # never a bad lowering
        self._tp = int(config.tp)
        if self._tp < 1:
            raise ValueError(f"tp={config.tp} must be >= 1")
        if config.tp_sync not in serve_tp.SYNC_MODES:
            raise ValueError(
                f"tp_sync={config.tp_sync!r} must be one of "
                f"{serve_tp.SYNC_MODES}")
        if self._tp == 1 and config.tp_sync != "exact":
            raise ValueError(
                f"tp_sync={config.tp_sync!r} relaxes cross-rank "
                f"synchronization; it needs tp >= 2 (a single chip has "
                f"no collectives to overlap or relax)")
        if h % self._tp:
            raise ValueError(
                f"tp={self._tp} must divide n_head={h}: the serving "
                f"mesh shards whole heads")
        if self._tp > 1:
            self.mesh: Optional[Any] = serve_tp.serving_mesh(self._tp)
            self._tp_params, self._tp_param_specs = \
                serve_tp.build_tp_params(model_cfg, params, self._tp,
                                         config.tp_sync, self.mesh)
            # the sharded tree is the ONLY param copy the compiled
            # paths read; keeping the caller's full replicated tree
            # alive too would pin a second whole-model copy for the
            # engine's lifetime — for the model sizes TP exists for,
            # that is the dominant memory cost duplicated
            self.params = None
        else:
            self.mesh = None
            self._tp_params = self._tp_param_specs = None
        # resolve the tuned geometry ONCE at engine build (cache lookups
        # at trace time inside scan would re-announce per position);
        # paged mode validates block_k against page_size here — a tuned
        # or explicit chunk that does not divide the page is a clear
        # ValueError at build, never a bad gather at trace time. A
        # sharded engine tunes at its PER-SHARD head count with the
        # shard count as its own key axis (winners never leak across
        # mesh shapes)
        self.block_k = resolve_block_k(self.max_len, h // self._tp, d,
                                       model_cfg.compute_dtype,
                                       config.block_k,
                                       page_size=config.page_size,
                                       tp_shards=self._tp)
        # speculative decoding + the DecodePolicy seam: every bad knob is
        # a build-time ValueError (both CLIs surface them as exit 2
        # before any compile)
        self._spec_k = int(config.spec_draft_len or 0)
        if self._spec_k < 0:
            raise ValueError(
                f"spec_draft_len={config.spec_draft_len} must be >= 0 "
                f"(0 disables speculation)")
        if self._spec_k and self._spec_k + 1 > self.max_len:
            raise ValueError(
                f"spec_draft_len={self._spec_k} needs max_len >= "
                f"{self._spec_k + 1}: a verify step scores draft_len + 1 "
                f"positions")
        self._policy = (serve_spec.parse_policy(
            config.decode_policy, spec_draft_len=self._spec_k)
            if config.decode_policy is not None else None)
        # block-scale KV quantization: codec validation is build-time
        # (unknown codec / missing float8 support), and the two
        # incompatible knob combinations are refused loudly rather than
        # served unproven — kv_quant needs the fp32 engine as its
        # tolerance reference, and speculation's acceptance oracle is
        # bit-exact where quant is tolerance-based
        self._kv_quant = config.kv_quant
        if self._kv_quant is not None:
            from apex_tpu.quant.kv import check_kv_codec

            check_kv_codec(self._kv_quant)
            if model_cfg.compute_dtype != jnp.float32:
                raise ValueError(
                    f"kv_quant={self._kv_quant!r} requires "
                    f"compute_dtype=float32: the quantization quality "
                    f"gate (quant_ppl_delta) is calibrated against the "
                    f"fp32 engine as the exact reference")
            if self._spec_k:
                raise ValueError(
                    f"kv_quant={self._kv_quant!r} is incompatible with "
                    f"spec_draft_len={self._spec_k}: the speculative "
                    f"acceptance oracle is bit-exact, the quantized "
                    f"cache is tolerance-gated — the combination is "
                    f"refused until separately proven")
        self._init_state(seed)

        # trace counters: tier-1 asserts decode_traces == 1 across a full
        # admit/complete/evict/backfill trace (the one-jit invariant —
        # one compile per MESH SHAPE: a tp engine's single decode trace
        # covers every rank, there is no per-rank compile to count).
        # Speculation adds verify_traces with the identical contract:
        # one verify trace per mesh shape, churn-proof.
        self.decode_traces = 0
        self.prefill_traces = 0
        self.verify_traces = 0

        self._decode = jax.jit(self._decode_fn)
        self._decode_aot = None
        self._decode_lowered = None    # kept so collective counting and
        #                                postmortems never re-trace
        self._prefill_jits: Dict[int, Any] = {}
        self._prefill_aot: Dict[int, Any] = {}
        self._prefill_lowered: Dict[int, Any] = {}   # same retention
        #                                contract as _decode_lowered: the
        #                                cost ledger reads prefill costs
        #                                without re-lowering after reset()
        self._verify = jax.jit(self._make_verify()) if self._spec_k \
            else None
        self._verify_aot = None
        self._verify_lowered = None    # retention contract shared with
        #                                _decode_lowered: cost_ledger()
        #                                prices verify after reset()
        #                                without ever re-tracing
        if self._tp > 1:
            publish_event(
                "serve_tp_mesh_ready", tp=self._tp,
                tp_sync=config.tp_sync, heads_per_shard=h // self._tp,
                collectives_per_decode_step=sum(
                    self.tp_collectives_per_step().values()))

    # ------------------------------------------------------------ graphs
    def _sample(self, logits, rng, pol=None):
        """Temperature / top-k sampling; greedy when temperature == 0.
        With the DecodePolicy seam armed, ``pol`` carries the per-slot
        temperature/top_p/min_p arrays as data and the branchless
        combined sampler runs instead (greedy rows stay an exact
        argmax)."""
        if pol is not None:
            return serve_spec.sample_with_policy(
                logits, rng, pol, top_k=int(self.config.top_k))
        t = float(self.config.temperature)
        k = int(self.config.top_k)
        if t <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / jnp.float32(t)
        if k > 0 and k < logits.shape[-1]:
            kth = jax.lax.top_k(scaled, k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, jnp.float32(-1e30), scaled)
        return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)

    def _token_step(self, cache, tokens, positions, mask, *,
                    final_scope: str = "sampling"):
        if self.mesh is None:
            return gpt2_token_forward(self.model_cfg, self.params, cache,
                                      tokens, positions, mask,
                                      block_k=self.block_k,
                                      kv_quant=self._kv_quant,
                                      final_scope=final_scope)
        # tensor-parallel: the SAME call sites (decode_fn, the prefill
        # scan body) lower the per-rank forward under shard_map — the
        # cache rides in head-sharded, the page table/lengths replicated,
        # logits come back replicated (identical on every rank by the
        # sync-mode contract), and sampling stays outside on the full
        # replicated logits exactly as on a single chip
        from jax.sharding import PartitionSpec as P

        specs = tp_cache_specs(cache)

        def rank_body(params, cache, tokens, positions, mask):
            return gpt2_token_forward_tp(
                self.model_cfg, self._tp, self.config.tp_sync, params,
                cache, tokens, positions, mask, block_k=self.block_k,
                kv_quant=self._kv_quant, final_scope=final_scope)

        fn = shard_map(rank_body, mesh=self.mesh,
                       in_specs=(self._tp_param_specs, specs, P(), P(),
                                 P()),
                       out_specs=(P(), specs), check_vma=False)
        return fn(self._tp_params, cache, tokens, positions, mask)

    def _decode_fn(self, cache, last_tokens, active, rng, pol=None):
        self.decode_traces += 1          # python side effect: trace count
        positions = cache.lengths
        logits, cache = self._token_step(cache, last_tokens, positions,
                                         active)
        with jax.named_scope("sampling"):
            rng, sub = jax.random.split(rng)
            next_tokens = self._sample(logits, sub, pol)
        cache = kv_cache.advance(cache, active)
        return next_tokens, logits, cache, rng

    def _make_prefill(self, bucket: int):
        keep = self.config.keep_prefill_logits

        def prefill_fn(cache, tokens, admit, start, tail_lens, rng,
                       pol=None):
            self.prefill_traces += 1
            cache = kv_cache.reset_slots(cache, admit)

            def body(carry, p):
                cache, last_logits = carry
                write = admit & (p < tail_lens)
                # absolute position = start + scan step: with a prefix
                # hit the scan covers only the tail, attending back over
                # the shared pages (start == 0 and tail == prompt on the
                # slot path — bit-identical to the pre-paging scan)
                positions = jnp.where(write, start + p, cache.lengths)
                logits, cache = self._token_step(
                    cache, tokens[:, p], positions, write)
                last_logits = jnp.where(write[:, None], logits,
                                        last_logits)
                return (cache, last_logits), (logits if keep else None)

            vocab = self.model_cfg.vocab_size
            init_logits = jnp.zeros((self.config.num_slots, vocab),
                                    jnp.float32)
            (cache, last_logits), all_logits = jax.lax.scan(
                body, (cache, init_logits),
                jnp.arange(bucket, dtype=jnp.int32))
            cache = kv_cache.set_lengths(cache, admit, start + tail_lens)
            with jax.named_scope("sampling"):
                rng, sub = jax.random.split(rng)
                first_tokens = self._sample(last_logits, sub, pol)
            return cache, first_tokens, last_logits, all_logits, rng

        return jax.jit(prefill_fn)

    def _make_verify(self):
        """The speculative verify step: structurally the prefill scan
        over ``draft_len + 1`` positions at decode width. Column 0
        re-feeds each slot's last committed token (exactly what
        ``decode_step`` would feed), columns ``1..K`` are the host
        drafter's guesses; position ``p``'s logits produce the target
        policy's own next token, and a draft is accepted iff it EQUALS
        that target (exact rejection-sampling acceptance for a
        point-mass drafter — no tolerance, the fp32 prefill-vs-decode
        bit-exactness IS the oracle). The accepted run length is data:
        ``set_lengths`` commits ``accepted + 1`` tokens and thereby
        rolls back every rejected draft row (stale K/V beyond
        ``lengths`` is unreachable — attention reachability is keyed on
        the position argument, the same mechanism evict relies on).
        Per-slot ``draft_lens`` is also data, so capacity- or
        budget-clamped slots (down to plain one-token steps at
        ``draft_lens == 0``) ride the same trace."""
        k = self._spec_k
        width = k + 1

        def verify_fn(cache, last_tokens, drafts, draft_lens, active,
                      rng, pol=None):
            self.verify_traces += 1      # python side effect: trace count
            start = cache.lengths

            def body(carry, p):
                cache = carry
                write = active & (p <= draft_lens)
                positions = jnp.where(write, start + p, cache.lengths)
                tokens = jnp.where(
                    p == 0, last_tokens,
                    drafts[:, jnp.maximum(p - 1, 0)])
                logits, cache = self._token_step(
                    cache, tokens, positions, write,
                    final_scope="verify")
                return cache, logits

            cache, all_logits = jax.lax.scan(
                body, cache, jnp.arange(width, dtype=jnp.int32))
            with jax.named_scope("sampling"):
                # ONE split of the engine key per verify call — the same
                # key-path contract as decode, so sampling_state()
                # journal replay covers speculative streams unchanged
                rng, sub = jax.random.split(rng)
                keys = jax.random.split(sub, width)
                targets = jax.vmap(
                    lambda lg, kk: self._sample(lg, kk, pol))(
                        all_logits, keys)
            targets = jnp.transpose(targets)          # [B, K+1]
            with jax.named_scope("verify"):
                proposed = (jnp.arange(k, dtype=jnp.int32)[None, :]
                            < draft_lens[:, None])
                match = (drafts == targets[:, :k]) & proposed
                # leading run of matches: a rejection truncates the draft
                accepted = jnp.cumprod(
                    match.astype(jnp.int32), axis=1).sum(axis=1)
                committed = jnp.where(active, accepted + 1, 0) \
                    .astype(jnp.int32)
                next_tokens = jnp.take_along_axis(
                    targets, accepted[:, None], axis=1)[:, 0]
                cache = kv_cache.set_lengths(cache, active,
                                             start + committed)
            return targets, committed, next_tokens, cache, rng

        return verify_fn

    # -------------------------------------------------------------- AOT
    def _policy_args(self):
        """Per-slot policy knobs as a jit-argument pytree (DATA — new
        values never retrace); None when the seam is unarmed, which
        keeps every legacy trace signature byte-identical."""
        if self._policy is None:
            return None
        return {"temps": jnp.asarray(self._pol_temps),
                "top_ps": jnp.asarray(self._pol_top_ps),
                "min_ps": jnp.asarray(self._pol_min_ps)}

    def _decode_args(self):
        args = (self.cache, jnp.zeros((self.config.num_slots,), jnp.int32),
                jnp.zeros((self.config.num_slots,), bool), self.rng)
        return args + ((self._policy_args(),)
                       if self._policy is not None else ())

    def _prefill_args(self, bucket: int):
        b = self.config.num_slots
        args = (self.cache, jnp.zeros((b, bucket), jnp.int32),
                jnp.zeros((b,), bool), jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.int32), self.rng)
        return args + ((self._policy_args(),)
                       if self._policy is not None else ())

    def _verify_args(self):
        b = self.config.num_slots
        args = (self.cache, jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, self._spec_k), jnp.int32),
                jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
                self.rng)
        return args + ((self._policy_args(),)
                       if self._policy is not None else ())

    def aot_compile(self, prompt_buckets: Sequence[int] = ()) -> "Engine":
        """Lower + compile decode (and the given prompt-length buckets)
        ahead of the first request — startup pays the trace, not traffic.

        Each fresh compile publishes its static XLA memory reservation as
        an ``hbm_snapshot`` event (``apex_tpu.monitor.memory``) — the
        serving AOT points are where the engine's HBM budget is decided,
        and the paged-vs-slot capacity comparison reads them.
        """
        from apex_tpu.monitor.memory import publish_compiled_memory

        if self._decode_aot is None:
            # the lowering is kept: decode_collectives() counts the
            # step's collective ops from it without ever re-tracing
            # (a second .lower() would grow decode_traces)
            self._decode_lowered = self._decode.lower(
                *self._decode_args())
            self._decode_aot = self._decode_lowered.compile()
            publish_compiled_memory(
                "serve_decode", self._decode_aot,
                num_slots=self.config.num_slots, max_len=self.max_len,
                page_size=self.config.page_size or 0,
                kv_cache_bytes=self.kv_cache_bytes)
        for bucket in prompt_buckets:
            bucket = pow2_ceil(int(bucket))
            if bucket not in self._prefill_aot:
                fn = self._prefill_jits.setdefault(
                    bucket, self._make_prefill(bucket))
                # retained like _decode_lowered: cost_ledger() prices
                # prefill buckets from the saved lowering — after a
                # reset()/warm restart there is nothing to re-trace
                lowered = fn.lower(*self._prefill_args(bucket))
                self._prefill_lowered[bucket] = lowered
                self._prefill_aot[bucket] = lowered.compile()
                publish_compiled_memory(
                    "serve_prefill", self._prefill_aot[bucket],
                    bucket=bucket, num_slots=self.config.num_slots,
                    max_len=self.max_len)
        if self._spec_k and self._verify_aot is None:
            # retained like _decode_lowered: cost_ledger() prices the
            # verify step from the saved lowering after reset()
            self._verify_lowered = self._verify.lower(
                *self._verify_args())
            self._verify_aot = self._verify_lowered.compile()
            publish_compiled_memory(
                "serve_verify", self._verify_aot,
                draft_len=self._spec_k,
                num_slots=self.config.num_slots, max_len=self.max_len,
                page_size=self.config.page_size or 0)
        return self

    def _init_state(self, seed: int) -> None:
        """ALL mutable serving state lives here (shared by __init__ and
        :meth:`reset` so a drain/restart can never miss a field)."""
        h = self.model_cfg.n_head
        d = self.model_cfg.n_embd // h
        b = self.config.num_slots
        if self._paged:
            ps = int(self.config.page_size)
            self.cache: Any = init_paged_cache(
                self.model_cfg.n_layer, b, self.max_len, ps,
                self._num_pages, h, d, self.model_cfg.compute_dtype,
                kv_quant=self._kv_quant)
            self.pool: Optional[PagePool] = PagePool(self._num_pages, ps)
            self.prefix: Optional[PrefixIndex] = \
                PrefixIndex(ps) if self.config.prefix_cache else None
            self._page_table = np.zeros((b, self._max_pages), np.int32)
            self._slot_pages = [[] for _ in range(b)]
            # per-slot admitted token capacity (pages reserved at
            # admission × page_size); slot engines use max_len flat
            self._slot_capacity = np.zeros((b,), np.int64)
        else:
            self.cache = init_cache(
                self.model_cfg.n_layer, b, self.max_len, h, d,
                self.model_cfg.compute_dtype, kv_quant=self._kv_quant)
            self.pool = None
            self.prefix = None
            self._slot_pages = [[] for _ in range(b)]
            self._slot_capacity = np.full((b,), self.max_len, np.int64)
        if self.mesh is not None:
            # head-sharded K/V pools, replicated bookkeeping — placed at
            # init so the compiled step never pays a layout move
            self.cache = shard_cache(self.cache, self.mesh)
        self.rng = jax.random.PRNGKey(seed)
        self.last_tokens = np.zeros((b,), np.int32)
        # host mirror of cache.lengths (advanced deterministically by
        # prefill/decode/evict) — lets decode_step enforce the context
        # bound without a per-step device fetch
        self._host_lengths = np.zeros((b,), np.int64)
        # prefix-cache accounting (tier-1 asserts a prefix hit SKIPS
        # prefill work via these, not via wall clock)
        self.decode_calls = 0            # decode_step executions
        self.prefill_calls = 0           # host prefill() invocations
        self.prefill_requests = 0        # slot-prompts prefilled
        self.prefill_scanned_tokens = 0  # scan steps actually paid
        self.prefix_hits = 0             # prompts that reused >=1 page
        self.prefix_hit_tokens = 0       # tokens served from the index
        self.last_prefill_stats: Dict[int, Dict[str, int]] = {}
        if self._policy is not None:
            # per-slot policy knobs (host mirrors of the jit-argument
            # arrays): reset() restores the engine-default policy
            self._pol_temps = np.full((b,), self._policy.temperature,
                                      np.float32)
            self._pol_top_ps = np.full((b,), self._policy.top_p,
                                       np.float32)
            self._pol_min_ps = np.full((b,), self._policy.min_p,
                                       np.float32)

    def reset(self, seed: int = 0, *,
              keep_prefix_cache: bool = False) -> "Engine":
        """Drop all serving state — empty cache, fresh PRNG stream — while
        keeping every compiled artifact (the jits close over params only).
        A server drain/restart costs zero recompiles; tests reuse one
        compiled engine across scenarios.

        Paged engines reset the page-pool free list and the prefix index
        too (a leaked refcount would poison the next scenario — tier-1
        regression-tests this). ``keep_prefix_cache=True`` (warm restart)
        instead releases every slot's page references but keeps the pool
        bytes and the index: shared prefix pages are read-only, so a
        crash cannot have corrupted them, and recovery re-prefills only
        the unshared tail of each surviving slot.
        """
        if keep_prefix_cache and self._paged and self.prefix is not None:
            b = self.config.num_slots
            for slot in range(b):
                self._release_slot_pages(slot)
            self.cache = self.cache.replace(
                lengths=jnp.zeros((b,), jnp.int32))
            self.rng = jax.random.PRNGKey(seed)
            self.last_tokens = np.zeros((b,), np.int32)
            self._host_lengths = np.zeros((b,), np.int64)
            self.last_prefill_stats = {}
            return self
        self._init_state(seed)
        return self

    # --------------------------------------------- warm-restart support
    def sampling_state(self) -> Dict[str, Any]:
        """The host-side sampling state a tick journal snapshots: the
        PRNG key (restoring it is what makes a ``temperature > 0``
        stream replay bit-for-bit across a warm restart — the key path
        is consumed one split per prefill/decode call), the per-slot
        last tokens (the next decode inputs), and the host length
        mirror (an integrity cross-check at restore)."""
        return {"rng": np.asarray(self.rng).tolist(),
                "last_tokens": self.last_tokens.tolist(),
                "lengths": self._host_lengths.tolist()}

    def restore_sampling_state(self, state: Dict[str, Any], *,
                               slots: Sequence[int] = ()) -> None:
        """Install a journaled sampling state after recovery re-prefill.

        ``slots`` names the slot indices the caller re-prefilled; their
        current cache lengths must equal the journaled ones (prompt +
        generated-but-last) or the rebuilt cache does NOT hold the state
        the PRNG/last-token restore assumes — refuse loudly rather than
        continue a stream from the wrong prefix."""
        want = np.asarray(state["lengths"], np.int64)
        for slot in slots:
            if self._host_lengths[slot] != want[slot]:
                raise ValueError(
                    f"recovery integrity: slot {slot} rebuilt to length "
                    f"{int(self._host_lengths[slot])}, journal says "
                    f"{int(want[slot])} — the re-prefilled prefix does "
                    f"not match the journaled stream")
        self.rng = jnp.asarray(np.asarray(state["rng"], np.uint32))
        self.last_tokens = np.asarray(state["last_tokens"], np.int32)

    def paging_state(self) -> Optional[Dict[str, Any]]:
        """The page-accounting view a tick journal records (None for a
        slot engine): per-slot page tables, pool refcounts, and the
        prefix-index size — the postmortem answer to "where did the HBM
        go" and the integrity cross-check for paged recovery."""
        if not self._paged:
            return None
        return {
            "page_size": int(self.config.page_size),
            "num_pages": self._num_pages,
            "free_pages": self.pool.free_count,
            "refcounts": list(self.pool.refcount),
            "page_table": self._page_table.tolist(),
            "slot_capacity": self._slot_capacity.tolist(),
            "prefix_entries": len(self.prefix) if self.prefix else 0,
        }

    # ---------------------------------------------------- page planning
    def _release_slot_pages(self, slot: int) -> None:
        """Drop the slot's page references (completion, eviction, or the
        re-prefill prologue); index-pinned prefix pages survive."""
        if not self._paged:
            return
        for page in self._slot_pages[slot]:
            self.pool.release(page)
        self._slot_pages[slot] = []
        self._page_table[slot, :] = paging.NULL_PAGE
        self._slot_capacity[slot] = 0

    def admission_page_cost(self, tokens: Sequence[int], budget: int,
                            pending: int = 0,
                            protect: Optional[set] = None) -> Optional[int]:
        """Admission probe: fresh pages admitting ``tokens`` with
        ``budget`` new-token headroom would allocate, or ``None`` when
        the pool (free list + LRU-evictable prefix pages) cannot cover
        them on top of ``pending`` pages already promised to earlier
        members of the same admission batch. ``protect`` (a set the
        scheduler threads through a batch of probes — the only mutation)
        accumulates every probed member's prefix-hit pages: a page one
        member plans to share must not count as evictable headroom for
        a later member, or prefill's eviction (which protects the whole
        batch's hits) would free fewer pages than the probes assumed
        and fail allocation mid-batch. Never touches the pool — the
        scheduler probes before popping a request. Slot engines always
        fit (cost 0)."""
        if not self._paged:
            return 0
        plan = paging.plan_admission(
            tokens, budget, self.max_len, int(self.config.page_size),
            self.prefix, touch=False)
        hits = {pg for _, pg in plan["hits"]}
        protect_all = hits | (protect or set())
        avail = self.pool.free_count
        if self.prefix is not None:
            avail += self.prefix.evictable(self.pool, protect_all)
        if plan["new_pages"] + pending > avail:
            return None
        if protect is not None:
            protect.update(hits)
        return plan["new_pages"]

    # ------------------------------------------------------------- calls
    def prefill(self, prompts: Dict[int, Sequence[int]], *,
                budgets: Optional[Dict[int, int]] = None,
                cacheable: Optional[Dict[int, int]] = None):
        """Insert ``{slot: prompt token ids}`` in one compiled call.

        Pads every prompt to the shared pow2 bucket, resets the target
        slots, scans the single-token forward over the prompt positions
        (non-target slots are fully masked), and samples each admitted
        slot's first generated token. Returns ``(first_tokens [B],
        last_logits [B, vocab], all_logits [P, B, vocab] | None)``; only
        the admitted slots' rows are meaningful.

        Paged mode: ``budgets[slot]`` (default: worst case ``max_len -
        len(prompt)``) sizes the page reservation — pages for the whole
        admitted budget are taken here so decode never allocates. With a
        prefix index, the longest indexed prefix is shared read-only and
        the scan covers only the tail (a partial boundary page is
        copied-on-write); afterwards the prompt's full pages are inserted
        into the index — ``cacheable[slot]`` caps how many leading tokens
        are indexable (recovery passes the original prompt length so
        generated-token pages never pin the index). Raises
        :class:`~apex_tpu.serve.paging.PagePoolExhausted` when pages run
        out — callers admit through :meth:`admission_page_cost` first.
        """
        if not prompts:
            raise ValueError("prefill needs at least one slot: prompt")
        b = self.config.num_slots
        max_p = max(len(t) for t in prompts.values())
        if max_p < 1:
            raise ValueError("empty prompt")
        for slot, toks in prompts.items():
            if not 0 <= slot < b:
                raise ValueError(f"slot {slot} out of range 0..{b - 1}")
            if len(toks) > self.max_len:
                raise ValueError(
                    f"prompt of {len(toks)} tokens exceeds max_len="
                    f"{self.max_len}")

        starts = np.zeros((b,), np.int32)
        tails: Dict[int, Sequence[int]] = dict(prompts)
        self.last_prefill_stats = {}
        quant_pages = 0
        if self._paged:
            ps = int(self.config.page_size)
            for slot in prompts:
                # the slot may still hold pages (same-tick backfill
                # defers the device-side evict; tests re-prefill
                # directly) — release before re-planning
                self._release_slot_pages(slot)
            # two passes: plan every slot BEFORE any eviction, so one
            # slot's LRU eviction can never free a page another batch
            # member planned to share (the probe counted those hits —
            # evicting them would make its page math wrong mid-batch)
            plans = {}
            for slot in sorted(prompts):
                toks = prompts[slot]
                budget = (budgets or {}).get(slot)
                if budget is None:
                    budget = self.max_len - len(toks)
                plans[slot] = paging.plan_admission(
                    toks, budget, self.max_len, ps, self.prefix,
                    touch=True)
            protect_all = {pg for plan in plans.values()
                           for _, pg in plan["hits"]}
            for slot in sorted(prompts):
                plan = plans[slot]
                shared = [pg for _, pg
                          in plan["hits"][:plan["shared_pages"]]]
                if plan["new_pages"] > self.pool.free_count \
                        and self.prefix is not None:
                    self.prefix.evict(
                        self.pool,
                        plan["new_pages"] - self.pool.free_count,
                        protect=protect_all)
                fresh = self.pool.alloc(plan["new_pages"])
                quant_pages += len(fresh)
                for pg in shared:
                    self.pool.retain(pg)
                if plan["cow_src"] is not None:
                    # copy-on-write: the tail starts mid-page, so the
                    # slot gets its own writable copy of the boundary
                    # page (one compiled op; identical bytes)
                    self.cache = kv_cache.copy_page(
                        self.cache, plan["cow_src"], fresh[0])
                row = shared + fresh
                self._page_table[slot, :] = paging.NULL_PAGE
                self._page_table[slot, :len(row)] = row
                self._slot_pages[slot] = row
                self._slot_capacity[slot] = plan["total_pages"] * ps
                starts[slot] = plan["use"]
                tails[slot] = plan["tail"]
                if plan["use"]:
                    self.prefix_hits += 1
                    self.prefix_hit_tokens += plan["use"]
                self.last_prefill_stats[slot] = {
                    "hit_tokens": plan["use"],
                    "hit_pages": plan["shared_pages"],
                    "scanned": len(plan["tail"]),
                }
            self.cache = self.cache.replace(
                page_table=jnp.asarray(self._page_table))
        else:
            for slot, toks in prompts.items():
                self.last_prefill_stats[slot] = {
                    "hit_tokens": 0, "hit_pages": 0, "scanned": len(toks)}

        bucket = pow2_ceil(max(len(t) for t in tails.values()))
        tokens = np.zeros((b, bucket), np.int32)
        admit = np.zeros((b,), bool)
        lens = np.zeros((b,), np.int32)
        for slot, toks in tails.items():
            tokens[slot, :len(toks)] = np.asarray(toks, np.int32)
            admit[slot] = True
            lens[slot] = len(toks)

        fn = self._prefill_aot.get(bucket)
        if fn is None:
            fn = self._prefill_jits.setdefault(
                bucket, self._make_prefill(bucket))
        args = (self.cache, jnp.asarray(tokens), jnp.asarray(admit),
                jnp.asarray(starts), jnp.asarray(lens), self.rng)
        if self._policy is not None:
            args += (self._policy_args(),)
        self.cache, first, last_logits, all_logits, self.rng = fn(*args)
        self.prefill_calls += 1
        self.prefill_requests += len(prompts)
        self.prefill_scanned_tokens += int(bucket)
        first_np = np.asarray(first)
        self.last_tokens = np.where(admit, first_np, self.last_tokens)
        full_lens = starts + lens
        self._host_lengths = np.where(admit, full_lens,
                                      self._host_lengths)
        if self._paged and self.prefix is not None:
            for slot, toks in prompts.items():
                upto = (cacheable or {}).get(slot, len(toks))
                row = self._slot_pages[slot]
                for i, h in enumerate(
                        paging.chunk_hashes(list(toks[:upto]), ps)):
                    self.prefix.insert(h, row[i], self.pool)
        if self._paged and self._kv_quant is not None:
            # quantized-capacity provenance: these pages now hold codec
            # bytes + scales, not fp32 rows — counted so a bench capture
            # can prove its resident_tokens_per_hbm_byte came from a
            # quantized pool, not a mislabeled fp32 one
            publish_event("serve_kv_quantized_pages", pages=quant_pages,
                          codec=self._kv_quant)
        return first_np, last_logits, all_logits

    def decode_step(self, last_tokens, active):
        """One decode step for every slot: feed each active slot its last
        token, get its next. ``last_tokens`` ``[num_slots]`` int,
        ``active`` ``[num_slots]`` bool. Returns ``(next_tokens
        np.ndarray, logits [num_slots, vocab] device array)``."""
        act_np = np.asarray(active, bool)
        full = act_np & (self._host_lengths >= self._slot_capacity)
        if full.any():
            # the cache write would silently clip (slot cache) or land in
            # an unreserved page (paged) and corrupt the newest K/V row —
            # refuse instead; the scheduler terminates at context-full /
            # budget before ever reaching this
            raise ValueError(
                f"slot(s) {np.flatnonzero(full).tolist()} are at their "
                f"admitted capacity "
                f"{self._slot_capacity[full].tolist()} (max_len="
                f"{self.max_len}); evict or raise max_len before "
                f"decoding further")
        fn = self._decode_aot or self._decode
        lt = jnp.asarray(np.asarray(last_tokens, np.int32))
        act = jnp.asarray(act_np)
        args = (self.cache, lt, act, self.rng)
        if self._policy is not None:
            args += (self._policy_args(),)
        next_tokens, logits, self.cache, self.rng = fn(*args)
        self.decode_calls += 1
        next_np = np.asarray(next_tokens)
        self.last_tokens = np.where(act_np, next_np, self.last_tokens)
        self._host_lengths = self._host_lengths + act_np
        return next_np, logits

    # ------------------------------------------------ speculative decode
    @property
    def spec_draft_len(self) -> int:
        """Static draft width K (0 = speculation off)."""
        return self._spec_k

    @property
    def policy_armed(self) -> bool:
        """True when the DecodePolicy seam threads per-slot knobs."""
        return self._policy is not None

    def set_slot_policy(self, slot: int, policy=None) -> None:
        """Install a per-request decode policy on ``slot`` (policy
        mixing in one batch): the knobs are DATA on the compiled calls,
        so this never retraces. ``policy`` is a
        :class:`~apex_tpu.serve.spec.DecodePolicy`, a policy spelling,
        or None to restore the engine default. Needs
        ``EngineConfig(decode_policy=...)`` — the unarmed engine's
        sampler is baked into the trace."""
        if self._policy is None:
            if policy is None:
                return
            raise ValueError(
                "per-slot policies need EngineConfig(decode_policy=...): "
                "the unarmed engine bakes its sampler into the trace")
        pol = policy if policy is not None else self._policy
        if isinstance(pol, str):
            pol = serve_spec.parse_policy(pol,
                                          spec_draft_len=self._spec_k)
        self._pol_temps[slot] = pol.temperature
        self._pol_top_ps[slot] = pol.top_p
        self._pol_min_ps[slot] = pol.min_p

    def spec_headroom(self, slot: int) -> int:
        """Cache rows still writable for ``slot`` (admitted capacity
        minus resident tokens) — the scheduler clamps each tick's draft
        to ``headroom - 1`` so a verify commit can never overrun."""
        return int(self._slot_capacity[slot] - self._host_lengths[slot])

    def spec_decode_step(self, last_tokens, drafts, draft_lens, active):
        """One speculative step for every slot: feed each active slot
        its last committed token plus up to ``spec_draft_len`` host
        draft guesses; the compiled verify step scores all ``K + 1``
        positions and commits the exactly-accepted run plus one bonus
        token. ``drafts`` ``[num_slots, K]`` int, ``draft_lens``
        ``[num_slots]`` int in ``[0, K]`` (data — a 0 row is a plain
        one-token step on the same trace), ``active`` ``[num_slots]``
        bool. Returns ``(committed [num_slots, K + 1] np.ndarray — only
        the first ``counts[slot]`` entries of each row are meaningful —
        and counts [num_slots] np.ndarray)``."""
        if not self._spec_k:
            raise ValueError(
                "spec_decode_step needs EngineConfig(spec_draft_len >= "
                "1); use decode_step on the one-token engine")
        act_np = np.asarray(active, bool)
        dl_np = np.asarray(draft_lens, np.int64)
        if ((dl_np < 0) | (dl_np > self._spec_k)).any():
            raise ValueError(
                f"draft_lens {dl_np.tolist()} must lie in "
                f"[0, spec_draft_len={self._spec_k}]")
        # capacity backstop, mirroring decode_step's refusal: the verify
        # scan writes positions length..length+draft_len, and commits up
        # to draft_len + 1 tokens — an overrun would clip (slot cache)
        # or land in an unreserved page (paged) and corrupt K/V rows
        need = self._host_lengths + np.where(act_np, dl_np + 1, 0)
        over = act_np & (need > self._slot_capacity)
        if over.any():
            raise ValueError(
                f"slot(s) {np.flatnonzero(over).tolist()} would overrun "
                f"their admitted capacity "
                f"{self._slot_capacity[over].tolist()} at draft_lens="
                f"{dl_np[over].tolist()} (max_len={self.max_len}); clamp "
                f"the draft or evict before speculating further")
        fn = self._verify_aot or self._verify
        b = self.config.num_slots
        args = (self.cache, jnp.asarray(np.asarray(last_tokens, np.int32)),
                jnp.asarray(np.asarray(drafts, np.int32).reshape(
                    b, self._spec_k)),
                jnp.asarray(dl_np.astype(np.int32)), jnp.asarray(act_np),
                self.rng)
        if self._policy is not None:
            args += (self._policy_args(),)
        committed, counts, next_tokens, self.cache, self.rng = fn(*args)
        self.decode_calls += 1
        committed_np = np.asarray(committed)
        counts_np = np.asarray(counts)
        self.last_tokens = np.where(act_np, np.asarray(next_tokens),
                                    self.last_tokens)
        self._host_lengths = self._host_lengths + counts_np
        return committed_np, counts_np

    def evict(self, slots) -> None:
        """Free the given slot indices (mask-shaped op, compiled once);
        paged engines return the slots' page references to the pool
        (index-pinned prefix pages stay resident)."""
        mask = np.zeros((self.config.num_slots,), bool)
        mask[np.asarray(list(slots), np.int64)] = True
        self.cache = kv_cache.evict_slots(self.cache, jnp.asarray(mask))
        self._host_lengths = np.where(mask, 0, self._host_lengths)
        if self._paged:
            for slot in np.flatnonzero(mask):
                self._release_slot_pages(int(slot))

    # --------------------- page migration (disaggregated prefill→decode)
    def export_prefix_pages(self, tokens: Sequence[int]):
        """Snapshot the indexed prefix pages of ``tokens`` for streaming
        into another replica's pool: ``[{chain_hash, k, v, digest}, ...]``
        in chain order, one entry per consecutive indexed full chunk.
        Payload arrays are host copies ``[n_layer, page_size, heads,
        head_dim]`` (under tensor parallelism ``device_get`` gathers the
        head shards — page indices are rank-invariant, payloads are
        whole pages). The digest is stamped here, over the exact bytes
        exported (:func:`~apex_tpu.serve.paging.page_payload_digest`), so
        the receiver can certify the transfer. ``touch=False``: an
        export is a read, not a use — it must not reorder the donor's
        LRU. Empty when not paged / no prefix index / no indexed prefix.
        """
        if not self._paged or self.prefix is None:
            return []
        out = []
        for h, page in self.prefix.lookup(tokens, touch=False):
            k_np = np.asarray(jax.device_get(self.cache.k[:, page]))
            v_np = np.asarray(jax.device_get(self.cache.v[:, page]))
            entry = {"chain_hash": h, "k": k_np, "v": v_np,
                     "codec": self._kv_quant}
            if self._kv_quant is not None:
                # quantized payloads ship their scale planes, and the
                # digest covers codes ‖ scales together: a flipped
                # scale bit fails certification exactly like a flipped
                # payload bit
                ks_np = np.asarray(
                    jax.device_get(self.cache.k_scale[:, page]))
                vs_np = np.asarray(
                    jax.device_get(self.cache.v_scale[:, page]))
                entry["k_scale"] = ks_np
                entry["v_scale"] = vs_np
                entry["digest"] = paging.page_payload_digest(
                    h, k_np.tobytes(), v_np.tobytes(),
                    ks_np.tobytes(), vs_np.tobytes())
            else:
                entry["digest"] = paging.page_payload_digest(
                    h, k_np.tobytes(), v_np.tobytes())
            out.append(entry)
        return out

    def import_prefix_pages(self, payloads) -> Dict[str, int]:
        """Install **certified** migrated pages into this engine's pool
        and prefix index; returns ``{"installed", "duplicate",
        "no_capacity"}`` counts. Certification (chain-hash + payload
        digest) is the CALLER's job — the disaggregation controller
        refuses un-certified pages before they reach here; this method
        enforces only the structural contract (paged + prefix engine,
        exact payload shape).

        Exactly-once by construction: a payload whose chain hash is
        already indexed is a duplicate stream (failover replay, a second
        handoff of the same prefix) and is skipped — the index insert
        no-op is the same door that makes two requests sharing a prompt
        idempotent. Installed pages are index-only (refcount 1): they
        age out through normal LRU eviction like locally-prefilled
        prefix pages, and the next admission of the migrated prompt
        shares them read-only exactly as a local prefix hit.
        """
        if not self._paged or self.prefix is None:
            raise ValueError(
                "import_prefix_pages needs a paged engine with "
                "prefix_cache=True (page migration lands in the prefix "
                "index)")
        ps = int(self.config.page_size)
        h_heads = self.model_cfg.n_head
        d = self.model_cfg.n_embd // h_heads
        shape = (self.model_cfg.n_layer, ps, h_heads, d)
        stats = {"installed": 0, "duplicate": 0, "no_capacity": 0}
        for p in payloads:
            if tuple(np.shape(p["k"])) != shape or \
                    tuple(np.shape(p["v"])) != shape:
                raise ValueError(
                    f"migrated page payload shape {np.shape(p['k'])} != "
                    f"engine page shape {shape} (torn transfer should "
                    f"have been refused at certification)")
            if p.get("codec") != self._kv_quant:
                raise ValueError(
                    f"migrated page codec {p.get('codec')!r} != engine "
                    f"kv_quant {self._kv_quant!r} (a codec mismatch "
                    f"should have been refused at certification — "
                    f"installing it would misread the pool bytes)")
            if p["chain_hash"] in self.prefix:
                stats["duplicate"] += 1
                continue
            if self.pool.free_count < 1:
                self.prefix.evict(self.pool, 1)
            if self.pool.free_count < 1:
                # chain order: a missing page truncates the usable
                # prefix, so later pages would be unreachable anyway
                stats["no_capacity"] += len(payloads) - (
                    stats["installed"] + stats["duplicate"])
                break
            page = self.pool.alloc(1)[0]
            if self._kv_quant is not None:
                self.cache = kv_cache.install_page(
                    self.cache, page, jnp.asarray(p["k"]),
                    jnp.asarray(p["v"]), jnp.asarray(p["k_scale"]),
                    jnp.asarray(p["v_scale"]))
            else:
                self.cache = kv_cache.install_page(
                    self.cache, page, jnp.asarray(p["k"]),
                    jnp.asarray(p["v"]))
            self.prefix.insert(p["chain_hash"], page, self.pool)
            # index-only residency (refcount 1): admission shares it
            # read-only like any local prefix hit; LRU can reclaim it
            self.pool.release(page)
            stats["installed"] += 1
        if self._kv_quant is not None and stats["installed"]:
            publish_event("serve_kv_quantized_pages",
                          pages=stats["installed"],
                          codec=self._kv_quant)
        return stats

    @property
    def lengths(self) -> np.ndarray:
        return np.asarray(self.cache.lengths)

    @property
    def paged(self) -> bool:
        return self._paged

    # ------------------------------------------------- tensor parallel
    @property
    def tp(self) -> int:
        """Tensor-parallel mesh size (1 = single chip)."""
        return self._tp

    def tp_collectives_per_step(self) -> Dict[str, int]:
        """The per-decode-step collective CONTRACT of this engine's sync
        mode (zeros on a single chip); tier-1 holds it against the
        actual lowering via :meth:`decode_collectives`."""
        if self._tp == 1:
            return {"all_gather": 0, "all_reduce": 0}
        return serve_tp.expected_collectives(self.model_cfg.n_layer,
                                             self.config.tp_sync)

    def decode_collectives(self) -> Dict[str, int]:
        """Collective ops in the ACTUAL lowered decode step (StableHLO
        count — the verifier of :meth:`tp_collectives_per_step`). Uses
        the saved AOT lowering, producing it first if needed — on an
        engine already serving through the plain jit path, that
        ``.lower()`` resolves from the jit's trace cache, so
        ``decode_traces`` stays at 1 either way (tier-1 pins exactly
        this ordering)."""
        if self._decode_lowered is None:
            self.aot_compile()
        return serve_tp.count_collectives(self._decode_lowered.as_text())

    def cost_ledger(self, chip: Optional[str] = None,
                    prompt_buckets: Sequence[int] = ()) -> Dict[str, Any]:
        """The engine's compiled-step cost ledger
        (``apex_tpu.monitor.costs``): phase-attributed FLOPs/HBM bytes/
        op histograms walked from the SAVED AOT lowerings plus XLA's own
        cost/memory analyses, with a roofline projection on ``chip``
        (auto-detected; ``"cpu"`` — marked non-gating — off silicon).

        Rides ``_decode_lowered``/``_prefill_lowered`` exactly like
        :meth:`decode_collectives` — producing them first if needed,
        never re-tracing (``decode_traces`` stays at 1), and surviving
        ``reset()``/warm restarts, which keep the compiled artifacts.
        Entries: ``decode`` plus ``prefill_<bucket>`` for every bucket
        already compiled or requested via ``prompt_buckets``, plus
        ``verify`` when speculation is armed (``spec_draft_len >= 1``;
        a one-token engine's ledger is byte-identical to PR 17's —
        there is no verify artifact to price).
        """
        from apex_tpu.monitor import costs
        from apex_tpu.utils.prof import detect_chip

        if self._decode_lowered is None or any(
                pow2_ceil(int(b)) not in self._prefill_lowered
                for b in prompt_buckets) or (
                    self._spec_k and self._verify_lowered is None):
            self.aot_compile(prompt_buckets)
        execs = {"decode": costs.executable_record(
            self._decode_lowered, self._decode_aot)}
        for bucket in sorted(self._prefill_lowered):
            execs[f"prefill_{bucket}"] = costs.executable_record(
                self._prefill_lowered[bucket],
                self._prefill_aot.get(bucket))
        if self._spec_k:
            execs["verify"] = costs.executable_record(
                self._verify_lowered, self._verify_aot)
        dtype = jnp.dtype(self.model_cfg.compute_dtype)
        workload = {
            "model": "gpt2",
            "num_slots": int(self.config.num_slots),
            "max_len": int(self.max_len),
            "page_size": int(self.config.page_size or 0),
            "dtype": dtype.name,
            "dtype_bytes": int(dtype.itemsize),
            "block_k": int(self.block_k),
            "tp": int(self._tp),
            "tp_sync": self.config.tp_sync if self._tp > 1 else None,
            "n_layer": int(self.model_cfg.n_layer),
            "n_embd": int(self.model_cfg.n_embd),
            "n_head": int(self.model_cfg.n_head),
            "vocab_size": int(self.model_cfg.vocab_size),
            "spec_draft_len": int(self._spec_k),
            "decode_policy": self.config.decode_policy,
            "kv_quant": self._kv_quant,
            "quant_block": int(self.quant_block),
        }
        return costs.build_ledger(execs, workload,
                                  chip=chip or detect_chip() or "cpu")

    def tp_rank_snapshots(self, meta: Optional[Dict[str, Any]] = None):
        """Per-rank mergeable metrics snapshots (the PR-10
        ``merge_snapshots`` seam) — see
        :func:`apex_tpu.serve.tp.rank_snapshots`. Empty on a single
        chip (there are no ranks to fold)."""
        if self._tp == 1:
            return []
        return serve_tp.rank_snapshots(self, meta=meta)

    @property
    def resident_tokens(self) -> int:
        """Tokens currently resident in the cache across all slots."""
        return int(self._host_lengths.sum())

    @property
    def free_page_frac(self) -> float:
        """Fraction of the pool allocatable RIGHT NOW: free pages plus
        index-only cached pages an LRU sweep could evict on demand (1.0
        for slot engines — they have no pool to pressure). Counting
        evictable pages matters: a warm prefix cache deliberately keeps
        the free list near empty, so raw free_count reads as permanent
        pressure on an engine that actually has plenty of headroom."""
        if not self._paged:
            return 1.0
        free = self.pool.free_count
        if self.prefix is not None:
            free += self.prefix.evictable(self.pool)
        return free / max(self.pool.capacity, 1)

    @property
    def kv_quant(self) -> Optional[str]:
        """The armed KV codec (``"int8"``/``"mxfp8"``) or None."""
        return self._kv_quant

    @property
    def quant_block(self) -> int:
        """Quantization block size (elements per scale): the head_dim
        when ``kv_quant`` is armed — one scale per (token, head) vector —
        else 0 (unquantized). A workload-provenance axis: captures at
        different blocks are incomparable."""
        if self._kv_quant is None:
            return 0
        return int(self.model_cfg.n_embd // self.model_cfg.n_head)

    @property
    def kv_cache_bytes(self) -> int:
        """Resident bytes of the KV buffers — the slot cache's
        ``num_slots * max_len`` reservation, or the paged pool's
        ``num_pages * page_size``, INCLUDING the fp32 scale planes when
        ``kv_quant`` is armed (the capacity win must be priced net of
        its scale overhead); stamped into the serving AOT
        ``hbm_snapshot`` and the bench's
        ``resident_tokens_per_hbm_byte`` so captures carry it."""
        total = int(self.cache.k.nbytes) + int(self.cache.v.nbytes)
        if self.cache.k_scale is not None:
            total += int(self.cache.k_scale.nbytes)
            total += int(self.cache.v_scale.nbytes)
        return total


def init_gpt2_params(cfg: GPT2Config, seed: int = 0):
    """Random GPT-2 params for smoke/bench serving (real deployments load
    a checkpoint). Init runs the training forward once at a short length.
    """
    from apex_tpu.models.gpt2 import GPT2

    model = GPT2(cfg)
    dummy = jnp.zeros((1, min(8, cfg.n_positions)), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), dummy)
