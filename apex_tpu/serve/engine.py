"""AOT prefill + one-jit decode over the static KV cache.

The engine owns three compiled artifacts and NOTHING else touches the
device:

- ``decode_step`` — ONE jitted function, ``[num_slots]`` tokens in,
  ``[num_slots]`` sampled tokens out. Admission, completion, eviction, and
  backfill all happen by changing *values* (masks, lengths), so the jit
  cache holds exactly one entry for the life of the engine — asserted by
  tier-1 (``Engine.decode_traces``).
- ``prefill`` — a ``lax.scan`` of the *same* single-token forward over the
  prompt positions, at the same ``[num_slots]`` width (non-admitted slots
  mask their writes). One compile per pow2 prompt-length bucket. Because
  prefill and decode share the forward at identical shapes, an
  incrementally decoded token's logits are bit-identical (fp32) to the
  same token's logits under full-sequence prefill — there is no
  "prefill path" to drift from.
- ``evict`` — a mask-shaped length reset (kv_cache.evict_slots), one
  compile total.

Sampling (temperature / top-k, greedy at ``temperature=0``) runs inside
the jitted step under a threaded PRNG key: the key is part of engine
state, split in-graph, and returned — a fixed seed replays a stream
bit-for-bit.

``aot_compile()`` lowers and compiles decode (and any requested prompt
buckets) ahead of time — the serving analog of the repo's AOT tooling: no
request ever pays a trace.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt2 import GPT2Config, gpt2_token_forward
from apex_tpu.ops.pallas.tiling import pow2_ceil
from apex_tpu.serve import kv_cache
from apex_tpu.serve.attention import resolve_block_k
from apex_tpu.serve.kv_cache import KVCache, init_cache


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-side knobs (the model config stays ``GPT2Config``)."""

    num_slots: int = 4
    max_len: Optional[int] = None      # default: model n_positions
    temperature: float = 1.0           # 0 => greedy argmax
    top_k: int = 0                     # 0 => full vocab
    block_k: Optional[int] = None      # decode-attention KV chunk (tuned)
    # keep per-position prefill logits (parity tests / scoring). O(P*B*V)
    # memory — leave False for real vocabularies.
    keep_prefill_logits: bool = False


class Engine:
    """A servable GPT-2: static cache + compiled prefill/decode.

    ``params`` is the standard flax param pytree of ``models.gpt2.GPT2``
    (``model.init(...)`` or a training checkpoint); serving casts to the
    model config's ``compute_dtype`` on the fly. Use fp32 configs for
    bit-exactness claims.
    """

    def __init__(self, model_cfg: GPT2Config, params,
                 config: EngineConfig = EngineConfig(), *, seed: int = 0):
        self.model_cfg = model_cfg
        self.config = config
        self.params = params
        self.max_len = int(config.max_len or model_cfg.n_positions)
        if self.max_len > model_cfg.n_positions:
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's "
                f"n_positions={model_cfg.n_positions}")
        h, d = model_cfg.n_head, model_cfg.n_embd // model_cfg.n_head
        # resolve the tuned geometry ONCE at engine build (cache lookups
        # at trace time inside scan would re-announce per position)
        self.block_k = resolve_block_k(self.max_len, h, d,
                                       model_cfg.compute_dtype,
                                       config.block_k)
        self._init_state(seed)

        # trace counters: tier-1 asserts decode_traces == 1 across a full
        # admit/complete/evict/backfill trace (the one-jit invariant)
        self.decode_traces = 0
        self.prefill_traces = 0

        self._decode = jax.jit(self._decode_fn)
        self._decode_aot = None
        self._prefill_jits: Dict[int, Any] = {}
        self._prefill_aot: Dict[int, Any] = {}

    # ------------------------------------------------------------ graphs
    def _sample(self, logits, rng):
        """Temperature / top-k sampling; greedy when temperature == 0."""
        t = float(self.config.temperature)
        k = int(self.config.top_k)
        if t <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / jnp.float32(t)
        if k > 0 and k < logits.shape[-1]:
            kth = jax.lax.top_k(scaled, k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, jnp.float32(-1e30), scaled)
        return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)

    def _token_step(self, cache, tokens, positions, mask):
        return gpt2_token_forward(self.model_cfg, self.params, cache,
                                  tokens, positions, mask,
                                  block_k=self.block_k)

    def _decode_fn(self, cache, last_tokens, active, rng):
        self.decode_traces += 1          # python side effect: trace count
        positions = cache.lengths
        logits, cache = self._token_step(cache, last_tokens, positions,
                                         active)
        rng, sub = jax.random.split(rng)
        next_tokens = self._sample(logits, sub)
        cache = kv_cache.advance(cache, active)
        return next_tokens, logits, cache, rng

    def _make_prefill(self, bucket: int):
        keep = self.config.keep_prefill_logits

        def prefill_fn(cache, tokens, admit, prompt_lens, rng):
            self.prefill_traces += 1
            cache = kv_cache.reset_slots(cache, admit)

            def body(carry, p):
                cache, last_logits = carry
                write = admit & (p < prompt_lens)
                positions = jnp.where(write, p, cache.lengths)
                logits, cache = self._token_step(
                    cache, tokens[:, p], positions, write)
                last_logits = jnp.where(write[:, None], logits,
                                        last_logits)
                return (cache, last_logits), (logits if keep else None)

            vocab = self.model_cfg.vocab_size
            init_logits = jnp.zeros((self.config.num_slots, vocab),
                                    jnp.float32)
            (cache, last_logits), all_logits = jax.lax.scan(
                body, (cache, init_logits),
                jnp.arange(bucket, dtype=jnp.int32))
            cache = kv_cache.set_lengths(cache, admit, prompt_lens)
            rng, sub = jax.random.split(rng)
            first_tokens = self._sample(last_logits, sub)
            return cache, first_tokens, last_logits, all_logits, rng

        return jax.jit(prefill_fn)

    # -------------------------------------------------------------- AOT
    def _decode_args(self):
        return (self.cache, jnp.zeros((self.config.num_slots,), jnp.int32),
                jnp.zeros((self.config.num_slots,), bool), self.rng)

    def _prefill_args(self, bucket: int):
        b = self.config.num_slots
        return (self.cache, jnp.zeros((b, bucket), jnp.int32),
                jnp.zeros((b,), bool), jnp.zeros((b,), jnp.int32),
                self.rng)

    def aot_compile(self, prompt_buckets: Sequence[int] = ()) -> "Engine":
        """Lower + compile decode (and the given prompt-length buckets)
        ahead of the first request — startup pays the trace, not traffic.

        Each fresh compile publishes its static XLA memory reservation as
        an ``hbm_snapshot`` event (``apex_tpu.monitor.memory``) — the
        serving AOT points are where the engine's HBM budget is decided,
        and the paged-KV ROADMAP item needs them on the record.
        """
        from apex_tpu.monitor.memory import publish_compiled_memory

        if self._decode_aot is None:
            self._decode_aot = self._decode.lower(
                *self._decode_args()).compile()
            publish_compiled_memory(
                "serve_decode", self._decode_aot,
                num_slots=self.config.num_slots, max_len=self.max_len,
                kv_cache_bytes=self.kv_cache_bytes)
        for bucket in prompt_buckets:
            bucket = pow2_ceil(int(bucket))
            if bucket not in self._prefill_aot:
                fn = self._prefill_jits.setdefault(
                    bucket, self._make_prefill(bucket))
                self._prefill_aot[bucket] = fn.lower(
                    *self._prefill_args(bucket)).compile()
                publish_compiled_memory(
                    "serve_prefill", self._prefill_aot[bucket],
                    bucket=bucket, num_slots=self.config.num_slots,
                    max_len=self.max_len)
        return self

    def _init_state(self, seed: int) -> None:
        """ALL mutable serving state lives here (shared by __init__ and
        :meth:`reset` so a drain/restart can never miss a field)."""
        h = self.model_cfg.n_head
        d = self.model_cfg.n_embd // h
        self.cache: KVCache = init_cache(
            self.model_cfg.n_layer, self.config.num_slots, self.max_len,
            h, d, self.model_cfg.compute_dtype)
        self.rng = jax.random.PRNGKey(seed)
        self.last_tokens = np.zeros((self.config.num_slots,), np.int32)
        # host mirror of cache.lengths (advanced deterministically by
        # prefill/decode/evict) — lets decode_step enforce the context
        # bound without a per-step device fetch
        self._host_lengths = np.zeros((self.config.num_slots,), np.int64)

    def reset(self, seed: int = 0) -> "Engine":
        """Drop all serving state — empty cache, fresh PRNG stream — while
        keeping every compiled artifact (the jits close over params only).
        A server drain/restart costs zero recompiles; tests reuse one
        compiled engine across scenarios."""
        self._init_state(seed)
        return self

    # --------------------------------------------- warm-restart support
    def sampling_state(self) -> Dict[str, Any]:
        """The host-side sampling state a tick journal snapshots: the
        PRNG key (restoring it is what makes a ``temperature > 0``
        stream replay bit-for-bit across a warm restart — the key path
        is consumed one split per prefill/decode call), the per-slot
        last tokens (the next decode inputs), and the host length
        mirror (an integrity cross-check at restore)."""
        return {"rng": np.asarray(self.rng).tolist(),
                "last_tokens": self.last_tokens.tolist(),
                "lengths": self._host_lengths.tolist()}

    def restore_sampling_state(self, state: Dict[str, Any], *,
                               slots: Sequence[int] = ()) -> None:
        """Install a journaled sampling state after recovery re-prefill.

        ``slots`` names the slot indices the caller re-prefilled; their
        current cache lengths must equal the journaled ones (prompt +
        generated-but-last) or the rebuilt cache does NOT hold the state
        the PRNG/last-token restore assumes — refuse loudly rather than
        continue a stream from the wrong prefix."""
        want = np.asarray(state["lengths"], np.int64)
        for slot in slots:
            if self._host_lengths[slot] != want[slot]:
                raise ValueError(
                    f"recovery integrity: slot {slot} rebuilt to length "
                    f"{int(self._host_lengths[slot])}, journal says "
                    f"{int(want[slot])} — the re-prefilled prefix does "
                    f"not match the journaled stream")
        self.rng = jnp.asarray(np.asarray(state["rng"], np.uint32))
        self.last_tokens = np.asarray(state["last_tokens"], np.int32)

    # ------------------------------------------------------------- calls
    def prefill(self, prompts: Dict[int, Sequence[int]]):
        """Insert ``{slot: prompt token ids}`` in one compiled call.

        Pads every prompt to the shared pow2 bucket, resets the target
        slots, scans the single-token forward over the prompt positions
        (non-target slots are fully masked), and samples each admitted
        slot's first generated token. Returns ``(first_tokens [B],
        last_logits [B, vocab], all_logits [P, B, vocab] | None)``; only
        the admitted slots' rows are meaningful.
        """
        if not prompts:
            raise ValueError("prefill needs at least one slot: prompt")
        b = self.config.num_slots
        max_p = max(len(t) for t in prompts.values())
        if max_p < 1:
            raise ValueError("empty prompt")
        for slot, toks in prompts.items():
            if not 0 <= slot < b:
                raise ValueError(f"slot {slot} out of range 0..{b - 1}")
            if len(toks) > self.max_len:
                raise ValueError(
                    f"prompt of {len(toks)} tokens exceeds max_len="
                    f"{self.max_len}")
        bucket = pow2_ceil(max_p)
        tokens = np.zeros((b, bucket), np.int32)
        admit = np.zeros((b,), bool)
        lens = np.zeros((b,), np.int32)
        for slot, toks in prompts.items():
            tokens[slot, :len(toks)] = np.asarray(toks, np.int32)
            admit[slot] = True
            lens[slot] = len(toks)

        fn = self._prefill_aot.get(bucket)
        if fn is None:
            fn = self._prefill_jits.setdefault(
                bucket, self._make_prefill(bucket))
        self.cache, first, last_logits, all_logits, self.rng = fn(
            self.cache, jnp.asarray(tokens), jnp.asarray(admit),
            jnp.asarray(lens), self.rng)
        first_np = np.asarray(first)
        self.last_tokens = np.where(admit, first_np, self.last_tokens)
        self._host_lengths = np.where(admit, lens, self._host_lengths)
        return first_np, last_logits, all_logits

    def decode_step(self, last_tokens, active):
        """One decode step for every slot: feed each active slot its last
        token, get its next. ``last_tokens`` ``[num_slots]`` int,
        ``active`` ``[num_slots]`` bool. Returns ``(next_tokens
        np.ndarray, logits [num_slots, vocab] device array)``."""
        act_np = np.asarray(active, bool)
        full = act_np & (self._host_lengths >= self.max_len)
        if full.any():
            # the cache write would silently clip to max_len - 1 and
            # corrupt the newest K/V row — refuse instead; the scheduler
            # terminates at context-full before ever reaching this
            raise ValueError(
                f"slot(s) {np.flatnonzero(full).tolist()} are at "
                f"max_len={self.max_len}; evict or raise max_len before "
                f"decoding further")
        fn = self._decode_aot or self._decode
        lt = jnp.asarray(np.asarray(last_tokens, np.int32))
        act = jnp.asarray(act_np)
        next_tokens, logits, self.cache, self.rng = fn(
            self.cache, lt, act, self.rng)
        next_np = np.asarray(next_tokens)
        self.last_tokens = np.where(act_np, next_np, self.last_tokens)
        self._host_lengths = self._host_lengths + act_np
        return next_np, logits

    def evict(self, slots) -> None:
        """Free the given slot indices (mask-shaped op, compiled once)."""
        mask = np.zeros((self.config.num_slots,), bool)
        mask[np.asarray(list(slots), np.int64)] = True
        self.cache = kv_cache.evict_slots(self.cache, jnp.asarray(mask))
        self._host_lengths = np.where(mask, 0, self._host_lengths)

    @property
    def lengths(self) -> np.ndarray:
        return np.asarray(self.cache.lengths)

    @property
    def kv_cache_bytes(self) -> int:
        """Resident bytes of the static KV cache — the number the paged
        pool (ROADMAP item 2) must beat; stamped into the serving AOT
        ``hbm_snapshot`` so captures carry it."""
        return int(self.cache.k.nbytes) + int(self.cache.v.nbytes)


def init_gpt2_params(cfg: GPT2Config, seed: int = 0):
    """Random GPT-2 params for smoke/bench serving (real deployments load
    a checkpoint). Init runs the training forward once at a short length.
    """
    from apex_tpu.models.gpt2 import GPT2

    model = GPT2(cfg)
    dummy = jnp.zeros((1, min(8, cfg.n_positions)), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), dummy)
