"""Speculative decoding: the host-side drafter and the ``DecodePolicy`` seam.

Self-speculation needs no second model: the :class:`NGramDrafter` is a
stdlib-only prompt-lookup drafter that proposes the next ``k`` tokens by
matching the request's trailing n-gram against its own committed history
(prompt + generated) and against a small corpus of recently observed
prompts — the serving analog of prompt-lookup decoding. Drafts are cheap
host guesses; correctness lives entirely in the engine's compiled verify
step (``Engine.spec_decode_step``), which scores ``draft_len + 1``
positions with the SAME single-token forward prefill/decode share.
Acceptance is exact: a draft token is committed iff it equals the token
the target policy itself produces at that position, so a greedy
speculative stream is bit-identical to the one-token engine and a
worthless drafter degrades throughput to the one-token path, never
correctness (docs/serving.md "Speculative decoding and the decode-policy
zoo").

The :class:`DecodePolicy` seam names the sampling behavior per request:
``greedy`` / ``top_p[=P]`` / ``min_p[=M]`` / ``spec(POLICY)``. With
``EngineConfig(decode_policy=...)`` armed, per-slot temperature/top_p/
min_p ride the compiled calls as DATA (``[num_slots]`` f32 arrays), so
mixing policies in one batch never retraces — the one-compile invariant
is indifferent to who wants nucleus sampling. :func:`sample_with_policy`
is the branchless in-graph sampler: greedy rows are an exact
``argmax`` selected by ``where(temperature <= 0)``, and at the default
knobs (``top_p=1``, ``min_p=0``) the filter keeps every token, reducing
to plain temperature sampling.

Beam-like policies (``beam`` / ``beam_search`` / ``best_of``) are
refused at parse time: they score whole sequences, so there is no
per-token acceptance test the verify step could run — with speculation
armed the refusal says so explicitly ("cannot be verified"), and both
CLIs surface either refusal as exit 2 before any compile.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, List, Optional, Sequence

__all__ = ["DecodePolicy", "parse_policy", "NGramDrafter",
           "sample_with_policy", "KNOWN_UNVERIFIABLE"]

# beam-like policies keep a frontier of candidate SEQUENCES; acceptance
# in the verify step is per-token, so there is nothing exact to verify a
# draft against — refused at parse time, never half-supported
KNOWN_UNVERIFIABLE = ("beam", "beam_search", "best_of")


@dataclasses.dataclass(frozen=True)
class DecodePolicy:
    """One request's sampling contract, as data.

    ``temperature <= 0`` is exact greedy argmax (the oracle policy);
    ``top_p`` keeps the smallest nucleus whose mass reaches P (rank 0 is
    always kept); ``min_p`` keeps tokens with ``prob >= min_p * max
    prob``. ``spec`` marks the ``spec(...)`` spelling — sugar that
    demands the engine be built with ``spec_draft_len >= 1``.
    """

    kind: str
    temperature: float = 1.0
    top_p: float = 1.0
    min_p: float = 0.0
    spec: bool = False


def _parse_value(kind: str, text: str, default: float) -> float:
    if text == "":
        return default
    try:
        return float(text)
    except ValueError:
        raise ValueError(
            f"decode policy {kind!r}: bad parameter value {text!r}")


def parse_policy(name: str, *, spec_draft_len: int = 0) -> DecodePolicy:
    """Parse a ``--decode-policy`` spelling into a :class:`DecodePolicy`.

    Grammar: ``greedy`` | ``top_p[=P]`` | ``min_p[=M]`` |
    ``spec(POLICY)``, with an optional ``,t=T`` temperature suffix on the
    sampled policies. Raises ``ValueError`` (CLIs map it to exit 2,
    before params or compile) for unknown names, out-of-range knobs,
    beam-like policies, and ``spec(...)`` without a draft length.
    """
    text = (name or "").strip()
    if text.startswith("spec(") and text.endswith(")"):
        inner = parse_policy(text[len("spec("):-1],
                             spec_draft_len=spec_draft_len)
        if inner.spec:
            raise ValueError(
                f"unknown decode policy {name!r}: spec(...) does not nest")
        if spec_draft_len < 1:
            raise ValueError(
                "decode policy 'spec(...)' needs speculation armed: set "
                "spec_draft_len >= 1 (--spec-draft-len)")
        return dataclasses.replace(inner, spec=True)
    base, _, tsuffix = text.partition(",")
    base = base.strip()
    kind, _, value = base.partition("=")
    kind = kind.strip()
    if kind in KNOWN_UNVERIFIABLE:
        if spec_draft_len >= 1:
            raise ValueError(
                f"decode policy {kind!r} cannot be verified by the "
                f"speculative acceptance oracle: beam-like policies "
                f"score whole sequences, verification accepts per token")
        raise ValueError(f"decode policy {kind!r} is not supported")
    temperature = None
    if tsuffix:
        tkey, _, tval = tsuffix.strip().partition("=")
        if tkey.strip() not in ("t", "temperature") or not tval:
            raise ValueError(
                f"unknown decode policy {name!r}: expected an optional "
                f"',t=T' temperature suffix")
        temperature = _parse_value(kind, tval.strip(), 1.0)
        if temperature < 0:
            raise ValueError(
                f"decode policy {kind!r}: temperature {temperature} "
                f"must be >= 0")
    if kind == "greedy":
        if value or temperature is not None:
            raise ValueError(
                "decode policy 'greedy' takes no parameters (it IS "
                "temperature 0)")
        return DecodePolicy("greedy", temperature=0.0)
    if kind == "top_p":
        p = _parse_value(kind, value.strip(), 0.9)
        if not 0.0 < p <= 1.0:
            raise ValueError(
                f"decode policy 'top_p': p={p} must be in (0, 1]")
        return DecodePolicy("top_p", top_p=p,
                            temperature=1.0 if temperature is None
                            else temperature)
    if kind == "min_p":
        m = _parse_value(kind, value.strip(), 0.05)
        if not 0.0 <= m < 1.0:
            raise ValueError(
                f"decode policy 'min_p': m={m} must be in [0, 1)")
        return DecodePolicy("min_p", min_p=m,
                            temperature=1.0 if temperature is None
                            else temperature)
    raise ValueError(
        f"unknown decode policy {name!r}: expected greedy | top_p[=P] | "
        f"min_p[=M] | spec(POLICY)")


class NGramDrafter:
    """Prompt-lookup drafter: stdlib-only, deterministic, never on-device.

    ``draft(history, k)`` proposes up to ``k`` next tokens by finding the
    most recent earlier occurrence of the history's trailing n-gram
    (``n = max_n .. 1``) and copying its continuation; each proposed
    token is appended to the working history so a single match can
    extend a whole draft. Two fallbacks keep proposals total: a corpus
    of recently :meth:`observe`-d prompt streams (cross-request prompt
    lookup — the host-side complement of the paged prefix index, which
    shares K/V pages but stores no token ids), then repeat-last-token —
    which exactly predicts the period-1 cycles greedy decode of small
    models falls into, so even the smoke bench sees real acceptance.

    A drafter is pure throughput: the verify step's exact acceptance
    means a wrong guess costs one discarded cache row (rolled back by
    length truncation), never a wrong token.
    """

    def __init__(self, max_n: int = 3, corpus_size: int = 32):
        if max_n < 1:
            raise ValueError(f"max_n={max_n} must be >= 1")
        self.max_n = int(max_n)
        self._corpus: Deque[List[int]] = deque(maxlen=int(corpus_size))

    def observe(self, tokens: Sequence[int]) -> None:
        """Feed a committed token stream (e.g. an admitted prompt) into
        the cross-request lookup corpus."""
        toks = [int(t) for t in tokens]
        if toks:
            self._corpus.append(toks)

    @staticmethod
    def _continuation(seq: List[int], pat: List[int],
                      before: int) -> Optional[int]:
        """The token following the most recent occurrence of ``pat``
        ending strictly before index ``before`` in ``seq``."""
        n = len(pat)
        for i in range(min(before, len(seq)) - n, -1, -1):
            if seq[i:i + n] == pat:
                return seq[i + n] if i + n < len(seq) else None
        return None

    def _propose(self, hist: List[int]) -> int:
        for n in range(min(self.max_n, len(hist) - 1), 0, -1):
            pat = hist[-n:]
            nxt = self._continuation(hist, pat, len(hist) - 1)
            if nxt is not None:
                return nxt
        for n in range(min(self.max_n, len(hist)), 0, -1):
            pat = hist[-n:]
            for seq in reversed(self._corpus):
                nxt = self._continuation(seq, pat, len(seq))
                if nxt is not None:
                    return nxt
        return hist[-1] if hist else 0

    def draft(self, history: Sequence[int], k: int) -> List[int]:
        """Up to ``k`` proposed next tokens for ``history`` (prompt +
        committed generations). Always returns exactly ``k`` tokens for
        ``k >= 0`` and a non-empty history (the fallbacks are total)."""
        hist = [int(t) for t in history]
        out: List[int] = []
        for _ in range(max(int(k), 0)):
            nxt = self._propose(hist)
            out.append(nxt)
            hist.append(nxt)
        return out


def sample_with_policy(logits, rng, pol, *, top_k: int = 0):
    """Branchless per-slot policy sampler (in-graph; policy knobs are
    DATA). ``logits`` ``[B, V]``; ``pol`` a dict of ``[B]`` f32 arrays
    ``temps`` / ``top_ps`` / ``min_ps``; ``top_k`` is the engine's
    static config knob and applies on top. Greedy rows
    (``temps <= 0``) return the exact fp32 argmax — bit-identical to
    the legacy sampler — selected by ``where``, so one trace serves
    every mixture of policies in the batch.
    """
    import jax
    import jax.numpy as jnp

    temps = pol["temps"]
    scaled = logits.astype(jnp.float32) / jnp.maximum(
        temps[:, None], jnp.float32(1e-6))
    if 0 < top_k < logits.shape[-1]:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, jnp.float32(-1e30), scaled)
    probs = jax.nn.softmax(scaled, axis=-1)
    # min_p: keep tokens at least min_p * the modal probability
    keep = probs >= pol["min_ps"][:, None] * probs.max(-1, keepdims=True)
    # top_p nucleus: sort descending, keep while the EXCLUSIVE prefix
    # mass is still below p (rank 0 always survives: its exclusive
    # cumsum is 0 < p)
    order = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, order, axis=-1)
    below = (jnp.cumsum(sorted_p, axis=-1) - sorted_p) \
        < pol["top_ps"][:, None]
    rows = jnp.arange(logits.shape[0])
    keep &= jnp.zeros(probs.shape, bool).at[rows[:, None], order].set(below)
    # the argmax row is unconditionally kept: an fp edge (all mass in
    # masked tokens) must never leave an empty support
    amax = jnp.argmax(logits, axis=-1)
    keep = keep.at[rows, amax].set(True)
    masked = jnp.where(keep, scaled, jnp.float32(-1e30))
    sampled = jax.random.categorical(rng, masked, axis=-1)
    return jnp.where(temps <= 0.0, amax, sampled).astype(jnp.int32)
