"""Static-shape, slot-addressed KV cache.

One buffer pair per layer, all layers stacked on a leading axis:
``k``/``v`` are ``[n_layer, num_slots, max_len, heads, head_dim]`` and
``lengths`` is ``[num_slots]`` — the number of tokens resident per slot.
The arrays never change shape for the lifetime of the engine; request
admission, completion, and eviction only move *values* (a length reset, a
masked token write), so the jitted decode step that closes over this
pytree compiles exactly once.

All mutators are pure functions returning a new :class:`KVCache` (the
engine's jitted callables donate nothing and alias nothing). Masked writes
read-modify-write the existing token so an inactive slot's bytes are
untouched — slot isolation is structural, not best-effort.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class KVCache:
    """Pytree of the serving cache; see module docstring for shapes."""

    k: jax.Array        # [n_layer, num_slots, max_len, heads, head_dim]
    v: jax.Array        # same shape as k
    lengths: jax.Array  # [num_slots] int32 — tokens resident per slot

    @property
    def n_layer(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(n_layer: int, num_slots: int, max_len: int, heads: int,
               head_dim: int, dtype: Any = jnp.float32) -> KVCache:
    """Allocate an empty cache. ``max_len`` bounds every request's total
    context (prompt + generated); the scheduler terminates a request that
    reaches it."""
    shape = (n_layer, num_slots, max_len, heads, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((num_slots,), jnp.int32))


def write_token(cache: KVCache, layer: int, k_tok: jax.Array,
                v_tok: jax.Array, positions: jax.Array,
                mask: jax.Array) -> KVCache:
    """Write one token's K/V per slot at ``positions[slot]`` where
    ``mask[slot]`` — the append primitive of both prefill and decode.

    ``k_tok``/``v_tok``: ``[num_slots, heads, head_dim]``; ``positions``:
    ``[num_slots]`` int32; ``mask``: ``[num_slots]`` bool. ``layer`` is a
    python int (the model unrolls its layers), so the layer slice is
    static. Masked-off slots get their current token written back
    bit-for-bit; shapes never change, so this is recompile-free under jit.
    """
    def _one(buf, tok, pos):       # buf [L, h, d], tok [h, d]
        return jax.lax.dynamic_update_slice(buf, tok[None], (pos, 0, 0))

    def _read(buf, pos):
        return jax.lax.dynamic_slice(
            buf, (pos, 0, 0), (1,) + buf.shape[1:])[0]

    pos = jnp.clip(positions.astype(jnp.int32), 0, cache.max_len - 1)
    out = {}
    for name, tok in (("k", k_tok), ("v", v_tok)):
        buf = getattr(cache, name)[layer]              # [B, L, h, d]
        cur = jax.vmap(_read)(buf, pos)                # [B, h, d]
        new = jnp.where(mask[:, None, None], tok.astype(buf.dtype), cur)
        out[name] = getattr(cache, name).at[layer].set(
            jax.vmap(_one)(buf, new, pos))
    return cache.replace(k=out["k"], v=out["v"])


def advance(cache: KVCache, mask: jax.Array) -> KVCache:
    """Bump ``lengths`` by one for masked slots (after a decode append)."""
    return cache.replace(
        lengths=cache.lengths + mask.astype(jnp.int32))


def reset_slots(cache: KVCache, mask: jax.Array) -> KVCache:
    """Zero masked slots' lengths — insertion prologue: the slot's stale
    bytes stay in place and are unreachable behind ``lengths``."""
    return cache.replace(
        lengths=jnp.where(mask, 0, cache.lengths).astype(jnp.int32))


def set_lengths(cache: KVCache, mask: jax.Array,
                new_lengths: jax.Array) -> KVCache:
    """Set masked slots' lengths (prefill epilogue: prompt lengths)."""
    return cache.replace(
        lengths=jnp.where(mask, new_lengths,
                          cache.lengths).astype(jnp.int32))


# host-callable eviction: ONE jitted (mask-shaped) op, compiled once per
# engine — freeing a slot between decode steps cannot recompile anything
@jax.jit
def evict_slots(cache: KVCache, mask: jax.Array) -> KVCache:
    """Free masked slots. Data is left in place; only ``lengths`` moves —
    the attention mask (``key_pos <= position``) makes the stale rows
    unreachable, and the next insert overwrites them."""
    return reset_slots(cache, mask)
