"""Static-shape KV caches: slot-addressed, and the paged block pool.

Two layouts, one contract — every array shape is fixed at engine build
and request admission/completion/eviction only move *values*, so the
jitted decode step that closes over either pytree compiles exactly once:

- :class:`KVCache` — per-slot reservation: ``k``/``v`` are
  ``[n_layer, num_slots, max_len, heads, head_dim]`` plus per-slot
  ``lengths``. Simple, but every slot pays ``max_len`` tokens of HBM
  whatever its request actually uses.
- :class:`PagedKVCache` — a shared block pool: ``k``/``v`` are
  ``[n_layer, num_pages, page_size, heads, head_dim]`` plus a per-slot
  page table ``[num_slots, max_pages_per_slot]`` of pool indices and the
  same ``lengths``. A slot's virtual key axis is its page-table row laid
  end to end; position ``p`` lives at ``(page_table[slot, p // page_size],
  p % page_size)``. Page indices are DATA (host-allocated in
  :mod:`apex_tpu.serve.paging`, threaded through the compiled call),
  never shapes — so paging multiplies resident requests per HBM byte
  without touching the one-compile invariant. Page 0 is the reserved
  null page: masked-off writes are routed there and unmapped table
  entries read its zeros (discarded by the attention reachability mask).

All mutators are pure functions returning a new cache (the engine's
jitted callables donate nothing and alias nothing). Masked writes
read-modify-write the existing token so an inactive slot's bytes are
untouched — slot isolation is structural, not best-effort.

Block-scale quantization (``EngineConfig(kv_quant=...)``) changes the
VALUES, never the structure of this contract: ``k``/``v`` hold codec
bytes (int8 / float8_e4m3fn) and two extra pytree fields
``k_scale``/``v_scale`` hold one fp32 scale per (token, head) — shaped
like the payload minus the head_dim axis, so scales ride every page
behaviour (prefix sharing, COW, eviction, export/import, tp head
sharding) through the exact same code paths as the payload. On an
unquantized cache both fields are ``None`` — an empty pytree node, so
legacy pytrees are structurally identical to before the feature
existed.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class KVCache:
    """Pytree of the serving cache; see module docstring for shapes."""

    k: jax.Array        # [n_layer, num_slots, max_len, heads, head_dim]
    v: jax.Array        # same shape as k
    lengths: jax.Array  # [num_slots] int32 — tokens resident per slot
    # per-(token, head) fp32 codec scales when kv_quant is armed:
    # [n_layer, num_slots, max_len, heads]; None when unquantized
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def n_layer(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(n_layer: int, num_slots: int, max_len: int, heads: int,
               head_dim: int, dtype: Any = jnp.float32,
               kv_quant: Optional[str] = None) -> KVCache:
    """Allocate an empty cache. ``max_len`` bounds every request's total
    context (prompt + generated); the scheduler terminates a request that
    reaches it. With ``kv_quant`` the payload arrays take the codec's
    storage dtype and the fp32 scale planes are allocated alongside."""
    shape = (n_layer, num_slots, max_len, heads, head_dim)
    lengths = jnp.zeros((num_slots,), jnp.int32)
    if kv_quant is None:
        return KVCache(k=jnp.zeros(shape, dtype),
                       v=jnp.zeros(shape, dtype), lengths=lengths)
    from apex_tpu.quant.kv import kv_storage_dtype

    sdtype = kv_storage_dtype(kv_quant)
    return KVCache(
        k=jnp.zeros(shape, sdtype), v=jnp.zeros(shape, sdtype),
        lengths=lengths,
        k_scale=jnp.zeros(shape[:-1], jnp.float32),
        v_scale=jnp.zeros(shape[:-1], jnp.float32))


def write_token(cache: KVCache, layer: int, k_tok: jax.Array,
                v_tok: jax.Array, positions: jax.Array,
                mask: jax.Array, codec: Optional[str] = None) -> KVCache:
    """Write one token's K/V per slot at ``positions[slot]`` where
    ``mask[slot]`` — the append primitive of both prefill and decode.

    ``k_tok``/``v_tok``: ``[num_slots, heads, head_dim]``; ``positions``:
    ``[num_slots]`` int32; ``mask``: ``[num_slots]`` bool. ``layer`` is a
    python int (the model unrolls its layers), so the layer slice is
    static. Masked-off slots get their current token written back
    bit-for-bit; shapes never change, so this is recompile-free under jit.

    With ``codec`` the token is block-scale encoded (one scale per head)
    and codes + scales land in the same masked read-modify-write — the
    scale write obeys the identical slot-isolation contract as the
    payload write.
    """
    def _one(buf, tok, pos):       # buf [L, ...], tok [...]
        return jax.lax.dynamic_update_slice(
            buf, tok[None], (pos,) + (0,) * tok.ndim)

    def _read(buf, pos):
        return jax.lax.dynamic_slice(
            buf, (pos,) + (0,) * (buf.ndim - 1), (1,) + buf.shape[1:])[0]

    pos = jnp.clip(positions.astype(jnp.int32), 0, cache.max_len - 1)
    out = {}
    for name, tok in (("k", k_tok), ("v", v_tok)):
        scales = None
        if codec is not None:
            from apex_tpu.quant.kv import encode_kv

            tok, scales = encode_kv(codec, tok.astype(jnp.float32))
        buf = getattr(cache, name)[layer]              # [B, L, h, d]
        cur = jax.vmap(_read)(buf, pos)                # [B, h, d]
        new = jnp.where(mask[:, None, None], tok.astype(buf.dtype), cur)
        out[name] = getattr(cache, name).at[layer].set(
            jax.vmap(_one)(buf, new, pos))
        if scales is not None:
            sname = name + "_scale"
            sbuf = getattr(cache, sname)[layer]        # [B, L, h]
            scur = jax.vmap(_read)(sbuf, pos)          # [B, h]
            snew = jnp.where(mask[:, None], scales.astype(sbuf.dtype),
                             scur)
            out[sname] = getattr(cache, sname).at[layer].set(
                jax.vmap(_one)(sbuf, snew, pos))
    return cache.replace(**out)


def advance(cache: KVCache, mask: jax.Array) -> KVCache:
    """Bump ``lengths`` by one for masked slots (after a decode append)."""
    return cache.replace(
        lengths=cache.lengths + mask.astype(jnp.int32))


def reset_slots(cache: KVCache, mask: jax.Array) -> KVCache:
    """Zero masked slots' lengths — insertion prologue: the slot's stale
    bytes stay in place and are unreachable behind ``lengths``."""
    return cache.replace(
        lengths=jnp.where(mask, 0, cache.lengths).astype(jnp.int32))


def set_lengths(cache: KVCache, mask: jax.Array,
                new_lengths: jax.Array) -> KVCache:
    """Set masked slots' lengths (prefill epilogue: prompt lengths)."""
    return cache.replace(
        lengths=jnp.where(mask, new_lengths,
                          cache.lengths).astype(jnp.int32))


# host-callable eviction: ONE jitted (mask-shaped) op, compiled once per
# engine (once per cache pytree structure — slot and paged engines each
# hold their own entry) — freeing a slot between decode steps cannot
# recompile anything
@jax.jit
def evict_slots(cache, mask: jax.Array):
    """Free masked slots. Data is left in place; only ``lengths`` moves —
    the attention mask (``key_pos <= position``) makes the stale rows
    unreachable, and the next insert overwrites them. Works on either
    cache layout (it only touches ``lengths``; a paged slot's page
    *indices* are host bookkeeping, freed by the allocator)."""
    return reset_slots(cache, mask)


# ------------------------------------------------------- paged block pool


@flax.struct.dataclass
class PagedKVCache:
    """Pytree of the paged serving cache; see module docstring."""

    k: jax.Array           # [n_layer, num_pages, page_size, heads, head_dim]
    v: jax.Array           # same shape as k
    lengths: jax.Array     # [num_slots] int32 — tokens resident per slot
    page_table: jax.Array  # [num_slots, max_pages_per_slot] int32
    # per-(token, head) fp32 codec scales when kv_quant is armed:
    # [n_layer, num_pages, page_size, heads] — scales live IN the page
    # structure, so sharing/COW/eviction/migration move them with the
    # page for free; None when unquantized
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def n_layer(self) -> int:
        return self.k.shape[0]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def max_pages_per_slot(self) -> int:
        return self.page_table.shape[1]

    @property
    def max_len(self) -> int:
        """Per-slot virtual context bound (the page-table row laid flat)."""
        return self.page_size * self.max_pages_per_slot


def init_paged_cache(n_layer: int, num_slots: int, max_len: int,
                     page_size: int, num_pages: int, heads: int,
                     head_dim: int, dtype: Any = jnp.float32,
                     kv_quant: Optional[str] = None) -> PagedKVCache:
    """Allocate an empty page pool. ``max_len`` (must be a multiple of
    ``page_size``) bounds every request's total context; ``num_pages``
    bounds the *pool* — sizing it below ``num_slots * max_len /
    page_size`` (+1 for the null page) is the point: mixed-length
    traffic shares the pool instead of each slot reserving ``max_len``.
    """
    if max_len % page_size:
        raise ValueError(
            f"page_size={page_size} must divide max_len={max_len} (a "
            f"slot's virtual key axis is whole pages laid end to end)")
    max_pages = max_len // page_size
    if num_pages < max_pages + 1:
        raise ValueError(
            f"num_pages={num_pages} cannot hold even one full-context "
            f"request: need max_len/page_size + 1 null page = "
            f"{max_pages + 1}")
    shape = (n_layer, num_pages, page_size, heads, head_dim)
    lengths = jnp.zeros((num_slots,), jnp.int32)
    table = jnp.zeros((num_slots, max_pages), jnp.int32)
    if kv_quant is None:
        return PagedKVCache(k=jnp.zeros(shape, dtype),
                            v=jnp.zeros(shape, dtype),
                            lengths=lengths, page_table=table)
    from apex_tpu.quant.kv import kv_storage_dtype

    sdtype = kv_storage_dtype(kv_quant)
    return PagedKVCache(
        k=jnp.zeros(shape, sdtype), v=jnp.zeros(shape, sdtype),
        lengths=lengths, page_table=table,
        k_scale=jnp.zeros(shape[:-1], jnp.float32),
        v_scale=jnp.zeros(shape[:-1], jnp.float32))


def paged_write_token(cache: PagedKVCache, layer: int, k_tok: jax.Array,
                      v_tok: jax.Array, positions: jax.Array,
                      mask: jax.Array,
                      codec: Optional[str] = None) -> PagedKVCache:
    """The paged analog of :func:`write_token`: append one token's K/V
    per slot at virtual position ``positions[slot]`` — physical page
    ``page_table[slot, pos // page_size]``, row ``pos % page_size`` —
    where ``mask[slot]``.

    Masked-off slots are routed to the null page (page 0) and write back
    its current row bit-for-bit: a stale page-table entry on an inactive
    slot can therefore never collide with a live slot's append inside
    the same scatter. Live slots' target pages are uniquely owned by
    construction (the host allocator never maps one writable page into
    two tables), so the scatter indices of real writes never alias.
    """
    ps = cache.page_size
    pos = jnp.clip(positions.astype(jnp.int32), 0, cache.max_len - 1)
    rows = jnp.arange(cache.num_slots)
    pages = cache.page_table[rows, pos // ps]          # [B]
    pages = jnp.where(mask, pages, 0)
    offs = jnp.where(mask, pos % ps, 0)
    out = {}
    for name, tok in (("k", k_tok), ("v", v_tok)):
        scales = None
        if codec is not None:
            from apex_tpu.quant.kv import encode_kv

            tok, scales = encode_kv(codec, tok.astype(jnp.float32))
        buf = getattr(cache, name)                     # [L, P, S, h, d]
        cur = buf[layer, pages, offs]                  # [B, h, d]
        new = jnp.where(mask[:, None, None], tok.astype(buf.dtype), cur)
        out[name] = buf.at[layer, pages, offs].set(new)
        if scales is not None:
            sname = name + "_scale"
            sbuf = getattr(cache, sname)               # [L, P, S, h]
            scur = sbuf[layer, pages, offs]            # [B, h]
            snew = jnp.where(mask[:, None], scales.astype(sbuf.dtype),
                             scur)
            out[sname] = sbuf.at[layer, pages, offs].set(snew)
    return cache.replace(**out)


# ------------------------------------------------- tensor-parallel layout
#
# Both cache layouts shard the SAME axis under tensor parallelism: axis 3
# is `heads` in `[n_layer, num_slots, max_len, heads, head_dim]` and in
# `[n_layer, num_pages, page_size, heads, head_dim]` alike. Everything
# host-indexed — `lengths`, the page table, page/slot indices — stays
# replicated data, which is why the allocator, prefix index, scheduler,
# and journal are mesh-agnostic: a page index addresses every rank's
# shard of that page simultaneously.


def tp_cache_specs(cache, axis: str = "tp"):
    """``PartitionSpec`` pytree for a TP-sharded cache: ``k``/``v`` on
    the head axis, ``lengths`` (and the page table) replicated. Shaped
    like the cache pytree itself, so it serves as ``shard_map``
    in/out_specs and as the ``device_put`` placement recipe."""
    from jax.sharding import PartitionSpec as P

    kv = P(None, None, None, axis, None)
    # scale planes end on the head axis — scales shard with their pages
    # on the tp head axis by construction, not by a separate code path
    sc = None if cache.k_scale is None else P(None, None, None, axis)
    if hasattr(cache, "page_table"):
        return PagedKVCache(k=kv, v=kv, lengths=P(), page_table=P(),
                            k_scale=sc, v_scale=sc)
    return KVCache(k=kv, v=kv, lengths=P(), k_scale=sc, v_scale=sc)


def shard_cache(cache, mesh, axis: str = "tp"):
    """Place a freshly-initialized cache onto the serving mesh per
    :func:`tp_cache_specs` (head-sharded K/V pools, replicated
    bookkeeping). Heads must divide over the mesh axis."""
    import jax
    from jax.sharding import NamedSharding

    heads = cache.k.shape[3]
    tp = int(mesh.shape[axis])
    if heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_head={heads}: the serving mesh "
            f"shards whole heads (pick a tp that divides the head "
            f"count)")
    # ONE spelling of the layout: the placement derives from the same
    # spec tree shard_map consumes, so the two can never drift
    specs = tp_cache_specs(cache, axis)

    def put(field):
        return jax.device_put(getattr(cache, field),
                              NamedSharding(mesh, getattr(specs, field)))

    out = cache.replace(k=put("k"), v=put("v"), lengths=put("lengths"))
    if hasattr(cache, "page_table"):
        out = out.replace(page_table=put("page_table"))
    if cache.k_scale is not None:
        out = out.replace(k_scale=put("k_scale"), v_scale=put("v_scale"))
    return out


# host-callable copy-on-write: ONE jitted op (page indices are traced
# scalars), compiled once per engine — sharing a partially-used prefix
# page costs a page copy, never a recompile
@jax.jit
def copy_page(cache: PagedKVCache, src, dst) -> PagedKVCache:
    """Copy page ``src`` onto page ``dst`` across every layer, both K and
    V — the copy-on-write that gives a slot its own writable copy of a
    shared prefix page whose tail it must append into."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = cache.replace(
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]))
    if cache.k_scale is not None:
        out = out.replace(
            k_scale=cache.k_scale.at[:, dst].set(cache.k_scale[:, src]),
            v_scale=cache.v_scale.at[:, dst].set(cache.v_scale[:, src]))
    return out


# host-callable page install: ONE jitted op (the page index is a traced
# scalar, the payload a fixed-shape array), compiled once per engine —
# landing a migrated page from another replica's pool costs one scatter,
# never a recompile. The inverse of reading `cache.k[:, page]` out: the
# disaggregated prefill→decode handoff streams `[n_layer, page_size,
# heads, head_dim]` payloads and this op parks them under a pool index
# the receiving allocator chose.
@jax.jit
def install_page(cache: PagedKVCache, page, k_page: jax.Array,
                 v_page: jax.Array, k_scale_page=None,
                 v_scale_page=None) -> PagedKVCache:
    """Write a whole page's K/V payload into pool slot ``page`` across
    every layer. ``k_page``/``v_page``: ``[n_layer, page_size, heads,
    head_dim]``; on a quantized cache the caller also supplies the
    page's scale planes ``[n_layer, page_size, heads]``. The caller
    owns ``page`` (freshly allocated, refcount held), so the scatter
    can never alias a live slot's append."""
    page = jnp.asarray(page, jnp.int32)
    out = cache.replace(
        k=cache.k.at[:, page].set(k_page.astype(cache.k.dtype)),
        v=cache.v.at[:, page].set(v_page.astype(cache.v.dtype)))
    if k_scale_page is not None:
        out = out.replace(
            k_scale=cache.k_scale.at[:, page].set(
                k_scale_page.astype(cache.k_scale.dtype)),
            v_scale=cache.v_scale.at[:, page].set(
                v_scale_page.astype(cache.v_scale.dtype)))
    return out
